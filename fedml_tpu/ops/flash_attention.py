"""Blockwise (flash) attention as Pallas TPU kernels.

Standard FlashAttention blocking (public algorithm: Dao et al. 2022; online
softmax per Milakov & Gionis) written for the TPU memory hierarchy: Q/K/V
blocks stream HBM→VMEM via the grid's BlockSpecs, scores/probabilities never
materialise in HBM (the S×S matrix XLA would allocate), and every matmul is
MXU-shaped. Forward saves the log-sum-exp rows; backward recomputes P
blockwise and accumulates dQ/dK/dV in two passes (dQ over K blocks; dK/dV
over Q blocks).

The reference has no attention op at all (its NLP models are LSTMs,
rnn.py:5-38); this kernel exists for the framework's long-context leg —
it is the per-shard compute core under sequence-parallel ring attention
(parallel/ring_attention.py) and the transformer LM (models/transformer.py).

Interpret mode (CPU tests) is selected automatically off-TPU.

Measured (v5e through the remote tunnel, bf16, causal, block 512; the
shared chip shows ~2× bimodal throughput windows so only interleaved
A/B differences are trustworthy — see docs/PERF_R3.md §3b):

- FORWARD-only, the kernel is at parity with XLA's attention lowering —
  XLA on TPU already avoids materialising the S×S scores (S=4096:
  ~11 ms both in the round-3 measurement, which used D=128; the training
  rows below use H=8 D=64, so the two sets of absolute numbers are not
  comparable to each other).
- The TRAINING step (fwd+bwd, H=8 D=64) is where the kernel wins:
  reverse-mode AD of plain jnp attention saves the S×S probabilities as
  a residual (H·S²·2 bytes — 2.1 GB at S=8192), while this kernel's
  custom VJP recomputes P blockwise. Interleaved best-of-5, twice
  reproduced: parity at S=4096, ~3× faster at S=8192 (116 vs 341 ms
  wall incl. ~100 ms tunnel RTT), ~1.35× at S=16384 (where XLA
  evidently switches to a rematerialising schedule itself).

Small blocks (≤256) are pathological (revisit overhead); keep ≥512 on
hardware."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _masked_scores(q, k, qi, ki, *, scale, causal, block_q, block_k):
    """Scaled scores for one (Q block, K block) pair with the causal mask —
    the ONE definition shared by forward and both backward kernels (a
    divergence here is the classic silent fwd/bwd gradient mismatch)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [Bq, Bk]
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(rows >= cols, s, _NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _step():
        s = _masked_scores(
            q_ref[0], k_ref[0], qi, ki, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        )
        m_prev = m_ref[:, :1]  # [Bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)  # [Bq, Bk]
        corr = jnp.exp(m_prev - m_new)  # [Bq, 1]
        l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:, :1] = m_new
        pv = jnp.dot(
            p.astype(v_ref.dtype), v_ref[0], preferred_element_type=jnp.float32
        )
        acc_ref[:] = acc_ref[:] * corr + pv

    if causal:
        # a block is live unless every (row, col) pair has col > row
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # lse rides in a sublane-replicated [8, Bq] layout (TPU block
        # shapes need the 2nd-to-last dim divisible by 8)
        lse_row = (m_ref[:, :1] + jnp.log(safe_l))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse_row[None, :], (8, lse_row.shape[0]))


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    BH, S, d = q.shape
    Sk = k.shape[1]
    nq, nk = pl.cdiv(S, block_q), pl.cdiv(Sk, block_k)
    grid = (BH, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, d), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (running max)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l (running sum)
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _compiler_params(interpret):
    """BH and Q-block grid dims are parallel; the K-block dim carries the
    online-softmax accumulator and must run in order."""
    if interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _step():
        k = k_ref[0]
        s = _masked_scores(
            q_ref[0], k, qi, ki, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        )
        p = jnp.exp(s - lse_ref[0, 0][:, None])  # [Bq, Bk]
        dov = jax.lax.dot_general(
            do_ref[0].astype(jnp.float32), v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )  # [Bq, Bk]
        ds = p * (dov - delta_ref[0, 0][:, None]) * scale
        acc_ref[:] += jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_k):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step():
        q = q_ref[0]
        s = _masked_scores(
            q, k_ref[0], qi, ki, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        )
        p = jnp.exp(s - lse_ref[0, 0][:, None])  # [Bq, Bk]
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Bk, d]
        dov = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        ds = p * (dov - delta_ref[0, 0][:, None]) * scale  # [Bq, Bk]
        dk_acc[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )  # [Bk, d]

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(
        q, k, v, 1.0 / math.sqrt(q.shape[-1]), causal, block_q, block_k,
        interpret,
    )
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(
        q, k, v, 1.0 / math.sqrt(q.shape[-1]), causal, block_q, block_k,
        interpret,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    BH, S, d = q.shape
    Sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    # delta in the same sublane-replicated [BH, 8, S] layout as lse
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    delta = jnp.broadcast_to(delta[:, None, :], (BH, 8, S))
    nq, nk = pl.cdiv(S, block_q), pl.cdiv(Sk, block_k)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 8, block_q), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
):
    """Blockwise attention: softmax(Q Kᵀ/√d [, causal]) V.

    q/k/v: [..., S, d] with any leading batch/head dims (flattened
    internally). Sequence lengths must be multiples of the block sizes
    (callers pad; ring attention's shards already are). Differentiable via
    the flash backward kernels."""
    if interpret is None:
        interpret = _use_interpret()
    orig_shape = q.shape
    S, d = q.shape[-2:]
    Sk = k.shape[-2]
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    if S % block_q or Sk % block_k:
        raise ValueError(
            f"sequence lengths ({S}, {Sk}) must be multiples of the block "
            f"sizes ({block_q}, {block_k})"
        )
    if causal and S != Sk:
        raise ValueError("causal attention requires matching Q/K lengths")
    q3 = q.reshape((-1, S, d))
    k3 = k.reshape((-1, Sk, d))
    v3 = v.reshape((-1, Sk, d))
    if q3.shape[0] != k3.shape[0] or k3.shape != v3.shape:
        # the grid is sized from Q's batch*heads; a smaller K/V (e.g. MQA
        # [B, 1, S, d]) would clamp block indices on TPU → silently wrong
        raise ValueError(
            f"q/k/v leading (batch, heads) dims must match: q {q.shape}, "
            f"k {k.shape}, v {v.shape} (broadcast MQA/GQA heads first)"
        )
    out = _flash(q3, k3, v3, causal, block_q, block_k, interpret)
    return out.reshape(orig_shape)


def flash_attention_bthd(q, k, v, causal: bool = True, **kw):
    """[B, T, H, D]-layout adapter matching the framework's attention
    callable convention (parallel/ring_attention.full_attention,
    models/transformer.TransformerBlock.attn_fn): drop-in flash-backed
    ``attn_fn`` for TransformerLM."""
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, T, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention(qt, kt, vt, causal=causal, **kw)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)
