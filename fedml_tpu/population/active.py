"""Bounded per-client bookkeeping — the O(cohort)-per-round structures
behind the scheduler's loss map and the telemetry health registry.

Both consumers share one failure mode at population scale: a dict keyed
by client id that only ever grows. At 10 clients it is invisible; at a
million clients × serve-layer tenants it is the design flaw ROADMAP
item 1 calls out ("a 1M ×-tenants dict of per-client deques cannot be
the design"). The fix is the same shape in both places:

- a **bounded map** with insertion-order eviction for values that are
  only ever read opportunistically (power_of_choice's last-known
  losses: a missing entry means "cold client, rank +inf" — already the
  defined semantics, so eviction degrades to exploration, never error);
- a **bounded LRU active set + compact spill** for records that carry
  exact counters (health participation/fault tallies): the full-
  fidelity record (timing window, dedupe memory) lives only for the
  most recently seen clients, and eviction folds the exact counters
  into a ~3-slot aggregate that is restored seamlessly if the client
  reappears — totals stay exact, memory per client drops from KBs to
  ~100 bytes, and per-round work never scans beyond the active set.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple


class BoundedLossMap:
    """Insertion-ordered dict bounded at ``capacity`` entries: setting a
    key refreshes its position; past capacity the STALEST entry (least
    recently written) is dropped. Exactly the dict surface the selection
    policies read (``get``/``items``/iteration/len/contains), so it
    drops in for the scheduler's ``ctx.losses``."""

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError("BoundedLossMap capacity must be >= 1")
        self.capacity = int(capacity)
        self._d: Dict[int, float] = {}

    def __setitem__(self, key: int, value: float) -> None:
        k = int(key)
        if k in self._d:
            del self._d[k]  # re-insert at the fresh end
        self._d[k] = float(value)
        while len(self._d) > self.capacity:
            self._d.pop(next(iter(self._d)))

    def get(self, key: int, default=None):
        return self._d.get(int(key), default)

    def __contains__(self, key: int) -> bool:
        return int(key) in self._d

    def __len__(self) -> int:
        return len(self._d)

    def __iter__(self) -> Iterator[int]:
        return iter(self._d)

    def __bool__(self) -> bool:
        return bool(self._d)

    def items(self):
        return self._d.items()

    def keys(self):
        return self._d.keys()

    def values(self):
        return self._d.values()

    def clear(self) -> None:
        self._d.clear()


class SpilledRecord:
    """Compact aggregate of an evicted full-fidelity client record —
    exactly the counters that must stay EXACT across eviction (sliding-
    window timing stats are definitionally lossy and are dropped)."""

    __slots__ = ("last_seen_round", "rounds_participated", "faults")

    def __init__(
        self,
        last_seen_round: int = -1,
        rounds_participated: int = 0,
        faults: Optional[Dict[str, int]] = None,
    ):
        self.last_seen_round = int(last_seen_round)
        self.rounds_participated = int(rounds_participated)
        self.faults = dict(faults) if faults else {}


class ActiveSet:
    """LRU-bounded map of full-fidelity records with compact spill.

    ``touch(cid, factory)`` returns the live record, creating it (seeded
    from any spilled aggregate via ``factory(spilled_or_None)``) and
    evicting the least-recently-touched record past ``capacity``;
    eviction calls ``spill_fn(record) -> SpilledRecord`` and files the
    aggregate. Iteration/len cover the ACTIVE set only — per-round scans
    (straggler quantiles) are bounded by construction; full-history
    queries merge :attr:`spilled` explicitly."""

    def __init__(self, capacity: int, spill_fn):
        if capacity < 1:
            raise ValueError("ActiveSet capacity must be >= 1")
        self.capacity = int(capacity)
        self._spill_fn = spill_fn
        self._live: Dict[int, object] = {}
        self.spilled: Dict[int, SpilledRecord] = {}

    def get(self, cid: int):
        """Live record or None — does NOT refresh recency."""
        return self._live.get(int(cid))

    def touch(self, cid: int, factory):
        cid = int(cid)
        rec = self._live.get(cid)
        if rec is not None:
            del self._live[cid]  # refresh: re-insert at the fresh end
            self._live[cid] = rec
            return rec
        rec = factory(self.spilled.pop(cid, None))
        self._live[cid] = rec
        while len(self._live) > self.capacity:
            old_cid = next(iter(self._live))
            old = self._live.pop(old_cid)
            self.spilled[old_cid] = self._spill_fn(old)
        return rec

    def __len__(self) -> int:
        return len(self._live)

    def __contains__(self, cid: int) -> bool:
        return int(cid) in self._live

    def items(self) -> Iterator[Tuple[int, object]]:
        return iter(list(self._live.items()))

    def known_ids(self):
        """Every client id with live OR spilled history (query-time
        only — O(participants), never on the round path)."""
        return set(self._live) | set(self.spilled)
