"""O(cohort) client sampling — the alias-table/rejection machinery that
lets `weighted` and `power_of_choice` selection never touch all N
clients per round.

The legacy draws in scheduler/policies.py are exact numpy draws over the
full population: `rng.choice(n, k, replace=False, p=p)` renormalizes an
N-vector per round (O(N) work and O(N) temporaries), which holds the
100k-client rows at ~6.5 r/s and cannot reach the north-star 1M–10M
population (ROADMAP item 1). This module replaces the per-round O(N)
with:

- **build time, once per run**: a Walker/Vose alias table over the
  per-client inclusion probabilities — two packed float/int arrays,
  O(N) to construct, O(1) per draw;
- **round time**: k distinct clients via draw-and-discard-duplicates.
  Discarding duplicates from a with-replacement stream is *exactly*
  sequential sampling without replacement (conditioning a categorical
  draw on "not already drawn" renormalizes the remaining mass), so the
  cohort distribution matches the legacy draw's; only the random stream
  differs — which is why the O(cohort) path engages behind a population
  threshold (PopulationConfig.ocohort_threshold) instead of silently
  changing historical small-N cohorts.

Determinism contract (the scheduler's): every draw is a pure function of
the generator handed in — same (seed, round) ⇒ byte-identical cohorts
across processes (pinned by tests/test_population.py, including a
subprocess check).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class AliasSampler:
    """Walker alias table over a fixed weight vector.

    ``sample(rng, m)`` draws m ids i.i.d. from p (with replacement) in
    O(m); :meth:`draw_distinct` builds a k-distinct cohort in O(k)
    expected when k << n. Zero-weight clients are never drawn by the
    table; :meth:`draw_distinct` tolerates k exceeding the non-zero
    support by filling uniformly from the zero-weight ids — the same
    degradation contract as policies._weighted_draw (a zero-sample shard
    under the Dirichlet partitioner must not crash a run mid-flight).
    """

    def __init__(self, weights: np.ndarray):
        w = np.asarray(weights, np.float64).ravel()
        if len(w) == 0 or np.any(w < 0) or not np.all(np.isfinite(w)):
            raise ValueError("alias weights must be finite and >= 0")
        total = w.sum()
        if total <= 0:
            raise ValueError("alias weights sum to zero")
        self.n = len(w)
        self.p = w / total
        self._nonzero = np.flatnonzero(w)
        # Vose construction: scaled probabilities split into under/over
        # stacks; each table cell holds (threshold, alias id). Python
        # loop is O(N) BUILD-time work (once per run) — the point is the
        # per-ROUND cost, which is O(cohort).
        scaled = self.p * self.n
        prob = np.ones(self.n, np.float64)
        alias = np.arange(self.n, dtype=np.int64)
        small = [int(i) for i in np.flatnonzero(scaled < 1.0)]
        large = [int(i) for i in np.flatnonzero(scaled >= 1.0)]
        scaled = scaled.copy()
        while small and large:
            s = small.pop()
            g = large.pop()
            prob[s] = scaled[s]
            alias[s] = g
            scaled[g] = (scaled[g] + scaled[s]) - 1.0
            (small if scaled[g] < 1.0 else large).append(g)
        for i in small + large:  # numerical stragglers land on 1.0
            prob[i] = 1.0
        self._prob = prob
        self._alias = alias

    def sample(self, rng: np.random.Generator, m: int) -> np.ndarray:
        """m i.i.d. draws from p (with replacement), O(m)."""
        i = rng.integers(0, self.n, size=m)
        u = rng.random(m)
        return np.where(u < self._prob[i], i, self._alias[i]).astype(np.int64)

    def draw_distinct(self, rng: np.random.Generator, k: int) -> np.ndarray:
        """k DISTINCT ids, distributionally identical to sequential
        weighted sampling without replacement (draw, discard repeats).
        Order of first appearance is preserved — the draw order, like
        the legacy rng.choice's."""
        k = int(k)
        nnz = len(self._nonzero)
        if k >= nnz:
            # request exceeds the weighted support: every weighted client
            # is taken (permuted) and the remainder fills uniformly from
            # the zero-weight ids — policies._weighted_draw's contract
            take = rng.permutation(self._nonzero)
            if k <= nnz:
                return take[:k].astype(np.int64)
            zeros = np.setdiff1d(
                np.arange(self.n, dtype=np.int64), self._nonzero
            )
            fill = rng.choice(zeros, size=k - nnz, replace=False)
            return np.concatenate([take, fill]).astype(np.int64)
        seen: dict = {}
        # batch the rejection rounds: expected acceptance stays high
        # while k << effective support; the batch size grows if the tail
        # keeps colliding (heavy-head weight vectors)
        batch = max(2 * k, 64)
        while len(seen) < k:
            for c in self.sample(rng, batch):
                ci = int(c)
                if ci not in seen:
                    seen[ci] = None
                    if len(seen) == k:
                        break
            batch = min(2 * batch, 1 << 16)
        return np.fromiter(seen.keys(), np.int64, count=k)


def draw_uniform_distinct(
    rng: np.random.Generator,
    n: int,
    k: int,
    exclude: Optional[np.ndarray] = None,
) -> np.ndarray:
    """k distinct ids uniform over [0, n) minus ``exclude``, by
    rejection — O(k + |exclude|) while k + |exclude| << n, vs the O(N)
    ``np.setdiff1d(arange(n), ...)`` + permutation of the legacy path.
    Falls back to the exact dense draw when the request is a large
    fraction of the population (rejection would thrash)."""
    excl = set(int(i) for i in exclude) if exclude is not None else set()
    avail = n - len(excl)
    k = min(int(k), avail)
    if k <= 0:
        return np.empty(0, np.int64)
    if (k + len(excl)) * 4 >= n:
        eligible = np.setdiff1d(
            np.arange(n, dtype=np.int64),
            np.fromiter(excl, np.int64, count=len(excl)),
        )
        return rng.choice(eligible, size=k, replace=False).astype(np.int64)
    seen: dict = {}
    batch = max(2 * k, 64)
    while len(seen) < k:
        for c in rng.integers(0, n, size=batch):
            ci = int(c)
            if ci not in seen and ci not in excl:
                seen[ci] = None
                if len(seen) == k:
                    break
        batch = min(2 * batch, 1 << 16)
    return np.fromiter(seen.keys(), np.int64, count=k)
