"""fedml_tpu.population — the million-client population runtime.

Everything per-ROUND that used to scale with the total client count N
lives here as an O(cohort) structure, with N touched only at build
time (docs/POPULATION.md):

- :class:`PopulationIndex` — packed per-client partition metadata
  (sample counts, weights, jit-shape classes), split from the
  materialized shards; mmap-backed above a size threshold.
- :class:`AliasSampler` — O(N)-build / O(cohort)-per-round weighted
  cohort draws (`weighted`, `power_of_choice` candidate pools), plus
  :func:`draw_uniform_distinct` rejection sampling for exclusion draws.
- :class:`BoundedLossMap` / :class:`ActiveSet` — the bounded per-client
  bookkeeping behind the scheduler's power_of_choice bias map and the
  telemetry health registry's active set + compact spill.
- ``state_tier.ShardedClientState`` — fixed-stride per-client record
  store for SCAFFOLD/Ditto state (imported from the submodule directly:
  it needs jax, and this package root stays numpy/stdlib-only so the
  scheduler and telemetry can import it before jax initializes).

Activation is config-driven (PopulationConfig, classified KNOWN_BENIGN
in the digest audit): populations at/above ``ocohort_threshold`` engage
the O(cohort) paths; below it every legacy draw and structure runs
unchanged, byte-for-byte.
"""

from fedml_tpu.population.active import ActiveSet, BoundedLossMap, SpilledRecord
from fedml_tpu.population.index import PopulationIndex
from fedml_tpu.population.sampler import AliasSampler, draw_uniform_distinct

__all__ = [
    "ActiveSet",
    "AliasSampler",
    "BoundedLossMap",
    "PopulationIndex",
    "SpilledRecord",
    "draw_uniform_distinct",
]
