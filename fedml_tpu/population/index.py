"""PopulationIndex — packed per-client partition *metadata*, split from
the materialized shards.

Everything per-round machinery needs to know about a client WITHOUT
touching its data fits in a few packed numpy arrays: the sample count
(weighted selection, bucket math), the derived inclusion weight, and the
jit-shape class its singleton bucket lands in (warmup pre-enumeration).
The legacy paths recomputed these from the shard containers — a Python
``len()`` loop over 100k lazy views per scheduler construction, a
per-count ``bucket_steps`` loop in warmup — which is O(N) Python at
every run start and unthinkable at 1M. Here:

- the index is built ONCE (O(N), vectorized numpy) from a dataset's
  counts — or loaded from disk, where it persists as plain ``.npy``
  memmaps so a million-client registry opens in O(1);
- every per-round consumer (alias sampler, shape classes, cohort count
  lookup) reads O(cohort) slices of the packed arrays;
- above ``PopulationConfig.index_mmap_bytes`` (with an ``index_dir``
  set) the packed arrays live mmap-backed on disk rather than in RAM.

The shards themselves stay wherever they were (host lists, the
data/mmap_store.py disk tier, the HBM device store); the index never
aliases them — it is the metadata HALF of the split ROADMAP item 1
names ("splitting partition metadata from materialized shards").
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import numpy as np

from fedml_tpu.data.base import partition_shape_classes
from fedml_tpu.population.sampler import AliasSampler

_COUNTS_FILE = "counts.npy"
_META_FILE = "index_meta.json"


class PopulationIndex:
    """Packed [N] per-client metadata + the derived per-round lookups
    (weights, alias table, shape classes), each computed once and
    cached. Counts may be an in-RAM array or a read-only memmap — every
    consumer goes through O(cohort) fancy-index slices either way."""

    def __init__(self, counts: np.ndarray):
        # asANYarray: a memmap-backed counts vector must stay a memmap
        # (asarray would silently copy it onto the heap — the exact cost
        # the mmap backing exists to avoid)
        self.counts = np.asanyarray(counts)
        if self.counts.dtype != np.int64:
            self.counts = self.counts.astype(np.int64)
        if self.counts.ndim != 1:
            raise ValueError("PopulationIndex counts must be 1-D")
        self._total: Optional[int] = None
        self._weights: Optional[np.ndarray] = None
        self._alias: Optional[AliasSampler] = None
        self._classes: Dict[Tuple[int, int], Dict[tuple, int]] = {}

    # -- construction --
    @classmethod
    def from_dataset(cls, data) -> "PopulationIndex":
        """Build from any FederatedDataset-shaped object. Uses the
        vectorized ``train_sample_counts`` property (O(N) numpy for the
        mmap store's offset diff; one O(N) Python pass for list-backed
        datasets — build-time, once)."""
        return cls(np.asarray(data.train_sample_counts, np.int64))

    @classmethod
    def from_counts(
        cls,
        counts,
        path: Optional[str] = None,
        mmap_threshold_bytes: int = 64 << 20,
    ) -> "PopulationIndex":
        """Build from raw counts; when ``path`` is given and the packed
        array exceeds ``mmap_threshold_bytes``, persist it and reopen
        mmap-backed so the index costs file-cache pages, not heap.

        ``path`` is a PARENT directory that may be shared across
        sessions (PopulationConfig.index_dir is a fixed config string):
        the index lands in a content-digest-keyed subdirectory, written
        once via tmp-dir + atomic rename. Different datasets can never
        clobber each other's mapped files, identical datasets share one
        copy, and a concurrent writer losing the rename race simply
        loads the winner's (bit-identical) index."""
        c = np.asarray(counts, np.int64)
        if path and c.nbytes >= mmap_threshold_bytes:
            import hashlib

            digest = hashlib.sha256(c.tobytes()).hexdigest()[:16]
            sub = os.path.join(path, f"pop_{len(c)}_{digest}")
            if not os.path.exists(os.path.join(sub, _META_FILE)):
                tmp = f"{sub}.tmp.{os.getpid()}"
                cls(c).save(tmp)
                try:
                    os.rename(tmp, sub)  # atomic publish
                except OSError:
                    # a concurrent writer won the rename — use theirs
                    import shutil

                    shutil.rmtree(tmp, ignore_errors=True)
                    if not os.path.exists(os.path.join(sub, _META_FILE)):
                        raise
            return cls.load(sub)
        return cls(c)

    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        np.save(os.path.join(path, _COUNTS_FILE), np.asarray(self.counts))
        with open(os.path.join(path, _META_FILE), "w") as f:
            json.dump(
                {"n": int(self.num_clients), "version": 1}, f
            )
        return path

    @classmethod
    def load(cls, path: str) -> "PopulationIndex":
        with open(os.path.join(path, _META_FILE)) as f:
            meta = json.load(f)
        counts = np.load(
            os.path.join(path, _COUNTS_FILE), mmap_mode="r"
        )
        if len(counts) != meta["n"]:
            raise ValueError(
                f"population index at {path}: counts length "
                f"{len(counts)} != meta n {meta['n']}"
            )
        return cls(counts)

    # -- O(1)/O(cohort) lookups --
    @property
    def num_clients(self) -> int:
        return len(self.counts)

    def total_samples(self) -> int:
        if self._total is None:
            self._total = int(np.sum(self.counts, dtype=np.int64))
        return self._total

    def weights(self) -> np.ndarray:
        """Per-client inclusion probabilities (counts / total), cached.
        One O(N) numpy pass on first use."""
        if self._weights is None:
            total = self.total_samples()
            if total <= 0:
                raise ValueError("population has zero total samples")
            self._weights = self.counts.astype(np.float64) / float(total)
        return self._weights

    def alias_table(self) -> AliasSampler:
        """The run's alias sampler, built once (O(N)) and cached —
        every subsequent round draws in O(cohort)."""
        if self._alias is None:
            self._alias = AliasSampler(self.weights())
        return self._alias

    def cohort_counts(self, ids) -> np.ndarray:
        """Sample counts of a cohort — the O(cohort) read the bucket
        math and weighted aggregation need per round."""
        return np.asarray(self.counts[np.asarray(ids, np.int64)], np.int64)

    def shape_classes(self, batch_size: int, pad_bucket: int):
        """``{(steps, bs): first client index}`` — THE warmup
        pre-enumeration contract, delegated to
        data.base.partition_shape_classes (one definition: its
        vectorized path IS this index's packed-counts form) and cached
        per (batch_size, pad_bucket)."""
        key = (int(batch_size), int(pad_bucket))
        cached = self._classes.get(key)
        if cached is None:
            cached = self._classes[key] = partition_shape_classes(
                self.counts, batch_size, pad_bucket
            )
        return cached
