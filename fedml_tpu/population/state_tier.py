"""Sharded mmap client-state tier — fixed-stride per-client records for
SCAFFOLD/Ditto state at million-client populations.

algorithms/state_store.MmapClientState (the 100k-era spill tier) keeps
one ``[N, *leaf_shape]`` memmap PER PYTREE LEAF: a cohort gather fans
out into one fancy-index read per leaf — for a model with dozens of
leaves that is dozens of scattered disk touches per client per round,
and every leaf file's row for one client lives far from its other
leaves' rows. This tier extends data/mmap_store.py's layout discipline
(np.memmap + offsets + meta.json, streaming writes, schema-checked
reopen) with a RECORD-MAJOR layout instead:

    records_{s}.bin     np.memmap uint8 [rows_in_shard, stride]
                        — client record = all leaves' bytes,
                        concatenated at fixed offsets (one contiguous
                        read/write per client per round)
    init_mask.npy       np.lib.format bool [N] (lazy-init bitmap,
                        exactly MmapClientState's)
    meta.json           {n, shard_bits, stride, leaves, layout}

Shards are ``1 << shard_bits`` clients each (PopulationConfig
.state_shard_bits, default 65536/shard ⇒ 1M clients = 16 files):
bounded per-file size for filesystem tooling, and a gather touches only
the shards its cohort lands in. Files are created sparse (O(1) in data
written, whatever N is) and rows are lazily initialized through the
same bitmap contract as MmapClientState — a gather of an untouched row
returns the algorithm's initial state with no write having happened.

Math contract (the spill tier's): gather/scatter are exact byte copies,
so a sharded run is BIT-IDENTICAL to the mmap-per-leaf run and to the
in-HBM run at the same seed — pinned by tests/test_population.py
against ScaffoldAPI. The API is MmapClientState's exactly (gather/
scatter/flush/initialized_ids/reset_to/initialized_count), so
state_store.CohortPrefetcher overlaps the NEXT cohort's record reads
with the current round's device compute unchanged.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import weakref
from typing import Optional, Sequence

import jax
import numpy as np


class ShardedClientState:
    """[N] fixed-stride per-client records over sharded np.memmap files.

    ``init_tree`` is ONE client's initial state (no leading N axis); its
    tree structure, shapes, and dtypes define the record layout."""

    def __init__(
        self,
        init_tree,
        n_clients: int,
        path: Optional[str] = None,
        shard_bits: int = 16,
    ):
        self.n = int(n_clients)
        self.shard_bits = int(shard_bits)
        if not (4 <= self.shard_bits <= 24):
            raise ValueError(
                f"state_shard_bits must be in [4, 24], got {shard_bits}"
            )
        leaves, self._treedef = jax.tree_util.tree_flatten(init_tree)
        self._init_leaves = [np.asarray(l) for l in leaves]
        self._sizes = [l.nbytes for l in self._init_leaves]
        self._offsets = np.concatenate(
            [[0], np.cumsum(self._sizes)]
        ).astype(np.int64)
        self.stride = int(self._offsets[-1])
        if self.stride <= 0:
            raise ValueError("client state record is empty")
        # one packed initial record — what an untouched row gathers as
        self._init_record = np.concatenate(
            [l.reshape(-1).view(np.uint8) for l in self._init_leaves]
        )
        path = path or None  # "" (FedConfig.state_dir default) == unset
        self.path = path or tempfile.mkdtemp(prefix="fedml_tpu_popstate_")
        if path is None:
            # scratch temp dirs are cleaned up; user-supplied paths are
            # THEIRS (resume target) — same stance as MmapClientState
            self._cleanup = weakref.finalize(
                self, shutil.rmtree, self.path, ignore_errors=True
            )
        else:
            self._cleanup = None
        os.makedirs(self.path, exist_ok=True)
        schema = [
            {"shape": list(l.shape), "dtype": str(l.dtype)}
            for l in self._init_leaves
        ]
        meta = {
            "layout": "record-v1",
            "n": self.n,
            "shard_bits": self.shard_bits,
            "stride": self.stride,
            "leaves": schema,
        }
        meta_path = os.path.join(self.path, "meta.json")
        fresh = not os.path.exists(meta_path)
        if not fresh:
            # resume: reopen an existing store — layout must match
            # exactly (a silent mismatch would interleave rows wrong)
            with open(meta_path) as f:
                existing = json.load(f)
            if existing != meta:
                raise ValueError(
                    f"existing sharded state store at {self.path} has "
                    f"layout {existing}, expected {meta}"
                )
        from fedml_tpu.data.mmap_store import advise_random

        shard_rows = 1 << self.shard_bits
        self._num_shards = -(-self.n // shard_rows) if self.n else 0
        self._shards = []
        for s in range(self._num_shards):
            rows = min(shard_rows, self.n - s * shard_rows)
            fp = os.path.join(self.path, f"records_{s:05d}.bin")
            # np.memmap w+ creates the file SPARSE at full logical size
            shard = np.memmap(
                fp,
                dtype=np.uint8,
                mode="r+" if (not fresh and os.path.exists(fp)) else "w+",
                shape=(rows, self.stride),
            )
            # cohort gathers are RANDOM rows by construction — without
            # this the kernel readahead turns every row fault into a
            # whole readahead window of sparse pages (184 ms vs 0.65 ms
            # per 8-row gather at 1M clients; see data.mmap_store)
            advise_random(shard)
            self._shards.append(shard)
        if fresh:
            self._init_mask = np.lib.format.open_memmap(
                os.path.join(self.path, "init_mask.npy"),
                mode="w+",
                dtype=np.bool_,
                shape=(self.n,),
            )
            with open(meta_path, "w") as f:
                json.dump(meta, f)
        else:
            self._init_mask = np.load(
                os.path.join(self.path, "init_mask.npy"), mmap_mode="r+"
            )
        advise_random(self._init_mask)

    @property
    def state_bytes_total(self) -> int:
        """Logical size of the full store (the number the HBM path would
        have to pin) — actual disk use is cohort-sparse."""
        return self.n * self.stride

    # -- record (un)packing --
    def _split_records(self, buf: np.ndarray, inited: np.ndarray):
        """(C, stride) uint8 record buffer -> leaf pytree [C, ...];
        rows with ``inited`` False are overwritten with the init state."""
        C = buf.shape[0]
        out = []
        fill = not inited.all()
        for off, base in zip(self._offsets[:-1], self._init_leaves):
            raw = np.ascontiguousarray(buf[:, off:off + base.nbytes])
            arr = raw.view(base.dtype).reshape((C,) + base.shape)
            if fill:
                arr[~inited] = base
            out.append(arr)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def _pack_records(self, rows_tree) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(rows_tree)
        C = len(np.asarray(leaves[0]))
        buf = np.empty((C, self.stride), np.uint8)
        for off, base, r in zip(
            self._offsets[:-1], self._init_leaves, leaves
        ):
            r = np.ascontiguousarray(np.asarray(r, dtype=base.dtype))
            buf[:, off:off + base.nbytes] = r.reshape(C, -1).view(np.uint8)
        return buf

    def _shard_rows(self, idx: np.ndarray):
        """Group a cohort's ids by shard: yields (shard array slice,
        local row ids, cohort positions) — one contiguous-file touch per
        shard the cohort lands in."""
        shard_of = idx >> self.shard_bits
        row_of = idx & ((1 << self.shard_bits) - 1)
        for s in np.unique(shard_of):
            m = shard_of == s
            yield self._shards[int(s)], row_of[m], m

    # -- the MmapClientState API --
    def gather(self, idx: Sequence[int]):
        """Cohort rows as a HOST pytree [C, ...] (copies — safe to ship
        to device). Untouched rows come back as the initial state."""
        idx = np.asarray(idx, np.int64)
        inited = np.asarray(self._init_mask[idx])
        buf = np.empty((len(idx), self.stride), np.uint8)
        for shard, rows, m in self._shard_rows(idx):
            buf[m] = shard[rows]
        return self._split_records(buf, inited)

    def scatter(self, idx: Sequence[int], rows_tree) -> None:
        """Write the cohort's updated records back (host arrays in)."""
        idx = np.asarray(idx, np.int64)
        buf = self._pack_records(rows_tree)
        for shard, rows, m in self._shard_rows(idx):
            shard[rows] = buf[m]
        self._init_mask[idx] = True

    def flush(self) -> None:
        for shard in self._shards:
            shard.flush()
        self._init_mask.flush()

    def initialized_ids(self) -> np.ndarray:
        """Client ids whose rows have ever been scattered — with
        :meth:`gather` of them, the store's ENTIRE information content
        (checkpoints embed exactly this; see MmapClientState)."""
        return np.flatnonzero(np.asarray(self._init_mask))

    def reset_to(self, idx: Sequence[int], rows_tree) -> None:
        """Roll back to {initial state everywhere except ``idx``, which
        holds ``rows_tree``} — the restore side of the self-contained
        checkpoint."""
        inited = self.initialized_ids()
        if len(inited):
            for shard, rows, _ in self._shard_rows(inited):
                shard[rows] = self._init_record
            self._init_mask[inited] = False
        if len(np.asarray(idx)):
            self.scatter(idx, rows_tree)

    def initialized_count(self) -> int:
        return int(np.count_nonzero(self._init_mask))
