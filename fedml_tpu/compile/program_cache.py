"""In-process program deduplication + AOT warmup handles.

The XLA jit cache is keyed by the *jit object*: two factories that build
byte-identical round functions still compile twice, because each
``jax.jit`` call wraps a fresh closure. Every algorithm family here
builds its round/eval/train programs through small factories, and a full
test run constructs hundreds of such factories over a handful of model ×
config shapes — so a cold tier-1 run used to spend the bulk of its
budget recompiling near-identical small programs (ROADMAP timeout note).

:class:`ProgramCache` closes that gap: factories describe the program's
static determinants (see :mod:`fedml_tpu.compile.digest`) and get back a
process-wide shared :class:`CachedProgram` — one jit object, one compile
per (program structure, shape class) per process. Factories handed
opaque callables (custom ``local_train_fn``, defense hooks) must bypass
the registry via :meth:`ProgramCache.wrap_uncached`; a digest that
over-merged two different programs would be a silent-wrong-numerics bug,
so the keying is deliberately conservative.

:class:`CachedProgram` is also the AOT warmup surface:
``prog.warmup(*args)`` runs ``jit(...).lower(...).compile()`` ahead of
round 0 (emitting a ``compile`` telemetry span + XLA cost analysis) and
keeps the compiled executable; subsequent calls whose abstract signature
matches dispatch straight to it, so the warmup compile IS the run's
compile — warm runs are numerically identical to cold runs because the
executable is built from the exact same lowering either way."""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from fedml_tpu.compile.digest import call_signature, program_digest
from fedml_tpu.telemetry import get_registry, get_tracer


def _device_pin_token() -> Optional[tuple]:
    """The thread-local ``jax.default_device`` pin as a signature token,
    or None when the thread is unpinned.

    Tenant placement (fedml_tpu/serve/placement.py) pins each tenant's
    threads to a device slice, but XLA executables are compiled PER
    DEVICE — the AOT dispatch map and the persistent executable store
    are keyed by abstract call signature, which is device-blind. Without
    this token a tenant pinned to device 3 could adopt a co-tenant's (or
    a predecessor process's) executable committed to device 0 and
    silently dispatch there, defeating the placement. Pinned threads
    therefore fold the pin into the signature; unpinned threads (every
    single-run path, the whole pre-placement world) keep signatures —
    and on-disk executable keys — byte-identical to every historical
    run."""
    try:
        import jax

        d = jax.config.jax_default_device
    except Exception:  # noqa: BLE001 — jax-free/old-jax contexts
        return None
    if d is None:
        return None
    return ("__device__", getattr(d, "platform", str(d)),
            int(getattr(d, "id", -1)))


def _pinned_signature(args) -> tuple:
    sig = call_signature(args)
    pin = _device_pin_token()
    return sig if pin is None else sig + (pin,)


class CachedProgram:
    """A jit-compiled program handle: callable, lowerable, warmable.

    Transparent stand-in for the wrapped ``jax.jit`` object at every call
    site (``__call__``/``lower`` forward to it). After :meth:`warmup`,
    calls whose signature matches a warmed executable dispatch to the AOT
    executable directly; anything else (different shape class, kwargs,
    sharding mismatch) falls back to the ordinary jit dispatch path."""

    def __init__(
        self,
        fn: Callable,
        label: str,
        digest: Optional[str] = None,
        cache: Optional["ProgramCache"] = None,
        key_fields: Optional[Dict[str, Any]] = None,
    ):
        self.fn = fn
        self.label = label
        self.digest = digest
        # The exact key_fields dict this program was registered under —
        # introspection surface for the digest-completeness fuzzer
        # (fedml_tpu/analysis/digest_audit.py recomputes digests with
        # fields deliberately dropped to prove the audit catches the
        # scaffold eta_g bug class). None for bypassed programs.
        self.key_fields = key_fields
        self._cache = cache
        self._aot: Dict[tuple, Any] = {}
        self._aot_stats: Dict[tuple, dict] = {}
        # signatures already probed against the persistent executable
        # store (executable_cache.py) — each shape class pays at most one
        # disk lookup, hit or miss
        self._exec_probed: set = set()
        # lazy-probe circuit breaker: every dispatch that computes a
        # signature while NOTHING has been adopted burns one unit —
        # probed-miss or repeat call alike — so after a few calls an
        # installed-but-empty store stops taxing the hot path with
        # per-call tree_flatten (e.g. the whole test suite under the
        # conftest session store). warmup() still probes regardless, and
        # any adoption re-arms the signature path via the non-empty _aot.
        self._exec_probe_budget = 4

    def _exec_cache(self):
        """The installed persistent executable store, when this program
        is eligible for it (a canonical digest is the cross-process half
        of the key — bypassed/opaque programs have none and never
        persist)."""
        if self.digest is None:
            return None
        from fedml_tpu.compile.executable_cache import (
            installed_executable_cache,
        )

        return installed_executable_cache()

    def _load_serialized(self, sig, tracer=None):
        """Try to adopt a persisted executable for ``sig``; returns its
        stats row or None. On a hit the executable enters the same AOT
        dispatch map warmup fills, so a warm-from-disk run takes exactly
        the dispatch path a warm-in-process run takes (byte-identical
        numerics — the executable IS the one a compile would build,
        pinned by tests/test_compile.py)."""
        cache = self._exec_cache()
        if cache is None:
            return None
        t0 = time.perf_counter()
        exe = cache.load(self.digest, sig)
        if exe is None:
            return None
        dt = time.perf_counter() - t0
        flops = bytes_accessed = None
        try:
            ca = exe.cost_analysis()
            if isinstance(ca, list):  # older jax returns [dict]
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0)) or None
            bytes_accessed = float(ca.get("bytes accessed", 0.0)) or None
        except Exception:  # noqa: BLE001 — no cost model on this backend
            pass
        self._aot[sig] = exe
        st = {
            "compile_s": 0.0,
            "flops": flops,
            "bytes": bytes_accessed,
            "aot_cache_hit": False,
            "deserialized": True,
            "deserialize_s": dt,
        }
        self._aot_stats[sig] = st
        if self._cache is not None:
            self._cache._note_deserialize(dt, label=self.label, digest=self.digest)
        if tracer is not None:
            # zero-duration marker span: the deserialize replaced a compile
            with tracer.span(
                "compile", program=self.label, aot=True, deserialized=True
            ):
                pass
        return st

    def __call__(self, *args, **kwargs):
        if not kwargs and (
            self._aot
            or (self._exec_probe_budget > 0 and self._exec_cache() is not None)
        ):
            sig = _pinned_signature(args)
            exe = self._aot.get(sig)
            if exe is None and self._exec_probe_budget > 0:
                if sig not in self._exec_probed:
                    # lazy dispatch of a shape class nobody warmed:
                    # before paying a compile, probe the persistent
                    # executable store once — a fresh process whose
                    # predecessor warmed this (program, shape class)
                    # dispatches with zero compiles
                    self._exec_probed.add(sig)
                    try:
                        if self._load_serialized(sig) is not None:
                            exe = self._aot.get(sig)
                    except Exception:  # noqa: BLE001 — the store must
                        import logging  # never break a dispatch

                        logging.exception(
                            "executable-cache probe failed for %r",
                            self.label,
                        )
                if exe is None and not self._aot:
                    # nothing adopted so far: burn breaker budget per
                    # CALL (not per class) so a program whose store
                    # entries don't exist stops paying call_signature
                    # after a handful of dispatches
                    self._exec_probe_budget -= 1
            if exe is not None:
                try:
                    return exe(*args)
                except (TypeError, ValueError):
                    # same shapes/dtypes but a different sharding/layout
                    # than the warmed executable (checked BEFORE anything
                    # executes) — evict the signature so later rounds
                    # don't re-pay the failed dispatch, and let the jit
                    # path compile/dispatch that variant normally. The
                    # stats entry goes too: a later warmup() must really
                    # recompile, not report a stale aot_cache_hit while
                    # the executable is gone
                    self._aot.pop(sig, None)
                    self._aot_stats.pop(sig, None)
        return self.fn(*args, **kwargs)

    def lower(self, *args, **kwargs):
        return self.fn.lower(*args, **kwargs)

    def measured_cost(self) -> Optional[dict]:
        """The measured XLA cost analysis of this program's warmed /
        adopted executables — ``{"flops", "bytes"}`` maxed over shape
        classes (the cohort-max class is what a round dispatches), or
        None when nothing has been AOT-compiled yet. The admission
        controller (fedml_tpu/serve/admission.py) prices candidate
        tenants from this: a MEASURED per-dispatch cost, not a guess."""
        flops = [
            st["flops"] for st in self._aot_stats.values()
            if st.get("flops")
        ]
        byts = [
            st["bytes"] for st in self._aot_stats.values()
            if st.get("bytes")
        ]
        if not flops and not byts:
            return None
        return {
            "flops": max(flops) if flops else None,
            "bytes": max(byts) if byts else None,
        }

    def warmup(self, *args, tracer=None) -> dict:
        """AOT-compile this program for the signature of ``args``
        (``jit(...).lower(...).compile()``) and keep the executable for
        dispatch. Lowering never executes the function, so donated
        buffers in ``args`` are untouched. Idempotent per signature —
        a second warmup is a hit with ``compile_s == 0``. Returns
        ``{compile_s, flops, bytes, aot_cache_hit}``."""
        sig = _pinned_signature(args)
        st = self._aot_stats.get(sig)
        if st is not None:
            # a hit costs nothing: report compile_s=0 (the docstring
            # contract) so a repeat run in a long-lived process doesn't
            # re-bill the first run's compile seconds in its summary rows
            return dict(st, compile_s=0.0, aot_cache_hit=True)
        tracer = tracer or get_tracer()
        # zero-cold-start path: a predecessor process may have persisted
        # this exact (program digest, shape class, environment) —
        # deserialize it instead of compiling (executable_cache.py; the
        # environment fingerprint guarantees skew lands here as a clean
        # miss, never as wrong numerics)
        self._exec_probed.add(sig)
        st = self._load_serialized(sig, tracer=tracer)
        if st is not None:
            return dict(st)
        t0 = time.perf_counter()
        with tracer.span("compile", program=self.label, aot=True):
            compiled = self.fn.lower(*args).compile()
        dt = time.perf_counter() - t0
        flops = bytes_accessed = None
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, list):  # older jax returns [dict]
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0)) or None
            bytes_accessed = float(ca.get("bytes accessed", 0.0)) or None
        except Exception:  # noqa: BLE001 — no cost model on this backend
            pass
        self._aot[sig] = compiled
        st = {
            "compile_s": dt,
            "flops": flops,
            "bytes": bytes_accessed,
            "aot_cache_hit": False,
        }
        self._aot_stats[sig] = st
        if self._cache is not None:
            self._cache._note_compile_time(dt, label=self.label, digest=self.digest)
        exec_cache = self._exec_cache()
        if exec_cache is not None:
            # export the executable so the NEXT process deserializes
            # instead of compiling (best-effort; save() warns on programs
            # this jaxlib cannot serialize)
            try:
                exec_cache.save(self.digest, sig, compiled)
            except Exception:  # noqa: BLE001 — persistence must not
                import logging  # break warmup

                logging.exception(
                    "persisting executable for %r failed", self.label
                )
        return dict(st)


class ProgramCache:
    """Process-wide registry of :class:`CachedProgram`s keyed by the
    canonical digest of their static determinants (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs: Dict[str, CachedProgram] = {}
        self.hits = 0
        self.misses = 0
        self.bypassed = 0
        self.compile_s = 0.0  # accumulated measured (AOT) compile seconds
        # zero-cold-start accounting (executable_cache.py): programs
        # adopted from the persistent executable store instead of
        # compiled, and the seconds spent deserializing them
        self.deserialize_hits = 0
        self.deserialize_s = 0.0
        # compile-event listeners (fedml_tpu/analysis/sentinel.py): called
        # OUTSIDE the lock as listener(kind, label, digest) with kind in
        # {"build", "hit", "bypass", "aot_compile", "aot_deserialize"} —
        # "build" = a new jit object was constructed (a cache miss),
        # "hit" = a dedup hit, "bypass" = an uncacheable wrap,
        # "aot_compile" = a warmup actually compiled an executable,
        # "aot_deserialize" = a PERSISTED executable was adopted instead
        # of compiling (the sentinel must not count these).
        self._listeners: List[Callable[[str, str, Optional[str]], None]] = []

    def add_listener(self, fn: Callable[[str, str, Optional[str]], None]) -> None:
        """Subscribe to compile events (see ``_listeners``). Listeners
        must be fast and must not raise — they run on the caller's
        thread inside factory construction paths."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def _emit(self, kind: str, label: str, digest: Optional[str]) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(kind, label, digest)
            except Exception:  # noqa: BLE001 — observers never break a build
                import logging

                logging.exception("program-cache listener failed")
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        """Mirror the cache counters into the Prometheus registry
        (telemetry/metrics.py) so the recompile picture is scrapeable
        live, not only visible in the end-of-run summary.json row."""
        try:
            snap = self.stats()
            # the ProgramCache is process-wide by design (co-tenant
            # federations share programs), so its gauges publish into the
            # GLOBAL registry even on a tenant-scoped thread — a tenant
            # registry must not carry process totals under a tenant label
            from fedml_tpu.telemetry import get_global_registry

            reg = get_global_registry()
            for key in ("hits", "misses", "bypassed", "programs"):
                reg.gauge(
                    f"fedml_compile_cache_{key}",
                    "ProgramCache activity (fedml_tpu/compile/)",
                ).set(snap[key])
        except Exception:  # noqa: BLE001 — telemetry must not break builds
            pass

    def get_or_build(
        self, label: str, key_fields: Dict[str, Any], builder: Callable[[], Callable]
    ) -> CachedProgram:
        """The shared program for ``key_fields``, building it via
        ``builder()`` on first request. ``builder`` must return a jit
        object whose traced program is FULLY determined by
        ``key_fields`` — when any closure input is not canonically
        describable, use :meth:`wrap_uncached` instead."""
        digest = program_digest(key_fields)
        with self._lock:
            prog = self._programs.get(digest)
            if prog is not None:
                self.hits += 1
        if prog is not None:
            self._emit("hit", label, digest)
            return prog
        # build outside the lock: builders only wrap jax.jit (compilation
        # itself stays lazy), so a racing duplicate build is cheap and the
        # second one below is discarded
        fn = builder()
        built = False
        with self._lock:
            prog = self._programs.get(digest)
            if prog is None:
                prog = CachedProgram(
                    fn, label, digest=digest, cache=self, key_fields=key_fields
                )
                self._programs[digest] = prog
                self.misses += 1
                built = True
            else:
                self.hits += 1
        self._emit("build" if built else "hit", label, digest)
        return prog

    def wrap_uncached(self, label: str, fn: Callable) -> CachedProgram:
        """Wrap a jit object that must NOT be deduplicated (opaque
        closures), still counting it and giving it the warmup surface."""
        with self._lock:
            self.bypassed += 1
        self._emit("bypass", label, None)
        return CachedProgram(fn, label, cache=self)

    def iter_programs(self) -> List[CachedProgram]:
        """Snapshot of the registered (deduped) programs — the digest
        fuzzer's enumeration surface."""
        with self._lock:
            return list(self._programs.values())

    def lookup(self, digest: str) -> Optional[CachedProgram]:
        """The registered program for ``digest`` WITHOUT building or
        counting a hit/miss — the admission controller's warm-program
        probe (a probe is a question, not a use)."""
        with self._lock:
            return self._programs.get(digest)

    def _note_compile_time(
        self, dt: float, label: str = "?", digest: Optional[str] = None
    ) -> None:
        with self._lock:
            self.compile_s += float(dt)
        self._emit("aot_compile", label, digest)

    def _note_deserialize(
        self, dt: float, label: str = "?", digest: Optional[str] = None
    ) -> None:
        """A persisted executable replaced a compile. Emitted as its own
        event kind — the recompile sentinel must NOT count it (nothing
        compiled; that is the whole point)."""
        with self._lock:
            self.deserialize_hits += 1
            self.deserialize_s += float(dt)
        self._emit("aot_deserialize", label, digest)

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "bypassed": self.bypassed,
                "programs": len(self._programs),
                "compile_s": self.compile_s,
                "deserialize_hits": self.deserialize_hits,
                "deserialize_s": self.deserialize_s,
            }

    def summary_row(self, baseline: Optional[dict] = None) -> dict:
        """Flat MetricsLogger row of (baseline-relative) cache activity —
        the summary.json compile-accounting contract (docs/COMPILE.md)."""
        snap = self.stats()
        base = baseline or {}
        return {
            "compile/cache_hits": snap["hits"] - base.get("hits", 0),
            "compile/cache_misses": snap["misses"] - base.get("misses", 0),
            "compile/cache_bypassed": snap["bypassed"] - base.get("bypassed", 0),
            "compile/programs": snap["programs"],
            "compile/compile_s": snap["compile_s"] - base.get("compile_s", 0.0),
            "compile/deserialize_hits": snap["deserialize_hits"]
            - base.get("deserialize_hits", 0),
            "compile/deserialize_s": snap["deserialize_s"]
            - base.get("deserialize_s", 0.0),
        }

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self.hits = self.misses = self.bypassed = 0
            self.compile_s = 0.0
            self.deserialize_hits = 0
            self.deserialize_s = 0.0


def hooks_cacheable(*hooks) -> bool:
    """THE cache-bypass predicate shared by every round factory: a
    factory may dedupe its program ONLY when every opaque hook that could
    shape the traced computation is None. Single-sourced so a factory
    growing a new hook parameter cannot forget the matching bypass term
    in one copy (an over-merged digest is silent wrong numerics)."""
    return all(h is None for h in hooks)


_GLOBAL = ProgramCache()


def get_program_cache() -> ProgramCache:
    """The process-wide program cache every factory dedupes through (the
    session-scoped ``program_cache`` pytest fixture exposes this same
    object, so test modules share each other's compiles)."""
    return _GLOBAL


@contextlib.contextmanager
def use_program_cache(cache: ProgramCache):
    """Temporarily swap the process-wide cache for ``cache`` (restored on
    exit, even on error). The digest-completeness fuzzer
    (fedml_tpu/analysis/digest_audit.py) builds each perturbed config's
    program in a FRESH cache so colliding digests cannot silently hand
    back the base program instead of invoking the factory's builder —
    the collision is exactly what the audit must observe. Not
    thread-safe: meant for single-threaded audit/test harnesses only."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = cache
    try:
        yield cache
    finally:
        _GLOBAL = prev
