"""Hardened persistent XLA compile cache — atomic, integrity-checked,
advisory-locked.

jax's stock file cache (``jax._src.lru_cache.LRUCache``) writes entries
with a plain ``write_bytes`` and reads them back with no integrity
check. Under concurrent writer processes a reader can observe a torn
write, and a corrupt entry then deserializes into a *wrong executable* —
the PR 3 incident class (deterministic ~1e-5 resume-numerics drift plus
munmap/segfault noise until the cache dir was wiped; ROADMAP
"compile-cache hygiene").

:class:`HardenedFileCache` is a drop-in ``CacheInterface`` replacement
that makes every failure mode loud-or-harmless:

- **atomic writes**: entries are written to a same-directory temp file,
  fsynced, then ``os.replace``d into place — a reader can only ever see
  a complete entry or no entry.
- **content-hash verification**: every entry embeds
  ``sha256(payload)``; a mismatch on load (torn write from a non-atomic
  writer, bit rot, truncation) returns a miss instead of wrong bytes.
- **quarantine**: corrupt entries are moved aside into ``quarantine/``
  (preserved for forensics, never re-read) and the program simply
  recompiles.
- **advisory file lock**: writers serialize on ``.ftpc.lock`` via
  ``fcntl.flock``, so concurrent pytest processes can no longer race
  each other's puts (best-effort: a lock timeout degrades to the
  still-atomic unlocked write rather than blocking training).

Entries use our own ``.ftpc`` suffix/format, so a directory previously
populated by the stock cache is simply treated as empty rather than
misread.

:func:`install_hardened_cache` wires an instance in as the process's jax
compilation cache and applies the cache-dir/threshold config in one
place (tests/conftest.py and the CLI ``--compile_cache_dir`` flag both
go through it). Installation is version-gated: if the jax internals
drift, it falls back to the stock persistent cache with a loud warning
rather than failing the run."""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
import pathlib
import threading
import time
from typing import Optional

_MAGIC = b"FTPC1\n"
_SUFFIX = ".ftpc"
_HASH_LEN = 32  # sha256 digest bytes


class HardenedFileCache:
    """Corruption-proof persistent byte store (jax CacheInterface shape:
    ``get(key) -> bytes | None``, ``put(key, value)``)."""

    def __init__(self, path: str, lock_timeout_s: float = 10.0):
        self._path = pathlib.Path(path)
        self._path.mkdir(parents=True, exist_ok=True)
        self.path = self._path  # stock LRUCache exposes .path; keep parity
        self._qdir = self._path / "quarantine"
        self._lock_path = self._path / ".ftpc.lock"
        self._lock_timeout_s = float(lock_timeout_s)
        self._mu = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.quarantined = 0
        self.evicted = 0

    # -- key/path hygiene --
    def _entry_path(self, key: str) -> pathlib.Path:
        # jax cache keys are hex digests; defend anyway against separators
        safe = str(key).replace(os.sep, "_").replace("/", "_")
        if not safe:
            raise ValueError("key cannot be empty")
        return self._path / f"{safe}{_SUFFIX}"

    # -- advisory lock --
    @contextlib.contextmanager
    def _flock(self):
        """Advisory exclusive lock on the cache dir's lockfile. Degrades
        to no-lock after the timeout (writes stay atomic regardless)."""
        fd = None
        locked = False
        try:
            try:
                import fcntl

                fd = os.open(
                    self._lock_path, os.O_CREAT | os.O_RDWR, 0o644
                )
                deadline = time.monotonic() + self._lock_timeout_s
                while True:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        locked = True
                        break
                    except OSError:
                        if time.monotonic() >= deadline:
                            logging.warning(
                                "compile cache lock %s held past %.1fs — "
                                "proceeding unlocked (writes stay atomic)",
                                self._lock_path,
                                self._lock_timeout_s,
                            )
                            break
                        time.sleep(0.05)
            except ImportError:  # non-POSIX: atomic rename is the guard
                pass
            yield
        finally:
            if fd is not None:
                if locked:
                    try:
                        import fcntl

                        fcntl.flock(fd, fcntl.LOCK_UN)
                    except Exception:  # noqa: BLE001
                        pass
                os.close(fd)

    # -- integrity --
    @staticmethod
    def _frame(payload: bytes) -> bytes:
        return _MAGIC + hashlib.sha256(payload).digest() + payload

    @staticmethod
    def _verify(blob: bytes) -> Optional[bytes]:
        head = len(_MAGIC) + _HASH_LEN
        if len(blob) < head or not blob.startswith(_MAGIC):
            return None
        digest = blob[len(_MAGIC):head]
        payload = blob[head:]
        if hashlib.sha256(payload).digest() != digest:
            return None
        return payload

    def quarantine_entry(self, key: str) -> None:
        """Move ``key``'s entry into quarantine/. The executable layer
        (executable_cache.py) calls this when an entry passes the byte
        integrity check but fails SEMANTIC verification — a mismatched
        embedded environment fingerprint, or a payload this jax cannot
        deserialize — so the forensics-preserving quarantine discipline
        covers both corruption classes."""
        self._quarantine(
            self._entry_path(key), reason="failed semantic verification"
        )

    def _quarantine(
        self,
        p: pathlib.Path,
        reason: str = "failed integrity verification",
    ) -> None:
        with self._mu:
            self.quarantined += 1
        try:
            self._qdir.mkdir(parents=True, exist_ok=True)
            dest = self._qdir / f"{p.name}.{os.getpid()}.{time.time_ns()}"
            os.replace(p, dest)
            logging.warning(
                "compile cache entry %s %s — quarantined to %s; the "
                "program recompiles", p.name, reason, dest,
            )
        except OSError:
            # a racing process already moved/removed it — that's fine,
            # the entry is gone either way
            logging.warning(
                "compile cache entry %s %s and could not be quarantined "
                "(already removed?)", p.name, reason,
            )

    # -- CacheInterface --
    def get(self, key: str) -> Optional[bytes]:
        p = self._entry_path(key)
        try:
            blob = p.read_bytes()
        except FileNotFoundError:
            with self._mu:
                self.misses += 1
            return None
        except OSError as e:
            logging.warning("compile cache read %s failed: %s", p, e)
            with self._mu:
                self.misses += 1
            return None
        payload = self._verify(blob)
        if payload is None:
            self._quarantine(p)
            with self._mu:
                self.misses += 1
            return None
        with self._mu:
            self.hits += 1
        # refresh the timestamp so size-cap eviction approximates LRU
        # (the stock LRUCache does the same on get)
        with contextlib.suppress(OSError):
            os.utime(p, None)
        return payload

    # -- size cap (jax_compilation_cache_max_size parity) --
    @staticmethod
    def _max_size_bytes() -> int:
        try:
            import jax

            return int(
                getattr(jax.config, "jax_compilation_cache_max_size", -1)
            )
        except Exception:  # noqa: BLE001 — cache is usable without jax
            return -1

    def _evict_if_needed(self, keep: pathlib.Path) -> None:
        """Drop least-recently-used entries until the directory fits the
        jax size cap (<= 0 means unbounded, jax's default). The stock
        LRUCache enforced this cap; a hardened replacement that silently
        ignored it would grow shared dirs without bound. Never evicts the
        entry just written."""
        cap = self._max_size_bytes()
        if cap <= 0:
            return
        entries = []
        for p in self._path.glob(f"*{_SUFFIX}"):
            try:
                st = p.stat()
            except OSError:  # racing process removed it
                continue
            entries.append((st.st_atime, st.st_size, p))
        total = sum(size for _, size, _ in entries)
        for _, size, p in sorted(entries, key=lambda e: e[0]):
            if total <= cap:
                break
            if p == keep:
                continue
            with contextlib.suppress(OSError):
                os.unlink(p)
                total -= size
                with self._mu:
                    self.evicted += 1

    def put(self, key: str, value: bytes) -> bool:
        """Write an entry; returns True only when THIS call persisted it
        (False: a first writer already holds the slot, or the write
        failed — callers reporting export counters must not count those
        as successes). jax's CacheInterface ignores the return value."""
        p = self._entry_path(key)
        blob = self._frame(bytes(value))
        tmp = p.with_name(f".tmp.{os.getpid()}.{p.name}")
        with self._flock():
            if p.exists():
                return False  # first writer wins (stock LRUCache semantics)
            try:
                with open(tmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, p)
            except OSError as e:
                logging.warning("compile cache write %s failed: %s", p, e)
                with contextlib.suppress(OSError):
                    os.unlink(tmp)
                return False
            self._evict_if_needed(keep=p)
        with self._mu:
            self.puts += 1
        return True

    def stats(self) -> dict:
        with self._mu:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "puts": self.puts,
                "quarantined": self.quarantined,
                "evicted": self.evicted,
            }

    def summary_row(self, baseline: Optional[dict] = None) -> dict:
        snap = self.stats()
        base = baseline or {}
        return {
            f"compile/persistent_{k}": v - base.get(k, 0)
            for k, v in snap.items()
        }


_INSTALLED: Optional[HardenedFileCache] = None


def installed_cache() -> Optional[HardenedFileCache]:
    """The process's installed hardened cache, if any."""
    return _INSTALLED


def install_hardened_cache(
    path: str,
    min_compile_time_secs: float = 2.0,
    min_entry_size_bytes: int = 0,
) -> Optional[HardenedFileCache]:
    """Enable jax's persistent compilation cache at ``path`` with the
    hardened store underneath.

    Applies the standard jax config (cache dir + write thresholds — the
    conservative >= 2 s default matches tests/conftest.py's
    corruption-clean setting; pass a fresh directory for a per-run
    cache), then installs :class:`HardenedFileCache` as the process's
    cache backend. Returns the cache, or None when the jax internals
    don't match (the stock persistent cache then applies, with a
    warning). Idempotent: re-installing over the same path returns the
    existing instance."""
    global _INSTALLED
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(min_compile_time_secs),
    )
    try:
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes",
            int(min_entry_size_bytes),
        )
    except Exception:  # noqa: BLE001 — flag name drift across jax versions
        pass
    if _INSTALLED is not None and str(_INSTALLED.path) == str(path):
        return _INSTALLED
    try:
        from jax._src import compilation_cache as cc

        cache = HardenedFileCache(path)
        with cc._cache_initialized_mutex:
            # claim the once-only initialization slot so jax neither
            # replaces the hardened store nor trips its _cache-is-None
            # assertion later
            cc._cache = cache
            cc._cache_initialized = True
        _INSTALLED = cache
        return cache
    except Exception as e:  # noqa: BLE001 — private-API drift
        logging.warning(
            "hardened compile cache could not be installed (%s: %s) — "
            "falling back to the stock jax persistent cache at %s",
            type(e).__name__, e, path,
        )
        return None


def install_run_cache(
    path: str, min_compile_time_secs: float = 2.0
):
    """Install a hardened cache for ONE run and return ``(cache,
    restore)``: ``restore()`` reinstates whatever persistent-cache binding
    existed before (the conftest-installed shared store, the stock cache,
    or nothing). Without the restore, a run embedded in a long-lived
    process (CliRunner tests, notebook sweeps) would leave every LATER
    compile in the process pointed at the run's — possibly deleted —
    cache directory."""
    import jax

    prev = {
        "dir": jax.config.jax_compilation_cache_dir,
        "min": jax.config.jax_persistent_cache_min_compile_time_secs,
        "installed": _INSTALLED,
        "cc": None,
    }
    try:
        from jax._src import compilation_cache as cc

        with cc._cache_initialized_mutex:
            prev["cc"] = (cc._cache, cc._cache_initialized)
    except Exception:  # noqa: BLE001 — private-API drift
        pass
    cache = install_hardened_cache(
        path, min_compile_time_secs=min_compile_time_secs
    )

    def restore() -> None:
        global _INSTALLED
        jax.config.update("jax_compilation_cache_dir", prev["dir"])
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev["min"]
        )
        if prev["cc"] is not None:
            try:
                from jax._src import compilation_cache as cc

                with cc._cache_initialized_mutex:
                    cc._cache, cc._cache_initialized = prev["cc"]
            except Exception:  # noqa: BLE001
                pass
        _INSTALLED = prev["installed"]

    return cache, restore
