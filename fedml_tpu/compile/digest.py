"""Canonical program digests — the ProgramCache keying contract.

A compiled XLA program is determined by (a) the round/eval/train *code
path* chosen by static configuration, (b) the model architecture, and
(c) the abstract shapes/dtypes/shardings of its inputs. Everything else
(dataset values, RNG values, round indices) is runtime data. The digest
here canonicalizes exactly those determinants into a stable sha256 so
that two independently constructed factories producing structurally
identical programs land on ONE jit object (and therefore ONE compile)
per process.

Canonicalization rules:

- dataclasses (TrainConfig, RobustConfig, ...) → qualname + field map
- dicts → sorted (key, value) pairs; lists/tuples → element lists
- anything with ``.shape``/``.dtype`` (np/jnp arrays, ShapeDtypeStruct)
  → its abstract signature only (shape, dtype, and sharding when
  present) — concrete values NEVER enter a digest
- callables → (module, qualname). This is an identity marker, not a
  semantic hash: factories must only cache programs whose closures are
  fully described by the digested fields, and must bypass the cache
  (``ProgramCache.wrap_uncached``) when handed opaque callables.

Digests of plain fields (configs, shapes, strings) are stable across
processes and runs — pinned by tests/test_compile.py. ``repr`` fallbacks
(e.g. flax module reprs in :func:`model_fingerprint`) are only
guaranteed stable within a process, which is all the in-process dedup
needs."""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict

import numpy as np


def canonical(obj: Any):
    """Reduce ``obj`` to a JSON-able canonical form (see module doc)."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return float(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.dtype):
        return {"__dtype__": str(obj)}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            "__dataclass__": type(obj).__qualname__,
            "fields": {
                f.name: canonical(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            },
        }
    if isinstance(obj, dict):
        return {
            "__dict__": sorted(
                (str(k), canonical(v)) for k, v in obj.items()
            )
        }
    if isinstance(obj, (list, tuple)):
        return [canonical(v) for v in obj]
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None and dtype is not None:
        aval: Dict[str, Any] = {
            "__aval__": [list(map(int, shape)), str(dtype)]
        }
        sharding = getattr(obj, "sharding", None)
        if sharding is not None:
            aval["sharding"] = str(sharding)
        return aval
    if callable(obj):
        return {
            "__callable__": [
                getattr(obj, "__module__", "?"),
                getattr(obj, "__qualname__", repr(type(obj))),
            ]
        }
    return {"__repr__": repr(obj)}


def program_digest(fields: Dict[str, Any]) -> str:
    """sha256 hex digest of the canonical form of ``fields``."""
    doc = json.dumps(
        canonical(fields), sort_keys=True, separators=(",", ":")
    )
    return hashlib.sha256(doc.encode("utf-8")).hexdigest()


def mesh_fingerprint(mesh) -> Dict[str, Any]:
    """Canonical identity of a device mesh: axis names/sizes plus the
    flat device list (id + platform + kind). Two meshes over the same
    devices in the same topology produce identical sharded programs."""
    devices = [
        {
            "id": int(d.id),
            "platform": str(getattr(d, "platform", "?")),
            "kind": str(getattr(d, "device_kind", "?")),
        }
        for d in np.asarray(mesh.devices).ravel()
    ]
    return {
        "axes": {str(k): int(v) for k, v in mesh.shape.items()},
        "devices": devices,
    }


def model_fingerprint(model) -> Dict[str, Any]:
    """Canonical identity of a :class:`fedml_tpu.models.ModelDef`.

    flax linen modules are frozen dataclasses whose ``repr`` prints every
    hyperparameter, so (module class, repr) pins the architecture; the
    ModelDef adapter fields (input shape/dtype, dropout/batch-stats
    switches) pin the adapter behavior that also shapes the traced
    program. NOT stable across processes for arbitrary modules — the
    ProgramCache is in-process by design."""
    module = getattr(model, "module", None)
    try:
        input_dtype = str(np.dtype(getattr(model, "input_dtype", np.float32)))
    except TypeError:
        input_dtype = repr(getattr(model, "input_dtype", None))  # fedlint: disable=repr-in-digest -- non-dtype fallback; in-process stability is the documented ProgramCache contract
    return {
        "name": getattr(model, "name", type(model).__name__),
        "module": (
            [
                type(module).__module__,
                type(module).__qualname__,
                repr(module),  # fedlint: disable=repr-in-digest -- flax frozen-dataclass repr pins hyperparams; in-process-only stability is documented above
            ]
            if module is not None
            else None
        ),
        "input_shape": [int(s) for s in getattr(model, "input_shape", ())],
        "num_classes": getattr(model, "num_classes", None),
        "input_dtype": input_dtype,
        "has_dropout": bool(getattr(model, "has_dropout", False)),
        "has_batch_stats": bool(getattr(model, "has_batch_stats", False)),
    }


def call_signature(args) -> tuple:
    """Hashable abstract signature of a concrete argument tuple: the
    pytree structure plus (shape, dtype) per leaf. This is the key the
    AOT-dispatch path uses to decide whether a warmed executable matches
    a call — shardings are deliberately NOT part of it (a sharding
    mismatch is caught by the executable itself and falls back to jit)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            sig.append(("py", repr(type(leaf)), repr(leaf)))
        else:
            sig.append((tuple(map(int, shape)), str(dtype)))
    return (str(treedef), tuple(sig))
