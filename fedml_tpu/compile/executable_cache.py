"""Serialized AOT executables — compile once per *machine*, not per process.

The compile runtime (PR 4/5) removed redundant compiles *in-process*: the
ProgramCache dedupes structurally identical programs onto one jit object,
and ``--warmup`` AOT-compiles them before round 0. But a fresh process — a
production restart, an autoscaled replica, a CI shard — still recompiles
everything from scratch: the persistent HLO cache (persistent.py) only
skips the *backend* half of slow compiles, and every warmed executable
dies with the process.

This module closes that gap: :class:`ExecutableCache` exports the
executables ``CachedProgram.warmup`` builds (via jax's AOT serialization,
``jax.experimental.serialize_executable``) through the existing
:class:`~fedml_tpu.compile.persistent.HardenedFileCache` — reusing its
atomic writes, sha256 integrity verification, quarantine, advisory lock
and LRU size cap rather than re-implementing them — so a second process
*deserializes* its programs instead of compiling them.

Keying: an entry is addressed by sha256 of

- the program's **ProgramCache canonical digest** (digest.py — the
  complete static determinants of the traced program; completeness is
  mechanically audited by fedml_tpu/analysis/digest_audit.py),
- the **call signature** (pytree structure + per-leaf shape/dtype — one
  executable per shape class, exactly like the in-process AOT map), and
- an **environment fingerprint**: jax/jaxlib versions, backend platform,
  device kind/count/topology, the jax config flags that change lowering
  (threefry partitioning, x64), ``XLA_FLAGS``, and a content hash of the
  fedml_tpu package source. Version skew — a jaxlib upgrade, a different
  accelerator, an edited round body — lands on a different key and
  deserializes to a clean MISS (the program recompiles), never to wrong
  numerics. The fingerprint is *also* embedded in every entry and
  re-verified on load, so an entry copied or forged under the right key
  is quarantined rather than trusted.

SECURITY — the cache directory is a CODE-TRUST boundary. Entries are
transported as pickles (jax's AOT serialization is itself pickle-based),
and unpickling attacker-controlled bytes is arbitrary code execution —
the sha256 frame and embedded fingerprint authenticate INTEGRITY, not
AUTHORSHIP (both live in the same file an attacker would write). Point
``--executable_cache`` only at directories writable solely by principals
you would let run code in the training process (the same trust you
already extend to the Python environment itself). The store chmods a
directory it creates to 0700, and tests/conftest.py keys its session
path by uid, so the default posture on shared machines is private.

Capability gate: serialization support differs across jaxlib versions.
:func:`supports_serialization` probes ``jax.experimental.
serialize_executable`` once; when absent, :func:`install_executable_cache`
warns LOUDLY and returns None — every caller degrades to the plain
compile path (slower, never wrong).

Observability: deserialize hits/seconds land in summary.json
(``compile/deserialize_hits``, ``compile/deserialize_s``, plus the
store's ``compile/executable_*`` counters) and mirror into Prometheus
(``fedml_compile_deserialize_hits``, ``fedml_compile_deserialize_s``,
``fedml_compile_executable_quarantined``). See docs/COMPILE.md."""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pathlib
import pickle
import threading
import time
from typing import Any, Optional

from fedml_tpu.compile.persistent import HardenedFileCache

_KEY_PREFIX = "xc-"
_FORMAT = 1  # bump to invalidate every persisted executable at once
# Entries not READ for this long are pruned on store construction. The
# environment fingerprint contains a source-content hash, so every code
# edit permanently orphans all prior entries under never-again-read keys
# — without age pruning a developer's session store (tests/conftest.py)
# would accumulate unreachable multi-MB pickles indefinitely (the LRU
# size cap only engages when jax_compilation_cache_max_size is set).
_PRUNE_AGE_S = 14 * 24 * 3600

_code_fp_lock = threading.Lock()
_code_fp: Optional[str] = None


def code_fingerprint() -> str:
    """sha256 over every ``.py`` file of the fedml_tpu package (relative
    path + content), memoized per process. A serialized executable bakes
    in the *traced program*, which the ProgramCache digest keys by config
    — but an edit to a round body changes the program without changing
    any config field. In-process that cannot go stale; across processes
    it can, so the code itself enters the environment fingerprint: any
    source change invalidates every persisted executable (clean miss,
    recompile)."""
    global _code_fp
    with _code_fp_lock:
        if _code_fp is not None:
            return _code_fp
        import fedml_tpu

        root = pathlib.Path(fedml_tpu.__file__).parent
        h = hashlib.sha256()
        for p in sorted(root.rglob("*.py")):
            h.update(str(p.relative_to(root)).encode("utf-8"))
            h.update(b"\0")
            h.update(p.read_bytes())
        _code_fp = h.hexdigest()
        return _code_fp


def environment_fingerprint() -> dict:
    """Canonical identity of everything that must match for a serialized
    executable to be safe to run here: jaxlib/XLA version, backend,
    device topology, the lowering-relevant jax config flags, and the
    package source hash (see :func:`code_fingerprint`). Any mismatch is
    a different cache key — skew deserializes to a recompile, never to
    wrong numerics."""
    import jax
    import jaxlib

    devs = jax.devices()

    def flag(name: str, default: Any = None) -> Any:
        try:
            return getattr(jax.config, name)
        except Exception:  # noqa: BLE001 — flag-name drift across versions
            return default

    return {
        "format": _FORMAT,
        "jax": str(jax.__version__),
        "jaxlib": str(jaxlib.__version__),
        "backend": str(jax.default_backend()),
        "device_kind": str(getattr(devs[0], "device_kind", "?")),
        "device_count": len(devs),
        "process_count": int(jax.process_count()),
        "threefry_partitionable": bool(flag("jax_threefry_partitionable", False)),
        "enable_x64": bool(flag("jax_enable_x64", False)),
        # precision/PRNG policy is BAKED into the traced dot/conv/random
        # ops — two processes differing here build different programs
        # under identical configs, so both must split the key (a
        # JAX_DEFAULT_MATMUL_PRECISION env var is jax config, not
        # XLA_FLAGS, and would otherwise adopt a wrong-precision
        # executable under a matching key)
        "matmul_precision": str(flag("jax_default_matmul_precision", None)),
        "prng_impl": str(flag("jax_default_prng_impl", "threefry2x32")),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        "code": code_fingerprint(),
    }


def supports_serialization() -> bool:
    """True when this jaxlib can serialize/deserialize AOT executables."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 — older jaxlib without the module
        return False


class ExecutableCache:
    """Persistent store of serialized AOT executables (thread-safe).

    A thin policy layer over :class:`HardenedFileCache` — the store
    already guarantees atomic writes, sha256-verified reads with
    quarantine, and LRU eviction; this class adds the (digest, signature,
    environment) keying, the embedded-fingerprint re-verification, and
    the serialize/deserialize transport."""

    def __init__(self, path: str):
        existed = pathlib.Path(path).is_dir()
        self._store = HardenedFileCache(path)
        self.path = self._store.path
        if not existed:
            # a directory WE created is private by default (the module
            # docstring's trust boundary); a pre-existing dir keeps its
            # owner's chosen policy — an operator sharing a cache across
            # trusted CI users must be able to
            try:
                os.chmod(self.path, 0o700)
            except OSError:
                pass
        self._prune_stale()
        self._mu = threading.Lock()
        self._env_doc: Optional[dict] = None
        self.hits = 0          # entries deserialized into live executables
        self.misses = 0        # clean key misses (incl. env-skew keys)
        self.puts = 0          # executables serialized + persisted
        self.put_errors = 0    # serialization not supported for a program
        # semantic-verification quarantines are counted by the STORE
        # (quarantine_entry); this stays for API shape + future non-store
        # quarantine paths, and summary_row sums both
        self.quarantined = 0
        self.deserialize_s = 0.0
        self.serialize_s = 0.0

    def _prune_stale(self) -> None:
        """Best-effort drop of OUR entries (xc- prefix only — a shared
        dir's HLO entries are untouched) whose last read/touch is older
        than ``_PRUNE_AGE_S``: code-hash skew orphans entries under keys
        that will never be read again (see _PRUNE_AGE_S). ``get()``
        refreshes atime-via-utime on every hit, so live entries
        survive."""
        now = time.time()
        pruned = 0
        try:
            for p in self.path.glob(f"{_KEY_PREFIX}*.ftpc"):
                try:
                    if now - p.stat().st_atime > _PRUNE_AGE_S:
                        p.unlink()
                        pruned += 1
                except OSError:  # racing process — already gone
                    continue
        except OSError:
            return
        if pruned:
            logging.info(
                "executable cache %s: pruned %d stale entr%s (untouched "
                "> %d days)", self.path, pruned,
                "y" if pruned == 1 else "ies", _PRUNE_AGE_S // 86400,
            )

    # -- keying ------------------------------------------------------------

    def _env(self) -> dict:
        with self._mu:
            if self._env_doc is None:
                self._env_doc = environment_fingerprint()
            return self._env_doc

    def key_for(self, digest: str, sig) -> str:
        doc = json.dumps(
            {"program": digest, "sig": repr(tuple(sig)), "env": self._env()},
            sort_keys=True,
            separators=(",", ":"),
        )
        return _KEY_PREFIX + hashlib.sha256(doc.encode("utf-8")).hexdigest()

    # -- load/save ---------------------------------------------------------

    def load(self, digest: str, sig):
        """The deserialized executable for (digest, sig) in THIS
        environment, or None. Entries that unpickle to a mismatched
        fingerprint, or fail to deserialize, are quarantined (forensics
        preserved) and reported as a miss — the program recompiles with
        identical numerics, mirroring the persistent store's
        corrupt-entry contract."""
        key = self.key_for(digest, sig)
        blob = self._store.get(key)  # sha256-verified; torn/bit-rotted
        if blob is None:             # entries already quarantined inside
            with self._mu:
                self.misses += 1
            return None
        t0 = time.perf_counter()
        try:
            doc = pickle.loads(blob)
            if (
                not isinstance(doc, dict)
                or doc.get("format") != _FORMAT
                or doc.get("program") != digest
                or doc.get("env") != self._env()
            ):
                raise ValueError(
                    "embedded environment/program fingerprint mismatch"
                )
            from jax.experimental import serialize_executable as se

            exe = se.deserialize_and_load(
                doc["payload"], doc["in_tree"], doc["out_tree"]
            )
        except Exception as e:  # noqa: BLE001 — any load fault = quarantine
            # quarantine_entry increments the STORE's quarantined counter
            # — the single source for this event (summary_row/gauges sum
            # store + semantic counters, so counting here too would
            # double-report one quarantine as two)
            self._store.quarantine_entry(key)
            with self._mu:
                self.misses += 1
            logging.warning(
                "serialized executable %s failed to load (%s: %s) — "
                "quarantined; the program recompiles", key, type(e).__name__, e,
            )
            self._publish_gauges()
            return None
        dt = time.perf_counter() - t0
        with self._mu:
            self.hits += 1
            self.deserialize_s += dt
        self._publish_gauges()
        return exe

    def save(self, digest: str, sig, compiled) -> bool:
        """Serialize ``compiled`` and persist it under (digest, sig, env).
        Best-effort: a program this jaxlib cannot serialize (exotic
        sharding, host callbacks) is skipped with a warning — the run is
        merely slower to restart, never wrong."""
        key = self.key_for(digest, sig)
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps(
                {
                    "format": _FORMAT,
                    "program": digest,
                    "env": self._env(),
                    "payload": payload,
                    "in_tree": in_tree,
                    "out_tree": out_tree,
                },
                protocol=pickle.HIGHEST_PROTOCOL,
            )
        except Exception as e:  # noqa: BLE001 — capability gap, not a bug
            with self._mu:
                self.put_errors += 1
            logging.warning(
                "executable for program %s could not be serialized "
                "(%s: %s) — it will recompile in fresh processes",
                digest[:12], type(e).__name__, e,
            )
            return False
        written = self._store.put(key, blob)
        with self._mu:
            if written:
                # only REAL persists count: a declined write (first
                # writer already holds the slot) or a failed one (full /
                # read-only filesystem) must not let the ci.sh
                # export-happened assertion pass vacuously
                self.puts += 1
            self.serialize_s += time.perf_counter() - t0
        self._publish_gauges()
        return written

    # -- observability -----------------------------------------------------

    def _publish_gauges(self) -> None:
        try:
            from fedml_tpu.telemetry import get_registry

            snap = self.stats()
            reg = get_registry()
            reg.gauge(
                "fedml_compile_deserialize_hits",
                "serialized AOT executables loaded instead of compiled",
            ).set(snap["hits"])
            reg.gauge(
                "fedml_compile_deserialize_s",
                "seconds spent deserializing persisted executables",
            ).set(snap["deserialize_s"])
            reg.gauge(
                "fedml_compile_executable_quarantined",
                "persisted executables that failed verification on load",
            ).set(snap["quarantined"] + snap["store"]["quarantined"])
        except Exception:  # noqa: BLE001 — telemetry must not break loads
            pass

    def stats(self) -> dict:
        with self._mu:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "puts": self.puts,
                "put_errors": self.put_errors,
                "quarantined": self.quarantined,
                "deserialize_s": self.deserialize_s,
                "serialize_s": self.serialize_s,
                "store": self._store.stats(),
            }

    def summary_row(self, baseline: Optional[dict] = None) -> dict:
        """Flat MetricsLogger row of the store mechanics (docs/COMPILE.md
        observability contract). The headline zero-cold-start keys —
        ``compile/deserialize_hits``/``_s`` — come from the
        :class:`~fedml_tpu.compile.program_cache.ProgramCache` row (the
        programs that actually adopted a persisted executable), so this
        row only carries the ``executable_*`` store counters."""
        snap = self.stats()
        base = baseline or {}
        return {
            "compile/executable_puts": snap["puts"] - base.get("puts", 0),
            "compile/executable_misses": snap["misses"] - base.get("misses", 0),
            "compile/executable_quarantined": (
                snap["quarantined"] + snap["store"]["quarantined"]
            )
            - (
                base.get("quarantined", 0)
                + base.get("store", {}).get("quarantined", 0)
            ),
        }


_INSTALLED: Optional[ExecutableCache] = None


def installed_executable_cache() -> Optional[ExecutableCache]:
    """The process's installed executable cache, if any."""
    return _INSTALLED


def install_executable_cache(path: str) -> Optional[ExecutableCache]:
    """Install an :class:`ExecutableCache` at ``path`` as the process's
    executable store (``CachedProgram`` warmup/dispatch consults it).
    Capability-gated: returns None — loudly — when this jaxlib cannot
    serialize executables, so every caller degrades to plain compilation.
    Idempotent per path."""
    global _INSTALLED
    if not supports_serialization():
        logging.warning(
            "executable cache at %s DISABLED: this jaxlib has no "
            "jax.experimental.serialize_executable — fresh processes will "
            "recompile every program (slower startup, identical numerics)",
            path,
        )
        return None
    if _INSTALLED is not None and str(_INSTALLED.path) == str(path):
        return _INSTALLED
    _INSTALLED = ExecutableCache(path)
    return _INSTALLED


def install_run_executable_cache(path: str):
    """Install an executable cache for ONE run and return ``(cache,
    restore)`` — ``restore()`` reinstates whatever binding existed before
    (the conftest-installed session store, or nothing), mirroring
    :func:`fedml_tpu.compile.persistent.install_run_cache` so a run
    embedded in a long-lived process can't hijack later loads."""
    global _INSTALLED
    prev = _INSTALLED
    cache = install_executable_cache(path)

    def restore() -> None:
        global _INSTALLED
        _INSTALLED = prev

    return cache, restore
