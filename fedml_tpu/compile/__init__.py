"""Compile runtime — program dedup, AOT warmup, hardened persistent cache.

In the reference framework (PyTorch eager) compilation cost does not
exist; in this JAX port XLA compilation is the dominant *new* cost
dimension. This package manages it as one first-class layer:

- :mod:`fedml_tpu.compile.program_cache` — in-process
  :class:`ProgramCache`: round/eval/train factories across the algorithm
  families dedupe structurally identical programs onto one jit object
  per canonical digest (:mod:`fedml_tpu.compile.digest`), so N
  algorithms × M test modules compile once per shape signature.
- :mod:`fedml_tpu.compile.warmup` — ``--warmup`` AOT path:
  ``jit(...).lower(...).compile()`` the round/eval/server programs
  before round 0, with ``compile`` telemetry spans and per-program XLA
  cost analysis into summary.json; warmed executables serve the actual
  dispatches, so warm runs are numerically identical to cold runs.
- :mod:`fedml_tpu.compile.persistent` — :class:`HardenedFileCache`, a
  corruption-proof wrapper for jax's persistent compilation cache:
  atomic rename writes, sha256 integrity verification with quarantine
  of corrupt entries, and an advisory file lock (the PR 3
  concurrent-writer incident class).

See docs/COMPILE.md for the keying/integrity model and the
observability contract (``compile/*`` keys in summary.json)."""

from fedml_tpu.compile.executable_cache import (
    ExecutableCache,
    environment_fingerprint,
    install_executable_cache,
    install_run_executable_cache,
    installed_executable_cache,
    supports_serialization,
)
from fedml_tpu.compile.digest import (
    call_signature,
    canonical,
    mesh_fingerprint,
    model_fingerprint,
    program_digest,
)
from fedml_tpu.compile.persistent import (
    HardenedFileCache,
    install_hardened_cache,
    install_run_cache,
    installed_cache,
)
from fedml_tpu.compile.program_cache import (
    CachedProgram,
    ProgramCache,
    get_program_cache,
    hooks_cacheable,
    use_program_cache,
)
from fedml_tpu.compile.warmup import (
    warmup_api,
    warmup_local_train,
    warmup_splitnn,
)

__all__ = [
    "CachedProgram",
    "ExecutableCache",
    "HardenedFileCache",
    "ProgramCache",
    "call_signature",
    "canonical",
    "compile_snapshot",
    "compile_summary_row",
    "environment_fingerprint",
    "get_program_cache",
    "hooks_cacheable",
    "install_executable_cache",
    "install_hardened_cache",
    "install_run_cache",
    "install_run_executable_cache",
    "installed_cache",
    "installed_executable_cache",
    "mesh_fingerprint",
    "model_fingerprint",
    "program_digest",
    "supports_serialization",
    "use_program_cache",
    "warmup_api",
    "warmup_local_train",
    "warmup_splitnn",
]


def compile_snapshot() -> dict:
    """Point-in-time counters of every compile-cache layer (baseline for
    :func:`compile_summary_row`, so a run embedded in a long-lived
    process reports ITS activity, not the process's lifetime totals)."""
    snap = {"programs": get_program_cache().stats()}
    hard = installed_cache()
    if hard is not None:
        snap["persistent"] = hard.stats()
    execs = installed_executable_cache()
    if execs is not None:
        snap["executables"] = execs.stats()
    return snap


def compile_summary_row(baseline: dict = None) -> dict:
    """Flat ``{"compile/...": value}`` MetricsLogger row combining the
    in-process program cache, the hardened persistent HLO layer, and the
    serialized-executable store (when installed) — summary.json stays
    the single CI oracle."""
    base = baseline or {}
    row = get_program_cache().summary_row(baseline=base.get("programs"))
    hard = installed_cache()
    if hard is not None:
        row.update(hard.summary_row(baseline=base.get("persistent")))
    execs = installed_executable_cache()
    if execs is not None:
        row.update(execs.summary_row(baseline=base.get("executables")))
    return row
