"""AOT warmup — compile the run's programs before round 0.

Round 0 of a cold run silently includes XLA compilation: the first round
dispatch blocks on a compile that can take orders of magnitude longer
than the round itself, which skews round-0 wall-clock metrics and forced
the transport deadline/quorum machinery to special-case "arbitrarily
long cold compiles" (PR 3). ``--warmup`` moves that cost to an explicit,
observable phase: every program the run will dispatch at round 0 is
``jit(...).lower(...).compile()``d up front (through
:meth:`CachedProgram.warmup`, which keeps the executable for dispatch —
so the warmup compile IS the run's compile, not a duplicate), under
``compile`` telemetry spans, with per-program XLA cost analysis
(flops/bytes) and compile seconds forwarded into summary.json.

Warm and cold runs are numerically identical by construction: warmup
only lowers and compiles — it executes nothing, consumes no RNG, and
touches no training state (pinned by tests/test_compile.py).

Covered programs, matching what ``FedAvgAPI.train`` dispatches first:

- the round program — the eager round-fn variant for round
  ``start_round``'s cohort shapes, or the fused multi-round chunk
  program when ``fused_rounds`` applies;
- the eval program at the cached test-batch shapes;
- the server-optimizer step (FedOpt family), when present.

Later shape classes (a differently-bucketed cohort, the second
``may_pad`` variant) still compile lazily on first dispatch — warmup
covers the round-0 cold start, not every program the run may ever
build."""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from fedml_tpu.telemetry import get_tracer


def _warm_one(rows: dict, label: str, fn, args, tracer) -> None:
    """AOT-compile one program; record per-program stats; never crash the
    run (a backend without AOT support degrades to lazy compilation)."""
    if not hasattr(fn, "warmup"):
        from fedml_tpu.compile.program_cache import get_program_cache

        # the AOT executable lives on this throwaway wrapper, so only the
        # persistent compile cache (when installed) carries the benefit to
        # the run's lazy dispatch — route the factory through the
        # ProgramCache instead of relying on this fallback
        logging.warning(
            "warmup program %r is a bare jit object (no ProgramCache "
            "wrapper): the warmed executable cannot serve its dispatches "
            "directly", label,
        )
        fn = get_program_cache().wrap_uncached(label, fn)
    try:
        st = fn.warmup(*args, tracer=tracer)
    except Exception as e:  # noqa: BLE001 — warmup must not kill the run
        logging.warning("warmup of program %r failed: %s", label, e)
        rows[f"compile/{label}_error"] = f"{type(e).__name__}: {e}"
        return
    rows[f"compile/{label}_compile_s"] = st["compile_s"]
    rows[f"compile/{label}_aot_cache_hit"] = bool(st.get("aot_cache_hit"))
    if st.get("flops"):
        rows[f"compile/{label}_flops"] = st["flops"]
    if st.get("bytes"):
        rows[f"compile/{label}_bytes"] = st["bytes"]


def warmup_api(api, log_fn: Optional[Callable[[dict], None]] = None) -> dict:
    """Warm a FedAvgAPI-family simulator (vmap or mesh): round + eval +
    server-optimizer programs for ``api.start_round``'s shapes. Returns
    the compile-stats row (also forwarded through ``log_fn``)."""
    import jax

    tracer = getattr(api, "_tracer", None) or get_tracer()
    rows: dict = {}
    t0 = time.perf_counter()
    with tracer.span("warmup"):
        r0 = int(getattr(api, "start_round", 0))
        mesh = getattr(api, "mesh", None)
        if mesh is not None:
            # mesh runtime: round outputs carry NamedSharding(mesh, P()),
            # so from round r0+1 on the round INPUT does too. Replicate
            # global_vars onto the mesh now (values unchanged) so ONE
            # warmed executable serves every round, instead of matching
            # only round r0's single-device placement.
            from jax.sharding import NamedSharding, PartitionSpec

            api.global_vars = jax.device_put(
                api.global_vars, NamedSharding(mesh, PartitionSpec())
            )
        # -- round program: fused chunk when the planner would fuse,
        #    else the eager variant for round r0's cohort --
        fused_len = 1
        if hasattr(api, "_fused_chunk_len") and hasattr(api, "_fused_plan"):
            try:
                fused_len = api._fused_chunk_len(r0)
            except Exception:  # noqa: BLE001 — planner guards vary by algo
                fused_len = 1
        if fused_len > 1:
            fn, rest = api._fused_plan(r0, fused_len)
            if hasattr(api, "_warm_fused"):
                # hand the whole plan to train_rounds_fused so the chunk's
                # index/mask stacking + H2D transfer is paid once, not twice
                api._warm_fused[(r0, fused_len)] = (fn, rest)
            _warm_one(
                rows, "round_fused", fn, (api.global_vars, *rest), tracer
            )
        else:
            sampled = api._round_plan(r0)[0]
            batch = api._round_batch(sampled, r0)
            rng = jax.random.fold_in(api.rng, r0 + 1)
            placed = api._place_batch(batch, rng)
            if hasattr(api, "_warm_placed"):
                # hand the placed batch to train_round(r0) so the stack +
                # host->device transfer is paid once, not twice
                api._warm_placed[r0] = placed
            fn = api.round_fn
            variant_for = getattr(fn, "variant_for", None)
            if variant_for is not None:
                fn = variant_for(api._round_may_pad(r0))
            _warm_one(rows, "round", fn, (api.global_vars, *placed), tracer)
        # -- eval program at the cached test-batch shapes --
        if getattr(api, "eval_fn", None) is not None and hasattr(
            api, "_eval_batches"
        ):
            batches = api._eval_batches()
            _warm_one(
                rows, "eval", api.eval_fn, (api.global_vars, *batches), tracer
            )
        # -- server optimizer step (FedOpt family) --
        server_step = getattr(api, "_server_step", None)
        opt_state = getattr(api, "server_opt_state", None)
        if server_step is not None and opt_state is not None:
            _warm_one(
                rows,
                "server_opt",
                server_step,
                (api.global_vars, api.global_vars, opt_state),
                tracer,
            )
    rows["compile/warmup_s"] = time.perf_counter() - t0
    if log_fn is not None:
        log_fn(dict(rows))
    return rows


def warmup_local_train(
    shared_train,
    config,
    data,
    global_vars,
    client_ids,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Warm a transport federation's shared local-train program for every
    distinct shape class among ``client_ids`` (the round-0 cohort) — the
    warmup *barrier* that lets ``--deadline_s`` rounds start with
    compilation already paid instead of racing a cold compile.

    Shape classes are derived exactly the way ``LocalTrainer._train``
    derives them (``stack_clients`` of one client at the configured
    batch/bucket settings), so the warmed signature matches the training
    dispatch byte-for-byte."""
    import jax
    import numpy as np

    from fedml_tpu.data.base import bucket_steps, stack_clients

    tracer = get_tracer()
    rows: dict = {}
    t0 = time.perf_counter()
    seen = set()
    with tracer.span("warmup", programs="local_train"):
        for cid in client_ids:
            n = len(data.client_y[int(cid)])
            klass = bucket_steps(
                [n], config.data.batch_size, config.data.pad_bucket
            )[:2]
            if klass in seen:
                continue
            seen.add(klass)
            batch = stack_clients(
                data,
                [int(cid)],
                config.data.batch_size,
                seed=0,  # values are irrelevant — only shapes enter lower()
                pad_bucket=config.data.pad_bucket,
            )
            rng = jax.random.PRNGKey(0)
            _warm_one(
                rows,
                f"local_train_s{klass[0]}b{klass[1]}",
                shared_train,
                (
                    global_vars,
                    np.asarray(batch.x[0]),
                    np.asarray(batch.y[0]),
                    np.asarray(batch.mask[0]),
                    rng,
                ),
                tracer,
            )
    rows["compile/warmup_s"] = time.perf_counter() - t0
    if log_fn is not None:
        log_fn(dict(rows))
    return rows
