"""AOT warmup — compile the run's programs before round 0.

Round 0 of a cold run silently includes XLA compilation: the first round
dispatch blocks on a compile that can take orders of magnitude longer
than the round itself, which skews round-0 wall-clock metrics and forced
the transport deadline/quorum machinery to special-case "arbitrarily
long cold compiles" (PR 3). ``--warmup`` moves that cost to an explicit,
observable phase: every program the run will dispatch at round 0 is
``jit(...).lower(...).compile()``d up front (through
:meth:`CachedProgram.warmup`, which keeps the executable for dispatch —
so the warmup compile IS the run's compile, not a duplicate), under
``compile`` telemetry spans, with per-program XLA cost analysis
(flops/bytes) and compile seconds forwarded into summary.json.

Warm and cold runs are numerically identical by construction: warmup
only lowers and compiles — it executes nothing, consumes no RNG, and
touches no training state (pinned by tests/test_compile.py).

Covered programs, matching what ``FedAvgAPI.train`` dispatches first:

- the round program — the eager round-fn variant for round
  ``start_round``'s cohort shapes, or the fused multi-round chunk
  program when ``fused_rounds`` applies;
- **every other (steps, bs) shape class the partition can produce**
  (:func:`fedml_tpu.data.base.partition_shape_classes` — the cohort
  bucket only reads the max member's count, so the reachable classes
  are exactly the per-client singleton buckets), including both
  ``may_pad`` round variants where the partition makes both reachable —
  so rounds 1..R never hit a lazy shape-bucket compile, no matter which
  cohorts the scheduler draws;
- the eval program at the cached test-batch shapes;
- the server-optimizer step (FedOpt family), when present.

With a persistent executable cache installed
(compile/executable_cache.py), every warmed program is additionally
serialized to disk — so the NEXT process deserializes its whole warmup
set instead of compiling it (zero-cold-start serving).

Fused multi-round chunk programs are pre-enumerated too
(:func:`_warm_fused_chunks`, ISSUE-14 satellite closing the PR-8
leftover): the horizon's chunk schedule is walked at STRUCTURAL lengths
and every distinct (program, [T, C, cap] signature) pair is warmed —
bounded by ``_MAX_WARM_CHUNKS`` programs over ``_MAX_CHUNK_SEGMENTS``
examined segments, skips logged. Remaining lazy compiles: chunk
programs past those caps, and cohorts reshaped mid-run by participation
faults (a fault-shrunk cohort is a different client-axis size)."""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from fedml_tpu.telemetry import get_tracer


def _warm_one(rows: dict, label: str, fn, args, tracer) -> None:
    """AOT-compile one program; record per-program stats; never crash the
    run (a backend without AOT support degrades to lazy compilation)."""
    if not hasattr(fn, "warmup"):
        from fedml_tpu.compile.program_cache import get_program_cache

        # the AOT executable lives on this throwaway wrapper, so only the
        # persistent compile cache (when installed) carries the benefit to
        # the run's lazy dispatch — route the factory through the
        # ProgramCache instead of relying on this fallback
        logging.warning(
            "warmup program %r is a bare jit object (no ProgramCache "
            "wrapper): the warmed executable cannot serve its dispatches "
            "directly", label,
        )
        fn = get_program_cache().wrap_uncached(label, fn)
    try:
        st = fn.warmup(*args, tracer=tracer)
    except Exception as e:  # noqa: BLE001 — warmup must not kill the run
        logging.warning("warmup of program %r failed: %s", label, e)
        rows[f"compile/{label}_error"] = f"{type(e).__name__}: {e}"
        return
    rows[f"compile/{label}_compile_s"] = st["compile_s"]
    rows[f"compile/{label}_aot_cache_hit"] = bool(st.get("aot_cache_hit"))
    if st.get("deserialized"):
        # the program came from the persistent executable store — nothing
        # compiled (compile_s is 0 by contract); the row says so, so a
        # warm-from-disk run's summary is distinguishable from a hit
        rows[f"compile/{label}_deserialized"] = True
        rows[f"compile/{label}_deserialize_s"] = st.get("deserialize_s", 0.0)
    if st.get("flops"):
        rows[f"compile/{label}_flops"] = st["flops"]
    if st.get("bytes"):
        rows[f"compile/{label}_bytes"] = st["bytes"]


# Pre-enumeration cap: full-batch mode (batch_size=-1) makes bs the
# cohort max, so a ragged partition can yield one class per DISTINCT
# client size — compiling them all would turn warmup into a multi-hour
# stall over shapes most runs never dispatch. Classes are warmed
# most-populous first and the skip is LOGGED (never silent).
_MAX_WARM_CLASSES = 32

# Fused-chunk pre-enumeration bounds (PR-8 leftover closed here: chunk
# programs beyond round ``start_round``'s used to compile lazily at
# dispatch). Chunk program diversity comes from (shape class, power-of-2
# length, chunk_may_pad) combinations, which recur with the eval/class
# period — examining a bounded window of chunk segments sees them all;
# the warm cap bounds compile time like the class cap above.
_MAX_WARM_CHUNKS = 8
_MAX_CHUNK_SEGMENTS = 64
# ... and the walk itself is bounded in ROUNDS examined: chunk-free
# schedules (eval every round) would otherwise call the per-round
# planner across the whole horizon warming nothing.
_MAX_WALK_ROUNDS = 1024


def _classes_by_population(
    counts, batch_size: int, pad_bucket: int, cohort: int = 1
):
    """Partition shape classes ordered by member count (descending) —
    under the warm cap, the classes that cover the most clients (and so
    the most future cohorts) compile first.

    ``cohort`` filters UNREACHABLE classes: a class is a cohort's shape
    only when its defining client is the cohort MAX, which needs at
    least ``cohort`` clients at-or-below that size to draw from (without
    replacement). With counts=[8,100,100,100] and cohort=4 every cohort
    contains a 100-sample client, so the size-8 singleton class can
    never be dispatched — warming it would waste compile time and cache
    entries. Callers with fault-shrinkable cohorts pass cohort=1 (a
    shrunk cohort CAN make small classes reachable)."""
    from fedml_tpu.data.base import bucket_steps, partition_shape_classes

    classes = partition_shape_classes(counts, batch_size, pad_bucket)
    population: dict = {}
    class_max: dict = {}
    for n in counts:
        k = bucket_steps([int(n)], batch_size, pad_bucket)[:2]
        population[k] = population.get(k, 0) + 1
        class_max[k] = max(class_max.get(k, 0), int(n))
    sorted_counts = sorted(int(n) for n in counts)
    import bisect

    def reachable(k) -> bool:
        return bisect.bisect_right(sorted_counts, class_max[k]) >= cohort

    ordered = sorted(
        ((k, v) for k, v in classes.items() if reachable(k)),
        key=lambda kv: (-population[kv[0]], kv[0]),
    )
    skipped = max(0, len(ordered) - _MAX_WARM_CLASSES)
    if skipped:
        logging.warning(
            "shape-class pre-enumeration capped: warming the %d most-"
            "populous of %d classes (%d skipped — they will compile "
            "lazily on first dispatch). A class count this high usually "
            "means batch_size=-1 (full-batch mode) over a ragged "
            "partition; consider pad_bucket to collapse classes.",
            _MAX_WARM_CLASSES, len(ordered), skipped,
        )
    return ordered[:_MAX_WARM_CLASSES], skipped


def _class_may_pad_variants(fulls, st: int, bs: int, cohort: int):
    """Which ``may_pad`` round variants are reachable for shape class
    ``(st, bs)`` given the partition's per-client full-step counts
    (``ceil(n/bs)`` per client). A client can join an (st, bs) cohort iff
    its full-step count <= st; the cohort pads iff any member underfills
    the bucketed step count. ``may_pad=False`` needs a whole cohort of
    exact fills; ``True`` needs one underfill (its own bucket rounding,
    or a smaller ride-along client)."""
    members = [f for f in fulls if f <= st]
    exact = sum(1 for f in members if f == st)
    variants = []
    if exact >= cohort:
        variants.append(False)
    if any(f < st for f in members):
        variants.append(True)
    return variants or [None]


def _warm_partition_classes(api, rows: dict, tracer, r0: int) -> None:
    """Pre-enumerate and AOT-compile the eager round program for EVERY
    (steps, bs) shape class the partition can produce — not just round
    ``r0``'s — so later rounds whose cohorts bucket differently dispatch
    a warmed executable instead of paying a lazy compile (ROADMAP item 1:
    every later-round shape bucket used to compile lazily at dispatch).

    Synthetic all-zero batches drive the lowering (only shapes/dtypes
    enter ``lower()``); they pass through ``api._place_batch`` so mesh
    runtimes warm against the exact shardings their dispatches carry.
    Round ``r0``'s class was already warmed from its real batch — the
    re-warm here is a free per-signature hit that only labels the row."""
    import jax
    import numpy as np

    from fedml_tpu.data.base import ClientBatch

    cfg = api.config
    data = api.data
    counts = [int(n) for n in api._client_counts(range(data.num_clients))]
    cohort = len(api._round_plan(r0)[0])
    # participation faults shrink cohorts mid-run, which can make classes
    # reachable that full cohorts never produce — enumerate as if cohorts
    # could be singletons then
    faults = getattr(api, "faults", None)
    reach_cohort = (
        1
        if faults is not None and faults.plan.has_participation_faults()
        else cohort
    )
    classes, skipped = _classes_by_population(
        counts, cfg.data.batch_size, cfg.data.pad_bucket,
        cohort=reach_cohort,
    )
    if skipped:
        rows["compile/warm_classes_skipped"] = skipped
    feat = tuple(data.client_x[0].shape[1:])
    lab = tuple(data.client_y[0].shape[1:])
    xdt, ydt = data.client_x[0].dtype, data.client_y[0].dtype
    fn = api.round_fn
    variant_for = getattr(fn, "variant_for", None)
    can_vary = bool(getattr(fn, "supports_may_pad", False))
    rng = jax.random.fold_in(api.rng, r0 + 1)  # shape-only: (2,) uint32
    store = getattr(api, "_store", None)
    for (st, bs), _rep in classes:
        if store is not None:
            # the HBM-store round-batch program (gather + reshape) is a
            # per-class dispatch too — warm it, or round 1..R's first
            # cohort in this class pays ITS lazy compile instead
            from fedml_tpu.data.device_store import gather_program

            _warm_one(
                rows,
                f"gather_s{st}b{bs}",
                gather_program(st, bs),
                (
                    store.flat_x,
                    store.flat_y,
                    np.zeros((cohort, st * bs), np.int32),
                    np.zeros((cohort, st * bs), np.float32),
                ),
                tracer,
            )
        batch = ClientBatch(
            x=np.zeros((cohort, st, bs) + feat, xdt),
            y=np.zeros((cohort, st, bs) + lab, ydt),
            mask=np.ones((cohort, st, bs), np.float32),
            num_samples=np.ones((cohort,), np.float32),
        )
        placed = api._place_batch(batch, rng)
        if can_vary:
            fulls = [-(-n // bs) for n in counts]
            variants = _class_may_pad_variants(fulls, st, bs, cohort)
        else:
            variants = [None]
        for mp in variants:
            f = variant_for(mp) if variant_for is not None else fn
            suffix = {False: "_nopad", True: "_pad"}.get(mp, "")
            _warm_one(
                rows,
                f"round_s{st}b{bs}{suffix}",
                f,
                (api.global_vars, *placed),
                tracer,
            )


def _warm_fused_chunks(api, rows: dict, tracer, r0: int, skip=None) -> None:
    """Walk the horizon's chunk schedule (STRUCTURAL lengths — the
    measured planner is deliberately not consulted, see
    ``_fused_chunk_len(structural=True)``) and AOT-compile every
    DISTINCT fused program the run can dispatch: distinct (program
    digest, [T, C, cap] signature) pairs — chunk lengths past
    ``start_round``'s and classes the walk reaches. Each newly warmed
    chunk's staged plan is memoized in ``api._warm_fused`` so its first
    dispatch reuses the index/mask H2D paid here — except under the
    measured planner, whose probe's eager segments shift every later
    chunk's start round, so the structural (round, length) keys would
    never be popped: there only the compiled programs are warmed (the
    ProgramCache/executable store is keyed by digest + shapes, not
    start round) and no staged arrays are retained. Bounded by
    :data:`_MAX_WARM_CHUNKS` warms over :data:`_MAX_CHUNK_SEGMENTS`
    examined segments and :data:`_MAX_WALK_ROUNDS` examined rounds
    (a schedule that never forms a chunk — eval every round — must not
    walk a 100k-round horizon for zero warms); skips logged, never
    silent."""
    cfg = api.config
    if (
        cfg.fed.fused_rounds <= 1
        or not getattr(api, "_supports_fused", True)
        or getattr(api, "_store", None) is None
        or not hasattr(api, "_fused_plan")
        or not hasattr(api, "_warm_fused")
    ):
        return
    warmed = set(skip or ())
    warms = segments = examined = 0
    r = r0
    while (
        r < cfg.fed.comm_round
        and segments < _MAX_CHUNK_SEGMENTS
        and examined < _MAX_WALK_ROUNDS
    ):
        examined += 1
        try:
            L = api._fused_chunk_len(r, structural=True)
        except Exception as e:  # noqa: BLE001 — planner guards vary by algo
            logging.warning("fused-chunk walk stopped at round %d: %s", r, e)
            break
        if L > 1:
            segments += 1
            try:
                fn, rest = (
                    api._warm_fused.get((r, L)) or api._fused_plan(r, L)
                )
                idx_shape = tuple(getattr(rest[2], "shape", ()))
                key = (getattr(fn, "digest", None) or id(fn), idx_shape)
                if key not in warmed:
                    if warms >= _MAX_WARM_CHUNKS:
                        rows["compile/warm_chunks_skipped"] = (
                            rows.get("compile/warm_chunks_skipped", 0) + 1
                        )
                        logging.warning(
                            "fused-chunk pre-enumeration capped at %d "
                            "programs; chunk at round %d compiles lazily",
                            _MAX_WARM_CHUNKS, r,
                        )
                    else:
                        warmed.add(key)
                        warms += 1
                        if getattr(api, "planner", None) is None:
                            # static plan only: dispatch pops the exact
                            # (start_round, L) key, so the staged H2D is
                            # reused; the measured probe shifts starts
                            # and would strand these device arrays
                            api._warm_fused.setdefault((r, L), (fn, rest))
                        _warm_one(
                            rows,
                            f"round_fused_r{r}x{L}",
                            fn,
                            (api.global_vars, *rest),
                            tracer,
                        )
            except Exception as e:  # noqa: BLE001 — enumeration must not
                logging.warning(  # kill the run
                    "fused-chunk warm at round %d failed: %s", r, e
                )
                break
        r += L
    if warms:
        rows["compile/warm_chunk_programs"] = warms


def warmup_api(api, log_fn: Optional[Callable[[dict], None]] = None) -> dict:
    """Warm a FedAvgAPI-family simulator (vmap or mesh): round + eval +
    server-optimizer programs for ``api.start_round``'s shapes. Returns
    the compile-stats row (also forwarded through ``log_fn``)."""
    import jax

    tracer = getattr(api, "_tracer", None) or get_tracer()
    rows: dict = {}
    t0 = time.perf_counter()
    with tracer.span("warmup"):
        r0 = int(getattr(api, "start_round", 0))
        mesh = getattr(api, "mesh", None)
        if mesh is not None:
            # mesh runtime: round outputs carry NamedSharding(mesh, P()),
            # so from round r0+1 on the round INPUT does too. Replicate
            # global_vars onto the mesh now (values unchanged) so ONE
            # warmed executable serves every round, instead of matching
            # only round r0's single-device placement.
            from jax.sharding import NamedSharding, PartitionSpec

            api.global_vars = jax.device_put(
                api.global_vars, NamedSharding(mesh, PartitionSpec())
            )
        # -- round program: fused chunk when the planner would fuse,
        #    else the eager variant for round r0's cohort --
        fused_len = 1
        if hasattr(api, "_fused_chunk_len") and hasattr(api, "_fused_plan"):
            try:
                fused_len = api._fused_chunk_len(r0)
            except Exception:  # noqa: BLE001 — planner guards vary by algo
                fused_len = 1
        if fused_len > 1:
            fn, rest = api._fused_plan(r0, fused_len)
            if hasattr(api, "_warm_fused"):
                # hand the whole plan to train_rounds_fused so the chunk's
                # index/mask stacking + H2D transfer is paid once, not twice
                api._warm_fused[(r0, fused_len)] = (fn, rest)
            _warm_one(
                rows, "round_fused", fn, (api.global_vars, *rest), tracer
            )
            # fused runs still dispatch EAGER rounds (single-round chunks
            # at eval boundaries, class changes under vmap) — enumerate
            # the partition's eager classes too
            try:
                _warm_partition_classes(api, rows, tracer, r0)
            except Exception as e:  # noqa: BLE001
                logging.warning(
                    "shape-class pre-enumeration failed: %s", e
                )
                rows["compile/class_enum_error"] = f"{type(e).__name__}: {e}"
            # ...and the horizon's OTHER chunk programs (lengths cut by
            # eval boundaries / class changes beyond this first chunk's)
            r0_key = (
                getattr(fn, "digest", None) or id(fn),
                tuple(getattr(rest[2], "shape", ())),
            )
            _warm_fused_chunks(
                api, rows, tracer, r0 + fused_len, skip={r0_key}
            )
        else:
            sampled = api._round_plan(r0)[0]
            batch = api._round_batch(sampled, r0)
            rng = jax.random.fold_in(api.rng, r0 + 1)
            placed = api._place_batch(batch, rng)
            if hasattr(api, "_warm_placed"):
                # hand the placed batch to train_round(r0) so the stack +
                # host->device transfer is paid once, not twice
                api._warm_placed[r0] = placed
            fn = api.round_fn
            variant_for = getattr(fn, "variant_for", None)
            if variant_for is not None:
                fn = variant_for(api._round_may_pad(r0))
            _warm_one(rows, "round", fn, (api.global_vars, *placed), tracer)
            # every OTHER shape class the partition can produce — rounds
            # 1..R must never pay a lazy shape-bucket compile
            try:
                _warm_partition_classes(api, rows, tracer, r0)
            except Exception as e:  # noqa: BLE001 — enumeration must not
                logging.warning(  # kill the run; r0 is already warm
                    "shape-class pre-enumeration failed: %s", e
                )
                rows["compile/class_enum_error"] = f"{type(e).__name__}: {e}"
            # a 1-length chunk at r0 (eval rounds terminate their chunk,
            # and round 0 is always an eval round) does NOT mean the run
            # is eager — fused chunks start at r0+1; enumerate them
            _warm_fused_chunks(api, rows, tracer, r0 + 1)
        # -- eval program at the cached test-batch shapes --
        if getattr(api, "eval_fn", None) is not None and hasattr(
            api, "_eval_batches"
        ):
            batches = api._eval_batches()
            _warm_one(
                rows, "eval", api.eval_fn, (api.global_vars, *batches), tracer
            )
        # -- server optimizer step (FedOpt family) --
        server_step = getattr(api, "_server_step", None)
        opt_state = getattr(api, "server_opt_state", None)
        if server_step is not None and opt_state is not None:
            _warm_one(
                rows,
                "server_opt",
                server_step,
                (api.global_vars, api.global_vars, opt_state),
                tracer,
            )
    rows["compile/warmup_s"] = time.perf_counter() - t0
    if log_fn is not None:
        log_fn(dict(rows))
    return rows


def warmup_splitnn(
    bottom,
    top,
    config,
    data,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Warm every program a split federation dispatches — the boundary-cut
    triple (client forward, server top-step, client backward), the fused
    simulator step they must stay byte-parity with, and the eval program —
    for the run's one activation shape class (``batch_size`` × the cut
    width, derived via ``jax.eval_shape`` so no real forward runs).

    Split rounds are a RELAY: a cold boundary compile stalls not just one
    client but every later ring slot behind it, so the warmup barrier
    matters more here than in the horizontal family. All five factories
    route through the ProgramCache, so with a persistent executable store
    installed the warmed set deserializes on the next process start."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.splitfed.programs import (
        make_split_optimizer,
        make_splitnn_client_backward,
        make_splitnn_client_forward,
        make_splitnn_eval,
        make_splitnn_fused_step,
        make_splitnn_server_step,
        merge_opt_state,
    )

    tracer = get_tracer()
    rows: dict = {}
    t0 = time.perf_counter()
    cfg = config
    lr = cfg.train.lr
    momentum = cfg.train.momentum
    wd = cfg.train.wd
    bs = int(cfg.data.batch_size)
    feat = tuple(np.asarray(data.client_x[0]).shape[1:])
    xdt = np.asarray(data.client_x[0]).dtype
    ydt = np.asarray(data.client_y[0]).dtype
    # params only drive shapes here — same init path as the transport
    k1, k2 = jax.random.split(jax.random.PRNGKey(cfg.seed))
    x0 = jnp.zeros((1,) + feat, jnp.float32)
    bp = jax.device_get(bottom.module.init(k1, x0)["params"])
    acts_sds = jax.eval_shape(
        lambda v, x: bottom.module.apply({"params": v}, x, train=False),
        bp,
        jax.ShapeDtypeStruct((bs,) + feat, jnp.float32),
    )
    tp = jax.device_get(
        top.module.init(k2, jnp.zeros((1,) + acts_sds.shape[1:]))["params"]
    )
    opt = make_split_optimizer(lr, momentum, wd)
    b_opt = jax.device_get(opt.init(bp))
    t_opt = jax.device_get(opt.init(tp))
    xb = np.zeros((bs,) + feat, xdt)
    yb = np.zeros((bs,), ydt)
    acts = np.zeros(acts_sds.shape, np.float32)
    with tracer.span("warmup", programs="splitfed"):
        _warm_one(
            rows,
            "split_forward",
            make_splitnn_client_forward(bottom),
            (bp, xb),
            tracer,
        )
        _warm_one(
            rows,
            "split_server_step",
            make_splitnn_server_step(top, lr, momentum, wd),
            (tp, t_opt, acts, yb),
            tracer,
        )
        _warm_one(
            rows,
            "split_backward",
            make_splitnn_client_backward(bottom, lr, momentum, wd),
            (bp, b_opt, xb, acts),
            tracer,
        )
        _warm_one(
            rows,
            "split_fused",
            make_splitnn_fused_step(bottom, top, lr=lr, momentum=momentum, wd=wd),
            (
                {"bottom": bp, "top": tp},
                merge_opt_state(opt, b_opt, t_opt, bp, tp),
                xb,
                yb,
            ),
            tracer,
        )
        _warm_one(
            rows,
            "split_eval",
            make_splitnn_eval(bottom, top),
            (bp, tp, xb, yb),
            tracer,
        )
    rows["compile/warmup_s"] = time.perf_counter() - t0
    if log_fn is not None:
        log_fn(dict(rows))
    return rows


def warmup_local_train(
    shared_train,
    config,
    data,
    global_vars,
    client_ids=None,
    log_fn: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Warm a transport federation's shared local-train program for every
    distinct shape class in the partition — the warmup *barrier* that
    lets ``deadline_s`` rounds start with compilation already paid
    instead of racing a cold compile, for EVERY round's cohort (the
    pre-PR-8 version only covered round 0's, so a later round whose
    client bucketed differently still raced a lazy compile against the
    deadline). ``client_ids`` restricts the enumeration (legacy round-0
    behavior); None — the default — derives the warmup set from the
    whole partition via :func:`partition_shape_classes`.

    Shape classes are derived exactly the way ``LocalTrainer._train``
    derives them (``stack_clients`` of one client at the configured
    batch/bucket settings), so the warmed signature matches the training
    dispatch byte-for-byte."""
    import jax
    import numpy as np

    from fedml_tpu.data.base import stack_clients

    tracer = get_tracer()
    rows: dict = {}
    t0 = time.perf_counter()
    if client_ids is None:
        client_ids = range(data.num_clients)
    client_ids = list(client_ids)
    counts = [len(data.client_y[int(cid)]) for cid in client_ids]
    classes, skipped = _classes_by_population(
        counts, config.data.batch_size, config.data.pad_bucket
    )
    if skipped:
        rows["compile/warm_classes_skipped"] = skipped
    with tracer.span("warmup", programs="local_train"):
        for (steps, bs), rep in classes:
            cid = int(client_ids[rep])
            batch = stack_clients(
                data,
                [cid],
                config.data.batch_size,
                seed=0,  # values are irrelevant — only shapes enter lower()
                pad_bucket=config.data.pad_bucket,
            )
            rng = jax.random.PRNGKey(0)
            _warm_one(
                rows,
                f"local_train_s{steps}b{bs}",
                shared_train,
                (
                    global_vars,
                    np.asarray(batch.x[0]),
                    np.asarray(batch.y[0]),
                    np.asarray(batch.mask[0]),
                    rng,
                ),
                tracer,
            )
    rows["compile/warmup_s"] = time.perf_counter() - t0
    if log_fn is not None:
        log_fn(dict(rows))
    return rows
