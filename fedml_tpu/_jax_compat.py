"""jax API compat shims for older jaxlib builds.

The mesh runtime is written against the stable ``jax.shard_map`` API: the
``check_vma=`` argument and ``jax.lax.pcast`` varying-axes marks. Older
jaxlib builds (this container ships 0.4.x) predate both: shard_map lives
at ``jax.experimental.shard_map.shard_map`` with the pre-rename
``check_rep=`` spelling, and there is no VMA type system at all — so
``pcast(..., to="varying")`` is semantically the identity there.

``install()`` adds forwarding shims so every call site keeps the
forward-looking spelling and the package still runs on the older runtime.
It is a no-op on jax with the stable API, idempotent, and called at import
time by the modules whose code paths reach ``jax.shard_map`` /
``jax.lax.pcast`` (parallel/, scaffold, ditto, fednova) — NOT by the
package ``__init__``, which stays import-free so jax-less consumers (e.g.
a telemetry scrape sidecar) can ``import fedml_tpu.telemetry``."""

from __future__ import annotations


def install() -> None:
    import jax

    if not hasattr(jax, "shard_map"):
        import functools

        from jax.experimental.shard_map import shard_map as _expm_shard_map

        @functools.wraps(_expm_shard_map)
        def _shard_map_compat(f, *args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _expm_shard_map(f, *args, **kwargs)

        jax.shard_map = _shard_map_compat
    if not hasattr(jax.lax, "pcast"):
        # no VMA typing on this jax — a replicated->varying cast is a no-op
        jax.lax.pcast = lambda x, axes=None, *, to=None: x
