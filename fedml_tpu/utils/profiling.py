"""Profiling subsystem — per-round device FLOPs, MFU, and jax.profiler
traces.

SURVEY §5 assigns this slot jax.profiler + per-round host metrics; the
reference has only ad-hoc timers (`time.perf_counter` around aggregation,
FedAVGAggregator.py:4,78; JSON-size log per message, message.py:77-78; the
TRPC latency sweep, trpc_comm_manager.py:146-211). Here the compiled XLA
cost model supplies exact per-call FLOPs, so MFU = achieved/peak is a
first-class per-round metric, and a trace directory flag captures a full
device timeline viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# Public per-chip peak dense-matmul throughput (FLOP/s). Keyed by substring
# of jax.Device.device_kind. bf16 is the MXU-native dtype; fp32 on TPU runs
# through the MXU at reduced rate (~1/8 via passes) — we track the bf16 and
# fp32 peaks separately so MFU is honest for both policies.
_PEAKS = {
    "v2": {"bfloat16": 45e12, "float32": 11e12},
    "v3": {"bfloat16": 123e12, "float32": 30e12},
    "v4": {"bfloat16": 275e12, "float32": 34e12},
    "v5 lite": {"bfloat16": 197e12, "float32": 25e12},
    "v5e": {"bfloat16": 197e12, "float32": 25e12},
    "v5p": {"bfloat16": 459e12, "float32": 57e12},
    "v6 lite": {"bfloat16": 918e12, "float32": 115e12},
    "v6e": {"bfloat16": 918e12, "float32": 115e12},
}


def device_peak_flops(dtype: str = "bfloat16", device=None) -> Optional[float]:
    """Per-chip peak FLOP/s for the current device, or None if unknown.

    Override with env FEDML_TPU_PEAK_FLOPS (a float) for hardware not in
    the table (e.g. CPU test meshes, future TPU generations)."""
    env = os.environ.get("FEDML_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for key, peaks in _PEAKS.items():
        if key in kind:
            return peaks.get(dtype)
    return None


def compiled_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """FLOPs for ONE call of a jitted function, from XLA's compiled cost
    analysis. Lowering does not execute the function (donated buffers are
    untouched). Returns None where the backend exposes no cost model."""
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):  # older jax returns [dict]
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def mfu(
    flops_per_call: Optional[float],
    calls_per_sec: float,
    dtype: str = "bfloat16",
    n_devices: int = 1,
) -> Optional[float]:
    """Model FLOPs Utilization: achieved FLOP/s over aggregate peak."""
    peak = device_peak_flops(dtype)
    if not flops_per_call or not peak:
        return None
    return (flops_per_call * calls_per_sec) / (peak * n_devices)


def scan_slope_seconds(step_fn, init_carry, k1: int = 1, k2: int = 5, reps: int = 5):
    """Device seconds for ONE ``step_fn(carry) -> carry`` call, measured
    tunnel-proof: jit a program that runs the step K times inside a
    lax.scan, wall-time it at K=k1 and K=k2, and take the slope
    (t2 - t1)/(k2 - k1). Per-program costs — dispatch latency, argument
    upload, the device->host fetch RTT of a remote-device transport —
    appear once per program and cancel in the slope, so the result is pure
    device execution time. Motivated by VERDICT r2 Weak #6: through the
    remote TPU tunnel, per-round wall clock conflates tunnel latency into
    every round.

    Noise discipline: the shared chip/tunnel shows BIMODAL throughput
    windows (~2× swings lasting seconds — PERF_R3.md §3b), so each rep
    measures its (k1, k2) PAIR back-to-back and contributes one slope;
    the result is the MEDIAN positive per-pair slope. Pooling best-of
    times across reps (the original scheme) can pair a fast-mode t(k1)
    with a slow-mode t(k2) and report a 2×-off slope; taking the min
    positive slope instead selects exactly the pairs where the mode
    flipped mid-pair (slow t(k1), fast t(k2) → spuriously tiny slope —
    observed as a 7.6 ms/182%-MFU north-star round). The median discards
    both tails."""

    def rep(c, k_arr):
        def body(c, _):
            return step_fn(c), jnp.float32(0)

        c, _ = jax.lax.scan(body, c, k_arr)
        return c

    jrep = jax.jit(rep)

    def fetch(c):
        np.asarray(jax.tree_util.tree_leaves(c)[0])

    def timed(k):
        t0 = time.perf_counter()
        fetch(jrep(init_carry, jnp.arange(k)))
        return time.perf_counter() - t0

    for k in (k1, k2):  # compile both shapes outside the timing
        fetch(jrep(init_carry, jnp.arange(k)))
    slopes = []
    for _ in range(reps + 3):  # a few retries when pairs straddle a switch
        slope = (timed(k2) - timed(k1)) / (k2 - k1)
        if slope > 0:
            slopes.append(slope)
        if len(slopes) >= reps:
            break
    if not slopes:
        # pathological: no pair produced a positive slope. Fall back to
        # whole-program time at k2 — an OVERestimate (includes the
        # per-program dispatch/fetch overhead the slope would cancel) but
        # always positive, never a negative-MFU artifact.
        return timed(k2) / k2
    slopes.sort()
    return slopes[len(slopes) // 2]


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """Capture a jax.profiler device trace into ``log_dir`` (TensorBoard /
    Perfetto format). No-op when log_dir is falsy, so call sites can pass
    the CLI flag straight through."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
