"""Segmentation metrics — confusion-matrix Evaluator (ref:
fedml_api/distributed/fedseg/utils.py:239+ Evaluator: Pixel_Accuracy,
Pixel_Accuracy_Class, Mean_Intersection_over_Union,
Frequency_Weighted_Intersection_over_Union).

The confusion-matrix accumulation is a jit-compiled bincount; metric
formulas match the reference exactly."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class Evaluator:
    def __init__(self, num_class: int, ignore_index: int = 255):
        self.num_class = num_class
        self.ignore_index = ignore_index
        self.confusion_matrix = np.zeros((num_class, num_class), np.int64)
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        C = self.num_class
        ig = self.ignore_index

        def update(gt, pred):
            valid = (gt != ig) & (gt >= 0) & (gt < C)
            idx = jnp.where(valid, gt * C + pred, C * C)  # overflow bucket
            counts = jnp.bincount(idx.reshape(-1), length=C * C + 1)
            return counts[: C * C].reshape(C, C)

        return update

    def add_batch(self, gt_image, pred_image) -> None:
        self.confusion_matrix += np.asarray(
            self._update(jnp.asarray(gt_image), jnp.asarray(pred_image))
        )

    def reset(self) -> None:
        self.confusion_matrix[:] = 0

    def Pixel_Accuracy(self) -> float:
        cm = self.confusion_matrix
        return float(np.diag(cm).sum() / max(cm.sum(), 1))

    def Pixel_Accuracy_Class(self) -> float:
        cm = self.confusion_matrix
        with np.errstate(divide="ignore", invalid="ignore"):
            acc = np.diag(cm) / cm.sum(axis=1)
        return float(np.nanmean(acc))

    def Mean_Intersection_over_Union(self) -> float:
        cm = self.confusion_matrix
        with np.errstate(divide="ignore", invalid="ignore"):
            iou = np.diag(cm) / (cm.sum(axis=1) + cm.sum(axis=0) - np.diag(cm))
        return float(np.nanmean(iou))

    def Frequency_Weighted_Intersection_over_Union(self) -> float:
        cm = self.confusion_matrix
        freq = cm.sum(axis=1) / max(cm.sum(), 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            iou = np.diag(cm) / (cm.sum(axis=1) + cm.sum(axis=0) - np.diag(cm))
        return float((freq[freq > 0] * iou[freq > 0]).sum())
