"""Analytic model-FLOPs counter — jaxpr walk over matmul/conv primitives.

Why this exists: XLA's compiled ``cost_analysis()['flops']`` reports the
FLOPs of the *optimized* HLO, where fusion/layout decisions (and, on some
backends, remote-device cost models) can drop or fold away large parts of
the count — measured on the cross-silo ResNet-56 round it undercounts the
analytic conv FLOPs ~6×, which silently deflates every MFU we publish.
The scaling-book convention (and the reference's own FLOPs claims) is
*model* FLOPs: 2·M·N·K per matmul, 2·|out_spatial|·B·Cout·(Cin/g)·|kernel|
per conv, counted from the program as written. That is what this module
computes: walk the jaxpr (including the backward pass — count the jaxpr of
the gradient function, not 3× the forward), descending into scan (×length),
while (×1, flagged), cond (max over branches), pjit/remat/custom-vjp
bodies.

Everything else (elementwise, reductions, BN) is ignored — consistent with
the MFU denominator being peak *matmul* throughput.
"""

from __future__ import annotations

from typing import Any

import jax


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _dot_general_flops(eqn) -> float:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = _prod(lhs[i] for i in lb)
    k = _prod(lhs[i] for i in lc)
    m = _prod(lhs[i] for i in range(len(lhs)) if i not in lc and i not in lb)
    n = _prod(rhs[i] for i in range(len(rhs)) if i not in rc and i not in rb)
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    # kernel shape already carries Cin/groups on its input-feature dim, so
    # feature_group_count needs no extra correction here
    out = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape  # kernel
    dn = eqn.params["dimension_numbers"]
    out_spatial = _prod(out[i] for i in dn.out_spec[2:])
    out_batch = out[dn.out_spec[0]]
    out_ch = out[dn.out_spec[1]]
    kernel_spatial = _prod(rhs[i] for i in dn.rhs_spec[2:])
    cin_per_group = rhs[dn.rhs_spec[1]]
    return 2.0 * out_batch * out_spatial * out_ch * cin_per_group * kernel_spatial


_SUBJAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr", "body_jaxpr")


def _closed(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def jaxpr_flops(jaxpr: Any) -> float:
    """Matmul+conv FLOPs of one execution of ``jaxpr`` (a Jaxpr or
    ClosedJaxpr), descending into control-flow/call sub-jaxprs."""
    j = _closed(jaxpr)
    total = 0.0
    for eqn in j.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "scan":
            total += float(eqn.params["length"]) * jaxpr_flops(eqn.params["jaxpr"])
        elif name == "while":
            # trip count unknowable statically — count one body iteration
            # and say so, rather than silently undercounting a hot loop
            body_flops = jaxpr_flops(eqn.params["body_jaxpr"]) + jaxpr_flops(
                eqn.params["cond_jaxpr"]
            )
            if body_flops:
                import warnings

                warnings.warn(
                    "fn_flops: lax.while_loop counted as ONE iteration "
                    f"({body_flops:.3g} FLOPs/iter) — the static count "
                    "cannot know the trip count",
                    stacklevel=2,
                )
            total += body_flops
        elif name == "cond":
            total += max(jaxpr_flops(b) for b in eqn.params["branches"])
        else:
            for key in _SUBJAXPR_KEYS:
                sub = eqn.params.get(key) if hasattr(eqn, "params") else None
                if sub is not None:
                    total += jaxpr_flops(sub)
                    break
    return total


def fn_flops(fn, *args, **kwargs) -> float:
    """Analytic matmul/conv FLOPs of ONE call of ``fn`` at these arg shapes.
    ``fn`` may be jitted (the pjit call jaxpr is descended into). To count a
    training step exactly, pass the function that *contains* the grad —
    the counted jaxpr then includes the real backward primitives."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return jaxpr_flops(jaxpr)
