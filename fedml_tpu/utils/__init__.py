"""Cross-cutting utilities: checkpoint/resume, metrics logging
(ref fedml_api/utils/ + the per-algorithm Saver/wandb call sites,
SURVEY §5)."""

from fedml_tpu.utils.checkpoint import (
    save_checkpoint,
    load_checkpoint,
    restore_like,
)
from fedml_tpu.utils.metrics import MetricsLogger

__all__ = ["save_checkpoint", "load_checkpoint", "restore_like", "MetricsLogger"]
