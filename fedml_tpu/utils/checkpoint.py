"""Round-level checkpoint/resume — the framework-level upgrade SURVEY §5
calls for: the reference has only per-algorithm torch.save of best models
(fedseg/utils.py:161-197, GKTServerTrainer.py:215) and never persists
optimizer state, round index, or RNG.

Format: one .npz of flattened (path → array) leaves + a JSON sidecar of
metadata (round_idx, treedefs are reconstructed from the path keys). Pure
numpy — no pickle, no framework lock-in; any jax/numpy pytree of arrays
round-trips exactly."""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

_SEP = "/"


def _flatten(prefix: str, node, out: Dict[str, np.ndarray]):
    if isinstance(node, dict):
        for k in sorted(node):
            _flatten(f"{prefix}{_SEP}{k}" if prefix else str(k), node[k], out)
    elif isinstance(node, (list, tuple)):
        for i, v in enumerate(node):
            _flatten(f"{prefix}{_SEP}#{i}", v, out)
        out[f"{prefix}{_SEP}#len"] = np.asarray(len(node))
    else:
        out[prefix] = np.asarray(node)


def _unflatten(flat: Dict[str, np.ndarray]):
    root: Dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split(_SEP)
        cur = root
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = val

    def rebuild(node):
        if not isinstance(node, dict):
            return node
        if "#len" in node:
            n = int(node["#len"])
            return [rebuild(node[f"#{i}"]) for i in range(n)]
        return {k: rebuild(v) for k, v in node.items()}

    return rebuild(root)


def save_checkpoint(
    path: str,
    global_vars,
    round_idx: int,
    rng=None,
    server_opt_state=None,
    algo_state=None,
    sched_state=None,
    extra_meta: Optional[dict] = None,
) -> None:
    """Atomic write of (params, server opt state, round, rng): everything —
    including the metadata — lives in ONE npz installed via os.replace, so a
    crash can never leave a mismatched meta/array pair. A sidecar .json copy
    of the metadata is written after the replace purely for humans.

    Multi-host safe: processes other than 0 no-op (params are replicated,
    host 0 owns the save — N concurrent writers on a shared filesystem
    would race), and the tmp name is per-PID so even misconfigured
    same-path writers cannot interleave into one file."""
    import jax

    if jax.process_index() != 0:
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    flat: Dict[str, np.ndarray] = {}
    _flatten("vars", _to_numpy(global_vars), flat)
    if rng is not None:
        flat["rng"] = np.asarray(rng)
    if server_opt_state is not None:
        _flatten("opt", _to_numpy(server_opt_state), flat)
    if algo_state is not None:
        # algorithm-private state (e.g. SCAFFOLD control variates) — the
        # API's checkpoint_state()/restore_state() hooks own its shape
        _flatten("algo", _to_numpy(algo_state), flat)
    if sched_state is not None:
        # scheduler selection memo + loss map (scheduler/policies.py
        # ClientScheduler.state_dict) — a resumed run re-selects the
        # in-flight round's cohort byte-identically
        _flatten("sched", _to_numpy(sched_state), flat)
    meta = {
        "round_idx": int(round_idx),
        "has_opt": server_opt_state is not None,
        "has_algo": algo_state is not None,
        "has_sched": sched_state is not None,
    }
    meta.update(extra_meta or {})
    flat["__meta__"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8
    )
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **flat)
    os.replace(tmp, path + ".npz")
    with open(path + ".json", "w") as f:
        json.dump(meta, f)


def load_checkpoint(
    path: str,
) -> Tuple[dict, int, Optional[np.ndarray], Any, Any, Any]:
    """Returns (global_vars, round_idx, rng, server_opt_state, algo_state,
    sched_state). ``sched_state`` is None for checkpoints written before
    the scheduler slot existed (meta carries no has_sched)."""
    with np.load(path + ".npz") as z:
        flat = {k: z[k] for k in z.files}
    meta = json.loads(flat.pop("__meta__").tobytes().decode("utf-8"))
    rng = flat.pop("rng", None)
    vars_flat = {k[len("vars/"):]: v for k, v in flat.items() if k.startswith("vars/")}
    opt_flat = {k[len("opt/"):]: v for k, v in flat.items() if k.startswith("opt/")}
    algo_flat = {k[len("algo/"):]: v for k, v in flat.items() if k.startswith("algo/")}
    sched_flat = {k[len("sched/"):]: v for k, v in flat.items() if k.startswith("sched/")}
    global_vars = _unflatten(vars_flat)
    opt_state = _unflatten(opt_flat) if meta.get("has_opt") else None
    algo_state = _unflatten(algo_flat) if meta.get("has_algo") else None
    sched_state = _unflatten(sched_flat) if meta.get("has_sched") else None
    return global_vars, meta["round_idx"], rng, opt_state, algo_state, sched_state


def _to_numpy(tree):
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def restore_like(template, loaded_tree):
    """Pour loaded leaves into ``template``'s structure (e.g. a fresh
    ``opt.init(params)`` NamedTuple pytree — the npz round-trip stores
    tuples as lists, so leaf order carries the structure)."""
    import jax

    leaves = jax.tree_util.tree_leaves(loaded_tree)
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)
