"""Metrics logging — wandb-compatible names without the wandb dependency
(ref SURVEY §5: wandb is the reference's metrics backbone; rank-0-only
wandb.init at main_fedavg.py:93-108, wandb.log of Train/Acc, Train/Loss,
Test/Acc, Test/Loss, round from 20+ call sites; CI reads
wandb-summary.json as its oracle, CI-script-fedavg.sh:44).

MetricsLogger keeps the same metric-name schema, appends JSONL rows, and
maintains a ``summary`` (last value per key) written as summary.json — the
drop-in analog of wandb-summary.json, so the reference's
read-summary-and-assert CI pattern ports directly. If wandb is importable
and a run is active, rows are forwarded."""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional


def wandb_init(
    project: str = "fedml_tpu",
    name: Optional[str] = None,
    config: Optional[dict] = None,
):
    """Optional wandb adapter (ref main_fedavg.py:93-108: rank-0 wandb.init
    with run name {fl_algorithm}-r{comm_round}-e{epochs}-lr{lr}): starts a
    run if wandb is importable, returns the run or None. Import-gated — the
    framework never *requires* wandb; MetricsLogger's JSONL/summary.json
    mirror is always written."""
    try:
        import wandb
    except ImportError:
        return None
    return wandb.init(project=project, name=name, config=config or {})


class MetricsLogger:
    def __init__(self, log_dir: Optional[str] = None, use_wandb: bool = False):
        self.log_dir = log_dir
        self.summary: Dict[str, float] = {}
        self.history = []
        self._fh = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._fh = open(os.path.join(log_dir, "metrics.jsonl"), "a")
        self._wandb = None
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb if wandb.run is not None else None
            except ImportError:
                self._wandb = None

    def log(self, row: Dict) -> None:
        row = dict(row)
        row.setdefault("_ts", time.time())
        self.history.append(row)
        self.summary.update(
            {k: v for k, v in row.items() if not k.startswith("_")}
        )
        if self._fh:
            self._fh.write(json.dumps(row) + "\n")
            self._fh.flush()
            with open(os.path.join(self.log_dir, "summary.json"), "w") as f:
                json.dump(self.summary, f)
        if self._wandb:
            self._wandb.log({k: v for k, v in row.items() if not k.startswith("_")})

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
