"""Selection policies + the ClientScheduler driver.

The reference samples cohorts with exactly one rule — ``np.random.seed(
round_idx)`` then ``choice`` without replacement (FedAVGAggregator.py:
80-88). That rule survives here verbatim as the ``uniform`` policy (its
parity is pinned by tests/test_fedavg_oracle.py), and everything else is
the scheduling layer the reference never had:

- ``weighted`` — inclusion probability proportional to local sample
  counts (larger shards carry more of the average; sampling them more
  often reduces aggregate variance at fixed k).
- ``power_of_choice`` — the loss-biased d-choose-k rule of Cho et al.
  2020: draw a candidate set of ``d = ceil(candidate_factor * k)``
  clients (size-weighted), then keep the k with the highest last-known
  local loss. Clients with no known loss rank as +inf, so cold clients
  are explored before the bias kicks in.
- ``straggler_aware`` — uniform over the clients the telemetry
  :class:`~fedml_tpu.telemetry.health.ClientHealthRegistry` does NOT
  currently flag as stragglers (the hook PR 1 shipped for exactly this),
  topping back up from the flagged set only when too few fast clients
  remain.
- ``overprovision`` — a wrapper around any policy that selects
  ``ceil(k * factor)`` clients, so a deadline/quorum round
  (FedConfig.deadline_s/min_clients) still closes with ~k useful uploads
  when some of the cohort drops.

Every policy is **round-keyed and seed-deterministic**: the draw is a
pure function of (seed, round_idx, policy inputs), never of call order or
process state — the vmap simulator and the transport federations must
select byte-identical cohorts from the same config, and a resumed run
must be able to re-derive its in-flight cohort.

Population scale (fedml_tpu/population/, docs/POPULATION.md): at/above
``PopulationConfig.ocohort_threshold`` clients the non-uniform policies
switch to O(cohort) draws — an alias table for ``weighted`` and the
power_of_choice candidate pool, rejection sampling for
``straggler_aware``'s exclusion draw — built once per run from the
:class:`~fedml_tpu.population.PopulationIndex` and never touching all N
per round. The switch is keyed on population size ONLY (identical in
the simulator and every transport, so sim/transport cohort parity is
preserved by construction); below the threshold the legacy exact numpy
draws run byte-for-byte. ``uniform`` stays the reference-parity
round-seeded draw at every scale — its O(N) permutation is the parity
contract itself.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class SelectionContext:
    """Everything a policy may consult beyond (round, k). All optional:
    a policy degrades gracefully (documented per policy) when its signal
    is missing rather than erroring — the transport server, the vmap
    simulator, and bare helpers construct different subsets of this."""

    seed: int = 0
    num_clients: int = 0
    # per-client local dataset sizes, indexed by client id (weighted /
    # power_of_choice candidate draw)
    sample_counts: Optional[np.ndarray] = None
    # last reported local train loss per client id (power_of_choice)
    losses: Optional[Dict[int, float]] = None
    # ClientHealthRegistry-shaped object (straggler_aware); only
    # .straggler_ids() is required
    health: Optional[object] = None
    # population.PopulationIndex for the O(cohort) draws; built lazily
    # from sample_counts at/above ocohort_threshold when absent
    index: Optional[object] = None
    ocohort_threshold: int = 65536


def _population_index(ctx: SelectionContext):
    """The context's PopulationIndex when the O(cohort) sampling paths
    should engage — explicit index, or lazily built from the packed
    counts once the population crosses the threshold. Returns None below
    the threshold (legacy exact draws) or when no counts exist."""
    if ctx.index is not None:
        return ctx.index
    if (
        ctx.sample_counts is not None
        and ctx.num_clients >= ctx.ocohort_threshold
        and len(ctx.sample_counts) == ctx.num_clients
    ):
        from fedml_tpu.population import PopulationIndex

        ctx.index = PopulationIndex(np.asarray(ctx.sample_counts, np.int64))
        return ctx.index
    return None


def _weighted_cohort(ctx: SelectionContext, rng, n: int, size: int) -> np.ndarray:
    """THE size-weighted distinct draw both weighted selection and the
    power_of_choice candidate pool use: the alias table's O(cohort)
    rejection draw at population scale, the legacy exact numpy draw
    below it. Distributionally identical (discarding duplicates from a
    with-replacement categorical stream IS sequential sampling without
    replacement); only the random stream differs, which is why the
    switch is population-keyed, never data-keyed."""
    pop = _population_index(ctx)
    if (
        pop is not None
        and pop.num_clients == n
        and pop.total_samples() > 0
    ):
        return pop.alias_table().draw_distinct(rng, size)
    return _weighted_draw(rng, n, size, _size_probs(ctx))


def _rng(ctx: SelectionContext, round_idx: int, salt: int = 0):
    """The one derivation of a policy's per-round RNG: a SeedSequence over
    (seed, round, salt) — independent of call order, identical across
    processes."""
    return np.random.default_rng([int(ctx.seed) & 0x7FFFFFFF, int(round_idx), int(salt)])


def _size_probs(ctx: SelectionContext) -> Optional[np.ndarray]:
    if ctx.sample_counts is None:
        return None
    c = np.asarray(ctx.sample_counts, np.float64)
    if len(c) != ctx.num_clients or c.sum() <= 0:
        return None
    return c / c.sum()


def _weighted_draw(rng, n: int, size: int, p: Optional[np.ndarray]) -> np.ndarray:
    """``rng.choice(n, size, replace=False, p=p)`` that tolerates
    zero-weight entries: numpy refuses to draw more items than p has
    non-zero entries (a zero-sample client shard — possible under the
    Dirichlet non-IID partitioner — would crash a weighted draw mid-run).
    When the request exceeds the non-zero support, every weighted client
    is taken and the remainder fills uniformly from the zero-weight ones."""
    if p is None:
        return rng.choice(n, size=size, replace=False)
    nz = np.flatnonzero(p)
    if size <= len(nz):
        return rng.choice(n, size=size, replace=False, p=p)
    zeros = np.setdiff1d(np.arange(n), nz)
    fill = rng.choice(zeros, size=size - len(nz), replace=False)
    return np.concatenate([rng.permutation(nz), fill])


class SelectionPolicy:
    """One cohort-selection rule. ``select`` must be a pure function of
    its arguments (round-keyed, seed-deterministic) and return a 1-D
    int array of distinct client ids of length ``min(k, num_clients)``."""

    name = "base"

    def select(self, round_idx: int, k: int, ctx: SelectionContext) -> np.ndarray:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


_POLICIES: Dict[str, Callable[..., SelectionPolicy]] = {}


def register_policy(name: str):
    """Register a policy factory under ``name`` (decorator)."""

    def deco(factory):
        _POLICIES[name] = factory
        return factory

    return deco


def get_policy(name: str, **kw) -> SelectionPolicy:
    try:
        factory = _POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown selection policy {name!r}; registered: "
            f"{sorted(_POLICIES)}"
        ) from None
    return factory(**kw)


@register_policy("uniform")
class UniformPolicy(SelectionPolicy):
    """Reference-parity uniform draw: ``np.random.seed(round_idx)`` then
    ``choice`` without replacement (FedAVGAggregator.py:80-88). NOTE this
    deliberately ignores the run seed — runs with different seeds sample
    the same cohorts, exactly like the reference (pinned by
    tests/test_fedavg_oracle.py::test_client_sampling_parity)."""

    name = "uniform"

    def select(self, round_idx: int, k: int, ctx: SelectionContext) -> np.ndarray:
        n = ctx.num_clients
        if k > n:
            raise ValueError(
                f"client_num_per_round={k} exceeds client_num_in_total={n}"
            )
        if n == k:
            return np.arange(n)
        np.random.seed(round_idx)
        return np.random.choice(range(n), k, replace=False)


@register_policy("weighted")
class WeightedPolicy(SelectionPolicy):
    """Inclusion probability proportional to local sample counts. Falls
    back to a (seeded) uniform draw when the context carries no counts."""

    name = "weighted"

    def select(self, round_idx: int, k: int, ctx: SelectionContext) -> np.ndarray:
        n = ctx.num_clients
        k = min(k, n)
        rng = _rng(ctx, round_idx, salt=1)
        return _weighted_cohort(ctx, rng, n, k)


@register_policy("power_of_choice")
class PowerOfChoicePolicy(SelectionPolicy):
    """Loss-biased d-choose-k (Power-of-Choice, Cho et al. 2020): draw
    ``d = ceil(candidate_factor * k)`` candidates size-weighted, keep the
    k with the highest last-known local loss. Unknown losses rank as +inf
    (cold clients are explored first); ties break on a seeded per-round
    permutation, so the rule stays deterministic given (seed, round,
    loss map)."""

    name = "power_of_choice"

    def __init__(self, candidate_factor: float = 2.0):
        if candidate_factor < 1.0:
            raise ValueError("candidate_factor must be >= 1.0")
        self.candidate_factor = float(candidate_factor)

    def select(self, round_idx: int, k: int, ctx: SelectionContext) -> np.ndarray:
        n = ctx.num_clients
        k = min(k, n)
        d = min(n, max(k, int(math.ceil(self.candidate_factor * k))))
        rng = _rng(ctx, round_idx, salt=2)
        candidates = _weighted_cohort(ctx, rng, n, d)
        losses = ctx.losses or {}
        loss_of = lambda c: losses.get(int(c), math.inf)
        tiebreak = rng.permutation(d)
        order = sorted(
            range(d), key=lambda i: (-loss_of(candidates[i]), tiebreak[i])
        )
        return np.asarray([int(candidates[i]) for i in order[:k]], np.int64)


@register_policy("straggler_aware")
class StragglerAwarePolicy(SelectionPolicy):
    """Uniform over the clients the health registry does not flag as
    stragglers (telemetry.health.ClientHealthRegistry.straggler_ids —
    sliding-window slowest decile AND materially slower than the fleet).
    When fewer than k fast clients exist, the cohort tops back up from
    the flagged set (deterministically, by id) rather than shrinking —
    participation guarantees beat straggler avoidance. With no registry
    attached this is a seeded uniform draw."""

    name = "straggler_aware"

    def select(self, round_idx: int, k: int, ctx: SelectionContext) -> np.ndarray:
        n = ctx.num_clients
        k = min(k, n)
        rng = _rng(ctx, round_idx, salt=3)
        flagged: List[int] = []
        if ctx.health is not None:
            flagged = [c for c in ctx.health.straggler_ids() if c < n]
        if n >= ctx.ocohort_threshold:
            # O(cohort) form: rejection-sample the uniform draw instead
            # of materializing the O(N) eligible set every round (the
            # flagged set is bounded by the health registry's active set)
            from fedml_tpu.population import draw_uniform_distinct

            take = min(k, n - len(flagged))
            sel = draw_uniform_distinct(
                rng, n, take, exclude=np.asarray(flagged, np.int64)
            )
        else:
            eligible = np.setdiff1d(np.arange(n), np.asarray(flagged, np.int64))
            take = min(k, len(eligible))
            sel = rng.choice(eligible, size=take, replace=False) if take else np.empty(0, np.int64)
        if take < k:
            # top up with the least-bad stragglers: slowest last
            by_speed = sorted(
                flagged,
                key=lambda c: (ctx.health.mean_train_s(c) or 0.0, c),
            )
            sel = np.concatenate([sel, np.asarray(by_speed[: k - take], np.int64)])
        return np.sort(sel.astype(np.int64))


class OverprovisionPolicy(SelectionPolicy):
    """Wrap any policy and select ``ceil(k * factor)`` clients (clamped
    to the population) — the deadline/quorum companion: a quorum round
    that expects stragglers/dropouts still closes with ~k useful uploads.
    Registered as ``overprovision`` mostly for introspection; runtimes
    normally compose it via :func:`make_policy`'s factor argument."""

    name = "overprovision"

    def __init__(self, inner: SelectionPolicy, factor: float = 1.0):
        if factor < 1.0:
            raise ValueError("overprovision factor must be >= 1.0")
        self.inner = inner
        self.factor = float(factor)

    def select(self, round_idx: int, k: int, ctx: SelectionContext) -> np.ndarray:
        return self.inner.select(
            round_idx, overprovisioned_k(k, self.factor, ctx.num_clients), ctx
        )


_POLICIES["overprovision"] = lambda inner=None, factor=1.0: OverprovisionPolicy(
    inner or UniformPolicy(), factor
)

#: the policy names a config/CLI may name directly (overprovision is a
#: wrapper, composed via overprovision_factor, not selected by name)
POLICY_NAMES = ("uniform", "weighted", "power_of_choice", "straggler_aware")


def overprovisioned_k(k: int, factor: float, num_clients: int) -> int:
    """ceil(k * factor) clamped to the population — the ONE definition of
    the overprovisioned cohort size, shared by the policy wrapper and by
    the transport runner that must spawn one worker per selected client."""
    return max(1, min(int(num_clients), int(math.ceil(k * float(factor)))))


def make_policy(name: str, overprovision_factor: float = 1.0, **kw) -> SelectionPolicy:
    """Build a registered policy, wrapped in overprovisioning when
    ``overprovision_factor > 1``."""
    inner = get_policy(name, **kw)
    if overprovision_factor and overprovision_factor != 1.0:
        return OverprovisionPolicy(inner, overprovision_factor)
    return inner


def select_clients(
    round_idx: int,
    num_clients: int,
    k: int,
    policy: str = "uniform",
    seed: int = 0,
    sample_counts=None,
    losses=None,
    health=None,
) -> np.ndarray:
    """One-shot selection through the registry — the convenience entry for
    call sites with no scheduler object (fednas, the hierarchical bridge,
    and the back-compat ``fedavg.client_sampling`` shim)."""
    ctx = SelectionContext(
        seed=seed,
        num_clients=int(num_clients),
        sample_counts=sample_counts,
        losses=losses,
        health=health,
    )
    return get_policy(policy).select(int(round_idx), int(k), ctx)


class ClientScheduler:
    """The per-run selection driver every runtime shares: policy + context
    + per-round memo + the telemetry/metrics fan-out.

    - ``select(r)`` is memoized per round, so the fused-chunk planner's
      lookahead, the round loop, and a checkpoint writer all see ONE
      decision per round; the memo (plus the loss map feeding
      power_of_choice) is exactly the state ``state_dict`` persists so a
      resumed run re-selects its in-flight cohort byte-identically.
    - every fresh decision is emitted as a ``select`` telemetry span
      (policy/round/cohort attrs) and forwarded through ``on_select`` —
      the runtimes route that into MetricsLogger so summary.json records
      the selected-client set (the CI oracle contract).
    """

    def __init__(
        self,
        num_clients: int,
        k: int,
        policy: str = "uniform",
        seed: int = 0,
        overprovision_factor: float = 1.0,
        sample_counts: Optional[Sequence[int]] = None,
        health: Optional[object] = None,
        tracer: Optional[object] = None,
        on_select: Optional[Callable[[int, np.ndarray], None]] = None,
        memoize: bool = True,
        index: Optional[object] = None,
        ocohort_threshold: int = 65536,
        loss_map_capacity: int = 65536,
        selection_memo_rounds: int = 64,
    ):
        from fedml_tpu.population import BoundedLossMap

        self.num_clients = int(num_clients)
        self.k = int(k)
        self.policy_name = policy
        self.overprovision_factor = float(overprovision_factor)
        self._policy = make_policy(policy, overprovision_factor)
        self._ctx = SelectionContext(
            seed=int(seed),
            num_clients=self.num_clients,
            sample_counts=(
                np.asarray(sample_counts, np.int64)
                if sample_counts is not None
                else None
            ),
            # bounded: the power_of_choice bias map may never grow O(N)
            # (it is the "sched" checkpoint slot — an unbounded dict
            # over ever-seen clients at 1M clients IS the checkpoint);
            # a missing entry already means "cold client, explore"
            losses=BoundedLossMap(loss_map_capacity),
            health=health,
            index=index,
            ocohort_threshold=int(ocohort_threshold),
        )
        self._memo_rounds = int(selection_memo_rounds)
        self._tracer = tracer
        self._on_select = on_select
        self._memoize = bool(memoize)
        self._selections: Dict[int, np.ndarray] = {}

    @classmethod
    def from_config(
        cls, config, num_clients: int, data=None, log_fn=None, **kw
    ) -> "ClientScheduler":
        """Build from a RunConfig (FedConfig.selection /
        .overprovision_factor / .client_num_per_round + RunConfig.seed).

        ``data`` (a FederatedDataset) derives the weighted-policy sample
        counts — used only when its client count matches the federation's
        (a transport server may be configured against a larger population
        than the dataset it evaluates with). ``log_fn`` installs the
        standard on_select forwarding (the summary.json
        ``scheduler/policy``/``scheduler/selected`` row) — ONE definition
        of both, so the sim/transport/fedbuff runtimes cannot drift."""
        policy = getattr(config.fed, "selection", "uniform")
        pop_cfg = getattr(config, "population", None)
        if pop_cfg is not None:
            kw.setdefault("ocohort_threshold", pop_cfg.ocohort_threshold)
            kw.setdefault("loss_map_capacity", pop_cfg.loss_map_capacity)
            kw.setdefault(
                "selection_memo_rounds", pop_cfg.selection_memo_rounds
            )
        if "sample_counts" not in kw and data is not None and (
            data.num_clients == num_clients
        ):
            # vectorized property (np.diff over the mmap store's offsets;
            # one build-time pass for list-backed datasets) — never the
            # per-client Python len() loop at 1M clients
            kw["sample_counts"] = np.asarray(
                data.train_sample_counts, np.int64
            )
            if (
                "index" not in kw
                and num_clients >= kw.get("ocohort_threshold", 65536)
            ):
                # build the packed population index ONCE here (O(N),
                # build time) so every runtime sharing this config —
                # simulator, transports, fedbuff — engages the identical
                # O(cohort) draws (cohort-parity by construction)
                from fedml_tpu.population import PopulationIndex

                kw["index"] = PopulationIndex.from_counts(
                    kw["sample_counts"],
                    path=(pop_cfg.index_dir or None) if pop_cfg else None,
                    mmap_threshold_bytes=(
                        pop_cfg.index_mmap_bytes if pop_cfg else 64 << 20
                    ),
                )
        if "on_select" not in kw and log_fn is not None:
            kw["on_select"] = lambda r, sel: log_fn(
                {
                    "round": int(r),
                    "scheduler/policy": policy,
                    "scheduler/selected": [int(c) for c in sel],
                }
            )
        return cls(
            num_clients=num_clients,
            k=config.fed.client_num_per_round,
            policy=policy,
            seed=config.seed,
            overprovision_factor=getattr(config.fed, "overprovision_factor", 1.0),
            **kw,
        )

    def cohort_size(self) -> int:
        """Clients selected per round after overprovisioning — the worker
        count a transport runner must spawn."""
        return overprovisioned_k(
            self.k, self.overprovision_factor, self.num_clients
        )

    def select(self, round_idx: int, k: Optional[int] = None) -> np.ndarray:
        """This round's cohort. ``k`` overrides the configured size
        verbatim (no overprovision rescale — the transport server passes
        its already-provisioned worker count)."""
        r = int(round_idx)
        if self._memoize and r in self._selections:
            return self._selections[r]
        if k is None:
            sel = self._policy.select(r, self.k, self._ctx)
        else:
            # explicit k: bypass the overprovision wrapper (k is final)
            inner = getattr(self._policy, "inner", self._policy)
            sel = inner.select(r, int(k), self._ctx)
        sel = np.asarray(sel, np.int64)
        if self._memoize:
            self._selections[r] = sel
            # the LIVE memo is bounded too, not just the checkpointed
            # one: a continuous serve-layer session runs rounds
            # indefinitely, and an unbounded per-round dict is exactly
            # the growth class the population runtime removes. Evicted
            # rounds re-derive as pure functions of (seed, round) — the
            # same property state_dict's bound already relies on. The
            # floor keeps the fused chunk planner's lookahead and the
            # short-run test contracts (full-run selections()) intact.
            cap = max(self._memo_rounds, 64)
            while len(self._selections) > cap:
                del self._selections[next(iter(self._selections))]
        if self._tracer is not None:
            with self._tracer.span(
                "select",
                round=r,
                policy=self.policy_name,
                clients=int(len(sel)),
            ):
                pass
        if self._on_select is not None:
            self._on_select(r, sel)
        return sel

    @property
    def wants_client_losses(self) -> bool:
        """True when the active policy biases on per-client losses
        (power_of_choice, possibly overprovision-wrapped) — the signal
        the vmap round program's ``client_loss_sum``/``client_count``
        vectors exist to feed (FedAvgAPI._report_client_losses)."""
        inner = getattr(self._policy, "inner", self._policy)
        return isinstance(inner, PowerOfChoicePolicy)

    def report_loss(self, client_id: int, loss: float) -> None:
        """Feed a client's last observed local train loss
        (power_of_choice's bias signal). Any runtime may call this with
        whatever loss signal it has — true per-client loss on the
        transports, the cohort mean in the vmap simulator. The vmap
        simulator upgrades to TRUE per-client losses when
        :attr:`wants_client_losses` (sim/transport parity for
        power_of_choice)."""
        if loss is None or not np.isfinite(loss):
            return
        self._ctx.losses[int(client_id)] = float(loss)

    def selections(self) -> Dict[int, List[int]]:
        """Memoized decisions so far, JSON-ready ({round: [ids]}) — the
        most recent ``max(selection_memo_rounds, 64)`` rounds (the live
        memo is bounded; see :meth:`select`)."""
        return {r: [int(c) for c in sel] for r, sel in sorted(self._selections.items())}

    # -- checkpoint support (utils/checkpoint.py "sched" slot) --
    def state_dict(self) -> dict:
        """Pytree of numpy arrays (checkpoint-flattenable): the per-round
        selection memo + the loss map. Enough to re-select the in-flight
        round byte-identically after a resume — policies are otherwise
        pure functions of (seed, round).

        BOUNDED by construction (population-scale checkpoint contract,
        pinned by tests/test_population.py): the loss map is a
        BoundedLossMap (at most ``loss_map_capacity`` entries, never
        O(N) at 1M clients), and only the most recent
        ``selection_memo_rounds`` rounds' selections persist — a resume
        only ever re-derives its in-flight round, and every policy is a
        pure function of (seed, round) beyond that."""
        rounds = sorted(self._selections)[-self._memo_rounds:]
        loss_ids = sorted(self._ctx.losses)
        return {
            "rounds": np.asarray(rounds, np.int64),
            "selections": [
                np.asarray(self._selections[r], np.int64) for r in rounds
            ],
            "loss_ids": np.asarray(loss_ids, np.int64),
            "loss_vals": np.asarray(
                [self._ctx.losses.get(i) for i in loss_ids], np.float64
            ),
        }

    def load_state_dict(self, state: dict) -> None:
        from fedml_tpu.population import BoundedLossMap

        rounds = [int(r) for r in np.asarray(state["rounds"]).ravel()]
        self._selections = {
            r: np.asarray(sel, np.int64)
            for r, sel in zip(rounds, state["selections"])
        }
        ids = np.asarray(state["loss_ids"]).ravel()
        vals = np.asarray(state["loss_vals"]).ravel()
        losses = BoundedLossMap(self._ctx.losses.capacity)
        for i, v in zip(ids, vals):
            losses[int(i)] = float(v)
        self._ctx.losses = losses
