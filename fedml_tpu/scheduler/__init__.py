"""Client scheduling & fault-injection runtime — beyond the reference,
whose only participation logic is one uniform round-seeded draw
(FedAVGAggregator.py:80-88) with "no straggler mitigation, no
client-dropout tolerance" (SURVEY §5).

Two halves:

- :mod:`fedml_tpu.scheduler.policies` — pluggable cohort selection behind
  one :class:`SelectionPolicy` interface with a registry (``uniform``,
  ``weighted``, ``power_of_choice``, ``straggler_aware``) plus an
  ``overprovision`` wrapper for deadline/quorum rounds, and the
  :class:`ClientScheduler` driver every runtime shares. Selection is
  round-keyed and seed-deterministic, so the vmap simulator and the
  transport federations pick byte-identical cohorts from the same config
  (a test contract, tests/test_scheduler.py).
- :mod:`fedml_tpu.scheduler.faults` — a deterministic fault-injection
  harness (:class:`FaultPlan`: per-client dropout probability, slowdown,
  crash-at-round, flaky upload) that wraps the client train path so the
  deadline/quorum recovery machinery, the FedBuff staleness path, and the
  transports can be exercised on purpose in tests/CI instead of by
  wall-clock luck.

Stdlib + numpy only — importable before (and without) jax, like
telemetry; scheduling must never add a hot-path dependency."""

from fedml_tpu.scheduler.faults import (
    DEVICE_PROFILES,
    DeviceProfile,
    FaultDecision,
    FaultInjector,
    FaultPlan,
    FaultTrace,
)
from fedml_tpu.scheduler.policies import (
    POLICY_NAMES,
    ClientScheduler,
    OverprovisionPolicy,
    SelectionContext,
    SelectionPolicy,
    get_policy,
    make_policy,
    overprovisioned_k,
    register_policy,
    select_clients,
)

__all__ = [
    "DEVICE_PROFILES",
    "POLICY_NAMES",
    "ClientScheduler",
    "DeviceProfile",
    "FaultDecision",
    "FaultInjector",
    "FaultPlan",
    "FaultTrace",
    "OverprovisionPolicy",
    "SelectionContext",
    "SelectionPolicy",
    "get_policy",
    "make_policy",
    "overprovisioned_k",
    "register_policy",
    "select_clients",
]
