"""Deterministic fault injection for federated runs.

The deadline/quorum recovery paths (fedavg_transport.py), the FedBuff
staleness machinery, and the loopback/shm/gRPC transports all exist to
tolerate clients that are slow, flaky, or gone — but until now the only
way to exercise them was wall-clock luck (a sleep in a test, a real
straggler in production). A :class:`FaultPlan` makes client misbehavior a
config input: per-client dropout probability, a fixed slowdown, a
crash-at-round, and a flaky (duplicated) upload, every decision a pure
function of ``(plan seed, client id, round)`` so the same plan injects
the same faults in every run, process, and resumed continuation.

JSON schema (CLI ``--fault_plan`` accepts the inline document or a path
to a file containing it)::

    {
      "seed": 0,                      # fault RNG seed (default 0)
      "default": {                    # spec applied to unlisted clients
        "dropout_p": 0.0,             # P(skip this round's upload)
        "slowdown_s": 0.0,            # sleep this long around training
        "crash_at_round": null,       # from this round on: silent forever
        "flaky_upload_p": 0.0         # P(upload delivered twice)
      },
      "clients": {"3": {"dropout_p": 0.5}, ...}   # per-client overrides
    }

Semantics by runtime:

- **sync transports** (loopback/shm/grpc/mqtt): ``dropout`` — the client
  skips training and never uploads that round (the server's
  deadline/quorum path absorbs it; sync runs therefore REQUIRE
  ``deadline_s > 0`` when the plan can drop). ``crash_at_round`` — the
  CLIENT is silent in every round that samples it from that round on
  (the worker slot stays alive: the sampler re-assigns clients to
  workers each round, and faults follow the client). ``slowdown_s`` —
  sleep around local training (drives the straggler detector and
  deadline races). ``flaky_upload`` — the upload is sent twice
  (at-least-once retry double-delivery; exercises the sync server's
  same-slot overwrite).
- **FedBuff**: faults are per assignment (dispatch tag). A dropped or
  crashed assignment is DECLINED (an empty ``ARG_DECLINED`` reply) and
  the server immediately re-dispatches, so the worker fleet never
  shrinks and the delta buffer keeps filling; ``flaky_upload``
  double-sends the delta, exercising the at-least-once dedupe.
- **vmap/mesh simulators**: the cohort trains as one jitted program, so
  only participation faults apply — ``dropout``/``crash`` remove the
  client from the round's cohort before batching (at least one survivor
  is kept so the round stays well-formed); timing faults are ignored.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class ClientFaultSpec:
    dropout_p: float = 0.0
    slowdown_s: float = 0.0
    crash_at_round: Optional[int] = None
    flaky_upload_p: float = 0.0

    def validate(self, who: str) -> None:
        if not 0.0 <= self.dropout_p <= 1.0:
            raise ValueError(f"{who}: dropout_p must be in [0, 1]")
        if not 0.0 <= self.flaky_upload_p <= 1.0:
            raise ValueError(f"{who}: flaky_upload_p must be in [0, 1]")
        if self.slowdown_s < 0:
            raise ValueError(f"{who}: slowdown_s must be >= 0")
        if self.crash_at_round is not None and self.crash_at_round < 0:
            raise ValueError(f"{who}: crash_at_round must be >= 0")


@dataclasses.dataclass(frozen=True)
class FaultDecision:
    """What happens to (client, round): at most one participation fault
    (crashed wins over drop) plus independent timing faults."""

    crashed: bool = False
    drop: bool = False
    slowdown_s: float = 0.0
    flaky: bool = False

    @property
    def participates(self) -> bool:
        return not (self.crashed or self.drop)


_SPEC_KEYS = {f.name for f in dataclasses.fields(ClientFaultSpec)}


def _parse_spec(doc: dict, who: str) -> ClientFaultSpec:
    unknown = set(doc) - _SPEC_KEYS
    if unknown:
        raise ValueError(
            f"{who}: unknown fault spec keys {sorted(unknown)} "
            f"(known: {sorted(_SPEC_KEYS)})"
        )
    spec = ClientFaultSpec(**doc)
    spec.validate(who)
    return spec


class FaultPlan:
    """Per-client fault specs + the deterministic per-round coin flips."""

    def __init__(
        self,
        clients: Optional[Dict[int, ClientFaultSpec]] = None,
        default: Optional[ClientFaultSpec] = None,
        seed: int = 0,
    ):
        self.clients = {int(c): s for c, s in (clients or {}).items()}
        self.default = default or ClientFaultSpec()
        self.seed = int(seed)

    # -- construction --
    @classmethod
    def from_json(cls, doc: dict) -> "FaultPlan":
        unknown = set(doc) - {"seed", "default", "clients"}
        if unknown:
            raise ValueError(
                f"fault plan: unknown top-level keys {sorted(unknown)} "
                "(known: seed, default, clients)"
            )
        default = _parse_spec(doc.get("default", {}), "fault plan default")
        clients = {
            int(cid): _parse_spec(spec, f"fault plan client {cid}")
            for cid, spec in (doc.get("clients") or {}).items()
        }
        return cls(clients=clients, default=default, seed=doc.get("seed", 0))

    @classmethod
    def from_spec(cls, spec: str) -> Optional["FaultPlan"]:
        """Parse the CLI/config string: inline JSON (starts with '{') or a
        path to a JSON file; ''/None means no faults."""
        if not spec:
            return None
        text = spec.strip()
        if not text.startswith("{"):
            if not os.path.exists(text):
                raise ValueError(
                    f"fault plan {text!r} is neither inline JSON nor an "
                    "existing file"
                )
            with open(text) as f:
                text = f.read()
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"fault plan is not valid JSON: {e}") from e
        return cls.from_json(doc)

    @classmethod
    def from_config(cls, config) -> Optional["FaultPlan"]:
        return cls.from_spec(getattr(config.fed, "fault_plan", ""))

    # -- queries --
    def spec_for(self, client_id: int) -> ClientFaultSpec:
        return self.clients.get(int(client_id), self.default)

    def has_participation_faults(self) -> bool:
        """True when the plan can remove an upload (dropout or crash) —
        sync transport runs then need deadline/quorum rounds to not hang."""
        return any(
            s.dropout_p > 0 or s.crash_at_round is not None
            for s in list(self.clients.values()) + [self.default]
        )

    def decide(
        self, client_id: int, round_idx: int, crash_round: Optional[int] = None
    ) -> FaultDecision:
        """The (client, round) fault decision — pure in (seed, client,
        round): one SeedSequence draw stream per pair, probabilities in a
        fixed order, so every process and every re-run agrees.

        ``crash_round`` overrides the value ``crash_at_round`` is compared
        against: FedBuff keys its probabilistic draws by the per-assignment
        dispatch tag (unique, unbounded), which would cross any
        ``crash_at_round`` threshold within a few dozen dispatches — it
        passes the server MODEL VERSION here instead (the async analog of
        a training round)."""
        spec = self.spec_for(client_id)
        cr = int(round_idx) if crash_round is None else int(crash_round)
        crashed = spec.crash_at_round is not None and cr >= spec.crash_at_round
        drop = flaky = False
        if spec.dropout_p > 0 or spec.flaky_upload_p > 0:
            rng = np.random.default_rng(
                [self.seed & 0x7FFFFFFF, int(client_id), int(round_idx) & 0x7FFFFFFF]
            )
            drop = bool(rng.random() < spec.dropout_p)
            flaky = bool(rng.random() < spec.flaky_upload_p)
        return FaultDecision(
            crashed=crashed,
            drop=drop and not crashed,
            slowdown_s=spec.slowdown_s,
            flaky=flaky and not crashed,
        )

    def to_json(self) -> dict:
        return {
            "seed": self.seed,
            "default": dataclasses.asdict(self.default),
            "clients": {
                str(c): dataclasses.asdict(s) for c, s in sorted(self.clients.items())
            },
        }


_FAULT_KINDS = ("dropout", "crash", "slowdown", "flaky")
# MetricsLogger key per kind (summary.json schema, asserted by CI)
_FAULT_ROW_KEYS = {
    "dropout": "faults/dropouts",
    "crash": "faults/crashes",
    "slowdown": "faults/slowdowns",
    "flaky": "faults/flaky_uploads",
}


class FaultInjector:
    """The runtime half: applies a plan's decisions and accounts for every
    injected fault (thread-safe — transport clients run in threads).

    One injector is shared across a federation's client actors so the
    counters describe the RUN; ``summary_row()`` is forwarded into
    MetricsLogger at the end (summary.json records the injected faults —
    the CI oracle contract), each event is emitted as a ``fault``
    telemetry span, and — when the server's health registry is reachable
    (in-process federations) — recorded per client via
    ``ClientHealthRegistry.observe_fault``."""

    def __init__(
        self,
        plan: FaultPlan,
        health: Optional[object] = None,
        tracer: Optional[object] = None,
    ):
        self.plan = plan
        self.health = health
        self._tracer = tracer
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {k: 0 for k in _FAULT_KINDS}
        self._crash_logged: set = set()

    @classmethod
    def from_config(
        cls, config, health=None, tracer=None
    ) -> Optional["FaultInjector"]:
        plan = FaultPlan.from_config(config)
        if plan is None:
            return None
        return cls(plan, health=health, tracer=tracer)

    def decide(
        self, client_id: int, round_idx: int, crash_round: Optional[int] = None
    ) -> FaultDecision:
        return self.plan.decide(client_id, round_idx, crash_round=crash_round)

    def record(self, client_id: int, round_idx: int, kind: str) -> None:
        assert kind in _FAULT_KINDS, kind
        with self._lock:
            if kind == "crash":
                # a crash is one event per client, not one per ignored round
                if client_id in self._crash_logged:
                    return
                self._crash_logged.add(client_id)
            self.counters[kind] += 1
        if self._tracer is not None:
            with self._tracer.span(
                "fault", client=int(client_id), round=int(round_idx), kind=kind
            ):
                pass
        if self.health is not None and hasattr(self.health, "observe_fault"):
            self.health.observe_fault(client_id, round_idx, kind)

    def total(self) -> int:
        with self._lock:
            return sum(self.counters.values())

    def summary_row(self) -> dict:
        """Flat MetricsLogger row of the run's injected-fault counts."""
        with self._lock:
            row = {
                _FAULT_ROW_KEYS[k]: int(v) for k, v in self.counters.items()
            }
            row["faults/total"] = sum(self.counters.values())
        return row
