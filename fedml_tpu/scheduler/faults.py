"""Deterministic fault injection for federated runs.

The deadline/quorum recovery paths (fedavg_transport.py), the FedBuff
staleness machinery, and the loopback/shm/gRPC transports all exist to
tolerate clients that are slow, flaky, or gone — but until now the only
way to exercise them was wall-clock luck (a sleep in a test, a real
straggler in production). A :class:`FaultPlan` makes client misbehavior a
config input: per-client dropout probability, a fixed slowdown, a
crash-at-round, and a flaky (duplicated) upload, every decision a pure
function of ``(plan seed, client id, round)`` so the same plan injects
the same faults in every run, process, and resumed continuation.

JSON schema (CLI ``--fault_plan`` accepts the inline document or a path
to a file containing it)::

    {
      "seed": 0,                      # fault RNG seed (default 0)
      "default": {                    # spec applied to unlisted clients
        "dropout_p": 0.0,             # P(skip this round's upload)
        "slowdown_s": 0.0,            # sleep this long around training
        "crash_at_round": null,       # from this round on: silent forever
        "flaky_upload_p": 0.0         # P(upload delivered twice)
      },
      "clients": {"3": {"dropout_p": 0.5}, ...}   # per-client overrides
    }

Semantics by runtime:

- **sync transports** (loopback/shm/grpc/mqtt): ``dropout`` — the client
  skips training and never uploads that round (the server's
  deadline/quorum path absorbs it; sync runs therefore REQUIRE
  ``deadline_s > 0`` when the plan can drop). ``crash_at_round`` — the
  CLIENT is silent in every round that samples it from that round on
  (the worker slot stays alive: the sampler re-assigns clients to
  workers each round, and faults follow the client). ``slowdown_s`` —
  sleep around local training (drives the straggler detector and
  deadline races). ``flaky_upload`` — the upload is sent twice
  (at-least-once retry double-delivery; exercises the sync server's
  same-slot overwrite).
- **FedBuff**: faults are per assignment (dispatch tag). A dropped or
  crashed assignment is DECLINED (an empty ``ARG_DECLINED`` reply) and
  the server immediately re-dispatches, so the worker fleet never
  shrinks and the delta buffer keeps filling; ``flaky_upload``
  double-sends the delta, exercising the at-least-once dedupe.
- **vmap/mesh simulators**: the cohort trains as one jitted program, so
  only participation faults apply — ``dropout``/``crash`` remove the
  client from the round's cohort before batching (at least one survivor
  is kept so the round stays well-formed); timing faults are ignored.

Beyond the probabilistic specs, three ways to describe a fleet once and
replay it forever (the record → replay → survive loop):

- **Device profiles** (``DeviceProfile`` / ``DEVICE_PROFILES``): named
  speed/memory tiers whose characteristics drive the fault spec — a slow
  tier adds per-round latency (``slowdown_s``), a memory-starved tier
  drops more often (background OOM kills). A client spec may be a
  profile NAME, the plan may define custom ``"profiles"``, and a
  ``"fleet"`` shorthand assigns tiers to a whole population
  deterministically by the plan seed.
- **Scripted events** (``"scripted"``): exact per-(client, round) fault
  events instead of coin flips — what :meth:`FaultPlan.from_trace`
  emits, so a recorded fleet replays byte-identically.
- **Fault traces** (:class:`FaultTrace`): the observed record the
  server-side :class:`~fedml_tpu.telemetry.health.ClientHealthRegistry`
  exports (per-client fault events with rounds + magnitudes, train-time
  stats). ``--fault_plan trace:<path>`` replays one.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class ClientFaultSpec:
    dropout_p: float = 0.0
    slowdown_s: float = 0.0
    crash_at_round: Optional[int] = None
    flaky_upload_p: float = 0.0

    def validate(self, who: str) -> None:
        if not 0.0 <= self.dropout_p <= 1.0:
            raise ValueError(f"{who}: dropout_p must be in [0, 1]")
        if not 0.0 <= self.flaky_upload_p <= 1.0:
            raise ValueError(f"{who}: flaky_upload_p must be in [0, 1]")
        if self.slowdown_s < 0:
            raise ValueError(f"{who}: slowdown_s must be >= 0")
        if self.crash_at_round is not None and self.crash_at_round < 0:
            raise ValueError(f"{who}: crash_at_round must be >= 0")


@dataclasses.dataclass(frozen=True)
class FaultDecision:
    """What happens to (client, round): at most one participation fault
    (crashed wins over drop) plus independent timing faults."""

    crashed: bool = False
    drop: bool = False
    slowdown_s: float = 0.0
    flaky: bool = False

    @property
    def participates(self) -> bool:
        return not (self.crashed or self.drop)


@dataclasses.dataclass(frozen=True)
class DeviceProfile:
    """One heterogeneous device class — speed/memory tiers described once
    and reused across plans. The tier's characteristics map onto the
    fault spec: a slow compute tier contributes per-round latency
    (``slowdown_s``, driving the straggler detector and deadline races),
    a memory-starved tier gets background-killed more often (higher
    ``dropout_p``) and re-sends more (``flaky_upload_p``)."""

    name: str
    slowdown_s: float = 0.0
    dropout_p: float = 0.0
    flaky_upload_p: float = 0.0
    crash_at_round: Optional[int] = None

    def spec(self) -> ClientFaultSpec:
        return ClientFaultSpec(
            dropout_p=self.dropout_p,
            slowdown_s=self.slowdown_s,
            crash_at_round=self.crash_at_round,
            flaky_upload_p=self.flaky_upload_p,
        )


# Built-in tiers (overridable / extendable via a plan's "profiles" key).
# Magnitudes are sized for CI-scale rounds (sub-second local training);
# scale slowdown_s up for real workloads.
DEVICE_PROFILES: Dict[str, DeviceProfile] = {
    p.name: p
    for p in (
        DeviceProfile("server_grade"),
        DeviceProfile("highend_phone", slowdown_s=0.02, dropout_p=0.01),
        DeviceProfile("midrange_phone", slowdown_s=0.08, dropout_p=0.05),
        DeviceProfile(
            "lowend_phone",
            slowdown_s=0.25, dropout_p=0.12, flaky_upload_p=0.05,
        ),
    )
}


_SPEC_KEYS = {f.name for f in dataclasses.fields(ClientFaultSpec)}


def _parse_spec(doc, who: str, profiles=None) -> ClientFaultSpec:
    """Parse one client spec: a plain field dict, a profile NAME, or
    ``{"profile": name, <field overrides>}``."""
    profiles = profiles or DEVICE_PROFILES
    if isinstance(doc, str):
        doc = {"profile": doc}
    if not isinstance(doc, dict):
        raise ValueError(
            f"{who}: a fault spec is a dict of fields or a profile name, "
            f"got {doc!r}"
        )
    doc = dict(doc)
    base: Dict[str, object] = {}
    prof_name = doc.pop("profile", None)
    if prof_name is not None:
        prof = profiles.get(str(prof_name))
        if prof is None:
            raise ValueError(
                f"{who}: unknown device profile {prof_name!r} "
                f"(known: {sorted(profiles)})"
            )
        base = dataclasses.asdict(prof.spec())
    unknown = set(doc) - _SPEC_KEYS
    if unknown:
        raise ValueError(
            f"{who}: unknown fault spec keys {sorted(unknown)} "
            f"(known: {sorted(_SPEC_KEYS)}, plus 'profile')"
        )
    base.update(doc)
    spec = ClientFaultSpec(**base)
    spec.validate(who)
    return spec


def _parse_profiles(doc: dict) -> Dict[str, DeviceProfile]:
    """The plan's custom tier definitions, layered over the built-ins."""
    out = dict(DEVICE_PROFILES)
    for name, fields in (doc or {}).items():
        # fields may be a dict, a built-in profile NAME (alias), or
        # {"profile": base, overrides} — _parse_spec handles all three
        spec = _parse_spec(fields, f"device profile {name!r}")
        out[str(name)] = DeviceProfile(
            name=str(name),
            slowdown_s=spec.slowdown_s,
            dropout_p=spec.dropout_p,
            flaky_upload_p=spec.flaky_upload_p,
            crash_at_round=spec.crash_at_round,
        )
    return out


def _assign_fleet(
    fleet: Dict[str, float],
    num_clients: int,
    seed: int,
    profiles: Dict[str, DeviceProfile],
) -> Dict[int, str]:
    """Deterministically assign every client id a profile name from
    ``{profile: weight}`` (weights are fractions or counts — normalized,
    apportioned by largest remainder). Pure in (fleet, num_clients,
    seed): the same fleet description always materializes the same
    per-client tiers, so a fleet is described once and replayed forever."""
    if num_clients <= 0:
        raise ValueError("fleet plans need a positive num_clients")
    names = sorted(fleet)
    for n in names:
        if n not in profiles:
            raise ValueError(
                f"fleet references unknown profile {n!r} "
                f"(known: {sorted(profiles)})"
            )
    weights = np.asarray([float(fleet[n]) for n in names], dtype=np.float64)
    if (weights < 0).any() or weights.sum() <= 0:
        raise ValueError("fleet weights must be non-negative and sum > 0")
    exact = weights / weights.sum() * num_clients
    counts = np.floor(exact).astype(int)
    # largest remainder fills the shortfall; ties break by name order
    for i in np.argsort(-(exact - counts), kind="stable")[: num_clients - counts.sum()]:
        counts[i] += 1
    ids = np.arange(num_clients)
    rng = np.random.default_rng([int(seed) & 0x7FFFFFFF, 0xF1EE7])
    rng.shuffle(ids)
    out: Dict[int, str] = {}
    pos = 0
    for name, c in zip(names, counts):
        for cid in ids[pos : pos + c]:
            out[int(cid)] = name
        pos += c
    return out


_SCRIPT_EVENT_KEYS = {"drop", "flaky", "slowdown_s"}


class FaultPlan:
    """Per-client fault specs + the deterministic per-round coin flips.

    ``scripted`` replaces the coin flips for the clients it names with
    exact per-round events — ``{client: {round: {"drop": bool, "flaky":
    bool, "slowdown_s": float}}}`` — which is how a recorded
    :class:`FaultTrace` replays byte-identically (crash stays on the
    spec's ``crash_at_round``: it is already deterministic)."""

    def __init__(
        self,
        clients: Optional[Dict[int, ClientFaultSpec]] = None,
        default: Optional[ClientFaultSpec] = None,
        seed: int = 0,
        scripted: Optional[Dict[int, Dict[int, dict]]] = None,
        tiers: Optional[Dict[int, str]] = None,
    ):
        self.clients = {int(c): s for c, s in (clients or {}).items()}
        self.default = default or ClientFaultSpec()
        self.seed = int(seed)
        self.scripted = {
            int(c): {int(r): dict(ev) for r, ev in rounds.items()}
            for c, rounds in (scripted or {}).items()
        }
        # client -> DeviceProfile tier NAME: the attribution key the
        # telemetry beacons carry (telemetry/wire.py) and the fleet
        # aggregator groups by. Populated by from_json from fleet
        # assignments and named-profile client entries; spec parsing used
        # to discard the names.
        self.tiers = {int(c): str(t) for c, t in (tiers or {}).items()}

    # -- construction --
    @classmethod
    def from_json(cls, doc: dict) -> "FaultPlan":
        unknown = set(doc) - {
            "seed", "default", "clients", "profiles", "fleet",
            "num_clients", "scripted", "tiers",
        }
        if unknown:
            raise ValueError(
                f"fault plan: unknown top-level keys {sorted(unknown)} "
                "(known: seed, default, clients, profiles, fleet, "
                "num_clients, scripted, tiers)"
            )
        seed = doc.get("seed", 0)
        profiles = _parse_profiles(doc.get("profiles"))
        clients = {}
        tiers: Dict[int, str] = {}
        if doc.get("fleet"):
            # the whole-population shorthand: {"fleet": {tier: weight},
            # "num_clients": N} — per-client tiers derive deterministically
            # from the plan seed, then explicit "clients" entries override
            assignment = _assign_fleet(
                doc["fleet"], int(doc.get("num_clients", 0)), seed, profiles
            )
            clients = {
                cid: profiles[name].spec() for cid, name in assignment.items()
            }
            tiers.update(assignment)
        elif "num_clients" in doc:
            raise ValueError("fault plan: num_clients only makes sense with fleet")
        for cid, spec in (doc.get("clients") or {}).items():
            clients[int(cid)] = _parse_spec(
                spec, f"fault plan client {cid}", profiles=profiles
            )
            # keep the tier NAME when the entry references a profile
            # (a plain string alias or {"profile": name, ...overrides})
            name = (
                spec if isinstance(spec, str)
                else spec.get("profile") if isinstance(spec, dict)
                else None
            )
            if name is not None:
                tiers[int(cid)] = str(name)
        # explicit tiers (e.g. a to_json round-trip) take precedence
        for cid, name in (doc.get("tiers") or {}).items():
            tiers[int(cid)] = str(name)
        default = _parse_spec(
            doc.get("default", {}), "fault plan default", profiles=profiles
        )
        scripted = {}
        for cid, rounds in (doc.get("scripted") or {}).items():
            per = {}
            for r, ev in rounds.items():
                unknown_ev = set(ev) - _SCRIPT_EVENT_KEYS
                if unknown_ev:
                    raise ValueError(
                        f"fault plan scripted[{cid}][{r}]: unknown keys "
                        f"{sorted(unknown_ev)} (known: {sorted(_SCRIPT_EVENT_KEYS)})"
                    )
                per[int(r)] = {
                    "drop": bool(ev.get("drop", False)),
                    "flaky": bool(ev.get("flaky", False)),
                    "slowdown_s": float(ev.get("slowdown_s", 0.0)),
                }
            scripted[int(cid)] = per
        return cls(
            clients=clients, default=default, seed=seed, scripted=scripted,
            tiers=tiers,
        )

    @classmethod
    def from_spec(cls, spec: str) -> Optional["FaultPlan"]:
        """Parse the CLI/config string: inline JSON (starts with '{'),
        ``trace:<path>`` (replay a recorded :class:`FaultTrace`
        byte-identically), or a path to a JSON plan file; ''/None means
        no faults."""
        if not spec:
            return None
        text = spec.strip()
        if text.startswith("trace:"):
            return cls.from_trace(FaultTrace.load(text[len("trace:"):]))
        if not text.startswith("{"):
            if not os.path.exists(text):
                raise ValueError(
                    f"fault plan {text!r} is neither inline JSON, a "
                    "trace:<path> reference, nor an existing file"
                )
            with open(text) as f:
                text = f.read()
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"fault plan is not valid JSON: {e}") from e
        return cls.from_json(doc)

    @classmethod
    def from_config(cls, config) -> Optional["FaultPlan"]:
        return cls.from_spec(getattr(config.fed, "fault_plan", ""))

    @classmethod
    def from_trace(cls, trace: "FaultTrace", seed: int = 0) -> "FaultPlan":
        """A plan that REPLAYS an observed trace exactly: every recorded
        (client, round) dropout/flaky/slowdown event becomes a scripted
        event (slowdowns at their recorded magnitude), a recorded crash
        becomes ``crash_at_round`` at its first observed round. Replayed
        against the same run config (same selection seed → same cohorts)
        the injected ``faults/*`` summary rows are byte-identical to the
        recorded run's — the ci.sh chaos gate."""
        clients: Dict[int, ClientFaultSpec] = {}
        scripted: Dict[int, Dict[int, dict]] = {}
        for cid, rec in trace.clients.items():
            if not rec.get("trace_complete", True):
                raise ValueError(
                    f"fault trace for client {cid} is truncated "
                    "(recorder event cap exceeded) — an incomplete trace "
                    "cannot replay faithfully"
                )
            faults = rec.get("faults", {})
            crash_rounds = [int(r) for r, _ in faults.get("crash", [])]
            if crash_rounds:
                clients[int(cid)] = ClientFaultSpec(
                    crash_at_round=min(crash_rounds)
                )
            script: Dict[int, dict] = {}
            for r, _ in faults.get("dropout", []):
                script.setdefault(int(r), {})["drop"] = True
            for r, _ in faults.get("flaky", []):
                script.setdefault(int(r), {})["flaky"] = True
            for r, detail in faults.get("slowdown", []):
                # the recorded magnitude, floored so the replayed decision
                # still REGISTERS as a slowdown when the original detail
                # was not captured (older traces)
                script.setdefault(int(r), {})["slowdown_s"] = max(
                    float(detail or 0.0), 1e-3
                )
            if script:
                scripted[int(cid)] = script
        return cls(clients=clients, seed=seed, scripted=scripted)

    # -- queries --
    def spec_for(self, client_id: int) -> ClientFaultSpec:
        return self.clients.get(int(client_id), self.default)

    def tier_of(self, client_id: int) -> Optional[str]:
        """The client's DeviceProfile tier name (None when the plan never
        assigned one) — what a client stamps into its telemetry beacon."""
        return self.tiers.get(int(client_id))

    def has_participation_faults(self) -> bool:
        """True when the plan can remove an upload (dropout or crash) —
        sync transport runs then need deadline/quorum rounds to not hang."""
        if any(
            s.dropout_p > 0 or s.crash_at_round is not None
            for s in list(self.clients.values()) + [self.default]
        ):
            return True
        return any(
            ev.get("drop")
            for rounds in self.scripted.values()
            for ev in rounds.values()
        )

    def decide(
        self, client_id: int, round_idx: int, crash_round: Optional[int] = None
    ) -> FaultDecision:
        """The (client, round) fault decision — pure in (seed, client,
        round): one SeedSequence draw stream per pair, probabilities in a
        fixed order, so every process and every re-run agrees. A client
        with a scripted schedule skips the coin flips entirely — its
        decision IS the recorded event for that round (none recorded =
        no fault), which is what makes trace replay byte-identical.

        ``crash_round`` overrides the value ``crash_at_round`` is compared
        against: FedBuff keys its probabilistic draws by the per-assignment
        dispatch tag (unique, unbounded), which would cross any
        ``crash_at_round`` threshold within a few dozen dispatches — it
        passes the server MODEL VERSION here instead (the async analog of
        a training round)."""
        spec = self.spec_for(client_id)
        cr = int(round_idx) if crash_round is None else int(crash_round)
        crashed = spec.crash_at_round is not None and cr >= spec.crash_at_round
        script = self.scripted.get(int(client_id))
        if script is not None:
            ev = script.get(int(round_idx), {})
            return FaultDecision(
                crashed=crashed,
                drop=bool(ev.get("drop")) and not crashed,
                slowdown_s=float(ev.get("slowdown_s", 0.0)),
                flaky=bool(ev.get("flaky")) and not crashed,
            )
        drop = flaky = False
        if spec.dropout_p > 0 or spec.flaky_upload_p > 0:
            rng = np.random.default_rng(
                [self.seed & 0x7FFFFFFF, int(client_id), int(round_idx) & 0x7FFFFFFF]
            )
            drop = bool(rng.random() < spec.dropout_p)
            flaky = bool(rng.random() < spec.flaky_upload_p)
        return FaultDecision(
            crashed=crashed,
            drop=drop and not crashed,
            slowdown_s=spec.slowdown_s,
            flaky=flaky and not crashed,
        )

    def to_json(self) -> dict:
        """Canonical (materialized) JSON: profile/fleet sugar is resolved
        to per-client specs at parse time, so ``from_json(to_json())``
        round-trips to identical decisions."""
        doc = {
            "seed": self.seed,
            "default": dataclasses.asdict(self.default),
            "clients": {
                str(c): dataclasses.asdict(s) for c, s in sorted(self.clients.items())
            },
        }
        if self.scripted:
            doc["scripted"] = {
                str(c): {str(r): dict(ev) for r, ev in sorted(rounds.items())}
                for c, rounds in sorted(self.scripted.items())
            }
        if self.tiers:
            doc["tiers"] = {
                str(c): t for c, t in sorted(self.tiers.items())
            }
        return doc


class FaultTrace:
    """An OBSERVED fleet: per-client fault events (round + magnitude) and
    train-time statistics, exported by the server-side
    :class:`~fedml_tpu.telemetry.health.ClientHealthRegistry`
    (``export_trace()``; the CLI writes ``fault_trace.json`` next to
    ``health.json`` under ``--telemetry_dir``).

    ``clients[cid]`` carries ``{"faults": {kind: [[round, detail], ...]},
    "rounds_participated", "last_seen_round", "mean_train_s",
    "p90_train_s", "trace_complete"}``. :meth:`FaultPlan.from_trace`
    turns it back into an injectable plan — record once, replay forever."""

    VERSION = 1

    def __init__(self, rounds: int, clients: Optional[Dict[int, dict]] = None):
        self.rounds = int(rounds)
        self.clients: Dict[int, dict] = {
            int(c): dict(rec) for c, rec in (clients or {}).items()
        }

    def to_json(self) -> dict:
        return {
            "version": self.VERSION,
            "rounds": self.rounds,
            "clients": {
                str(c): rec for c, rec in sorted(self.clients.items())
            },
        }

    @classmethod
    def from_json(cls, doc: dict) -> "FaultTrace":
        if doc.get("version", 1) != cls.VERSION:
            raise ValueError(
                f"unsupported fault trace version {doc.get('version')!r}"
            )
        return cls(rounds=doc.get("rounds", 0), clients={
            int(c): dict(rec) for c, rec in (doc.get("clients") or {}).items()
        })

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "FaultTrace":
        if not os.path.exists(path):
            raise ValueError(f"fault trace file {path!r} does not exist")
        with open(path) as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"fault trace is not valid JSON: {e}") from e
        return cls.from_json(doc)


_FAULT_KINDS = ("dropout", "crash", "slowdown", "flaky")
# MetricsLogger key per kind (summary.json schema, asserted by CI)
_FAULT_ROW_KEYS = {
    "dropout": "faults/dropouts",
    "crash": "faults/crashes",
    "slowdown": "faults/slowdowns",
    "flaky": "faults/flaky_uploads",
}


class FaultInjector:
    """The runtime half: applies a plan's decisions and accounts for every
    injected fault (thread-safe — transport clients run in threads).

    One injector is shared across a federation's client actors so the
    counters describe the RUN; ``summary_row()`` is forwarded into
    MetricsLogger at the end (summary.json records the injected faults —
    the CI oracle contract), each event is emitted as a ``fault``
    telemetry span, and — when the server's health registry is reachable
    (in-process federations) — recorded per client via
    ``ClientHealthRegistry.observe_fault``."""

    def __init__(
        self,
        plan: FaultPlan,
        health: Optional[object] = None,
        tracer: Optional[object] = None,
    ):
        self.plan = plan
        self.health = health
        self._tracer = tracer
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {k: 0 for k in _FAULT_KINDS}
        self._crash_logged: set = set()

    @classmethod
    def from_config(
        cls, config, health=None, tracer=None
    ) -> Optional["FaultInjector"]:
        plan = FaultPlan.from_config(config)
        if plan is None:
            return None
        return cls(plan, health=health, tracer=tracer)

    def decide(
        self, client_id: int, round_idx: int, crash_round: Optional[int] = None
    ) -> FaultDecision:
        return self.plan.decide(client_id, round_idx, crash_round=crash_round)

    def record(
        self, client_id: int, round_idx: int, kind: str, detail: float = 0.0
    ) -> None:
        """Account one injected fault. ``detail`` carries the event's
        magnitude where one exists (slowdown seconds) so the health
        registry's fault trace can replay it exactly."""
        assert kind in _FAULT_KINDS, kind
        with self._lock:
            if kind == "crash":
                # a crash is one event per client, not one per ignored round
                if client_id in self._crash_logged:
                    return
                self._crash_logged.add(client_id)
            self.counters[kind] += 1
        if self._tracer is not None:
            with self._tracer.span(
                "fault", client=int(client_id), round=int(round_idx), kind=kind
            ):
                pass
        if self.health is not None and hasattr(self.health, "observe_fault"):
            self.health.observe_fault(client_id, round_idx, kind, detail=detail)

    def total(self) -> int:
        with self._lock:
            return sum(self.counters.values())

    def summary_row(self) -> dict:
        """Flat MetricsLogger row of the run's injected-fault counts."""
        with self._lock:
            row = {
                _FAULT_ROW_KEYS[k]: int(v) for k, v in self.counters.items()
            }
            row["faults/total"] = sum(self.counters.values())
        return row
