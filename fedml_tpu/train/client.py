"""Local (client) training operator.

Replaces the reference's ModelTrainer ABC + per-task trainers
(fedml_core/trainer/model_trainer.py:41-81;
fedml_api/standalone/fedavg/my_model_trainer_classification.py:19-54) with one
pure function: ``local_train(variables, x, y, mask, rng) -> (variables',
metrics)`` — a `lax.scan` of optimizer steps over [epochs × steps] minibatches.
It is vmap-able over a client axis (the standalone simulator) and shard_map-able
over a device mesh (the distributed runtime); the reference's epoch×batch torch
loop is HOT LOOP #2 of SURVEY §3.1.

The FedProx proximal term μ/2·‖w − w_global‖² is included when
``train_config.prox_mu > 0`` — present in the reference only in FedNova's
optimizer (standalone/fednova/fednova.py:120s); its distributed fedprox omits
it (SURVEY §2b row fedprox)."""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.config import TrainConfig
from fedml_tpu.models import ModelDef
from fedml_tpu.train import losses as L


def build_client_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    """torch-semantics optimizers (ref my_model_trainer_classification.py
    get_optimizer: SGD(lr) | Adam(lr, wd, amsgrad=True)). Weight decay is
    L2-added-to-grad (torch style), not decoupled."""
    parts = []
    if tc.wd:
        parts.append(optax.add_decayed_weights(tc.wd))
    if tc.client_optimizer == "sgd":
        parts.append(optax.sgd(tc.lr, momentum=tc.momentum if tc.momentum else None))
    elif tc.client_optimizer == "adam":
        parts.append(optax.amsgrad(tc.lr))
    else:
        raise ValueError(f"unknown client_optimizer {tc.client_optimizer!r}")
    return optax.chain(*parts)


def _split_vars(variables: dict) -> Tuple[dict, dict]:
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    return params, extra


def cast_floats(tree, dtype):
    """Cast every floating leaf; ints (labels, step counts) pass through."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
        else a,
        tree,
    )


def make_mixed_forward(model: ModelDef, tc: TrainConfig):
    """The shared mixed-precision forward: fp32 master params are cast to
    ``tc.compute_dtype`` inside the differentiated function (the cast is
    linear, so grads come back fp32); logits are restored to fp32 so scan
    carries keep stable dtypes. Mutable collections (BN running stats) are
    NEVER cast down: batch statistics are fp32-only territory — the zoo's
    BatchNorms normalize in fp32 and cast back (models/norms.py), and
    quantizing the running-stat EMA to bf16 each step would re-inject the
    error that helper exists to remove. When
    ``tc.augment`` names a policy (train/augment.py), per-sample
    augmentation runs here — inside jit, fused with the forward — so both
    the federated and centralized paths share one definition.

    Returns ``fwd(params, extra, xb, step_rng) -> (logits_f32, new_extra_f32)``.
    Used by both the per-client local-train scan and the centralized DP
    trainer so the compute-dtype policy can never diverge between them."""
    from fedml_tpu.train.augment import resolve_augment

    cdt = jnp.dtype(tc.compute_dtype)
    mixed = cdt != jnp.dtype(jnp.float32)
    augment_fn = resolve_augment(getattr(tc, "augment", "none"))

    def fwd(params, extra, xb, step_rng):
        if augment_fn is not None:
            if step_rng is None:
                # a silent PRNGKey(0) fallback would freeze one augmentation
                # pattern for the whole run — fail loudly instead
                raise ValueError("augmentation requires a step rng")
            xb = augment_fn(jax.random.fold_in(step_rng, 7), xb)
        if mixed:
            params_c = cast_floats(params, cdt)
            xb_c = cast_floats(xb, cdt)
        else:
            params_c, xb_c = params, xb
        logits, new_vars = model.apply(
            {"params": params_c, **extra}, xb_c, train=True, rng=step_rng
        )
        logits = logits.astype(jnp.float32)
        if mixed:
            new_vars = cast_floats(new_vars, jnp.float32)
        _, new_extra = _split_vars(new_vars)
        return logits, new_extra

    return fwd


def make_task_loss(task: str) -> Callable:
    """task → (loss, (correct, total)) (ref per-task MyModelTrainer impls)."""

    def classification(logits, y, mask):
        loss = L.masked_softmax_ce(logits, y, mask)
        correct, total = L.masked_accuracy_stats(logits, y, mask)
        return loss, correct, total

    def nwp(logits, y, mask):
        loss = L.masked_seq_ce(logits, y, mask)
        correct, total = L.masked_seq_accuracy_stats(logits, y, mask)
        return loss, correct, total

    def tag(logits, y, mask):
        loss = L.masked_sigmoid_bce(logits, y, mask)
        pred = (logits > 0).astype(jnp.float32)
        correct = jnp.sum((pred == y).astype(jnp.float32) * mask[:, None])
        total = jnp.sum(mask) * y.shape[-1]
        return loss, correct, total

    def segmentation(logits, y, mask):
        loss = L.masked_pixel_ce(logits, y, mask)
        correct, total = L.masked_pixel_accuracy_stats(logits, y, mask)
        return loss, correct, total

    return {
        "classification": classification,
        "nwp": nwp,
        "tag": tag,
        "segmentation": segmentation,
    }[task]


def masked_epoch_perm(ep_rng, m_flat):
    """Mask-aware shuffle permutation — THE shared shuffle contract (used
    by make_local_train and the SCAFFOLD local train; a divergence here
    would silently change which samples share a minibatch): draw a key per
    slot, pin padded slots to +inf, argsort. Valid samples (slots 0..n-1
    by the stacking contract) get a random order in the first ceil(n/bs)
    minibatches; padding compacts to trailing all-padding steps. Because
    uniform draws are per-position (threefry partitionable) and valid
    slots always occupy the prefix, minibatch composition is INDEPENDENT
    of the padded capacity."""
    keys = jnp.where(
        m_flat > 0, jax.random.uniform(ep_rng, m_flat.shape), jnp.inf
    )
    return jnp.argsort(keys)


def make_local_train(
    model: ModelDef,
    tc: TrainConfig,
    epochs: int,
    task: str = "classification",
    reshuffle_each_epoch: bool = True,
    skip_empty_steps: bool = False,
    external_prox: bool = False,
):
    """Build the per-client training function.

    Returned fn: ``(variables, x, y, mask, rng) -> (variables', metrics)`` with
    x [S, B, *feat], y [S, B, *lab], mask [S, B]. metrics are SUMS
    {loss_sum, correct, count} so they aggregate exactly across clients.

    ``external_prox=True`` prepends a parameter tree to the signature —
    ``(prox_ref_params, variables, x, y, mask, rng)`` — and points the
    tc.prox_mu proximal term at it instead of the entry params. FedProx
    pulls toward the entry params (which ARE the broadcast global model);
    Ditto's personal step starts from the personal model but pulls toward
    the broadcast global model, so the reference must be external
    (algorithms/ditto.py). One loop serves both, keeping their math
    bit-identical at prox_mu=0 by construction.
    """
    opt = build_client_optimizer(tc)
    task_loss = make_task_loss(task)
    fwd = make_mixed_forward(model, tc)

    def _local_train(variables, x, y, mask, rng, prox_ref=None):
        params0, extra0 = _split_vars(variables)
        prox_ref_params = params0 if prox_ref is None else prox_ref
        S, B = mask.shape[0], mask.shape[1]
        n_flat = S * B
        x_flat = x.reshape((n_flat,) + x.shape[2:])
        y_flat = y.reshape((n_flat,) + y.shape[2:])
        m_flat = mask.reshape((n_flat,))

        def loss_fn(params, extra, xb, yb, mb, step_rng):
            logits, new_extra = fwd(params, extra, xb, step_rng)
            task_l, correct, total = task_loss(logits, yb, mb)
            loss = task_l
            if tc.prox_mu:
                loss = loss + 0.5 * tc.prox_mu * L.tree_sq_dist(
                    params, prox_ref_params
                )
            # task_l (not loss) feeds the metrics so FedProx runs report plain
            # task loss, comparable to FedAvg and the reference's logs.
            return loss, (new_extra, task_l, correct, total)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def epoch_body(carry, epoch_idx):
            params, extra, opt_state = carry
            ep_rng = jax.random.fold_in(rng, epoch_idx)
            if reshuffle_each_epoch:
                # masked_epoch_perm: the fused multi-round scan (uniform
                # chunk shapes) and the eager per-round path see identical
                # math — see its docstring
                perm = masked_epoch_perm(ep_rng, m_flat)
            else:
                perm = jnp.arange(n_flat)
            xe = x_flat[perm].reshape(x.shape)
            ye = y_flat[perm].reshape(y.shape)
            me = m_flat[perm].reshape(mask.shape)

            def step_body(carry, inp):
                xb, yb, mb, sidx = inp
                # An all-padding step (mask sum 0) must be a complete no-op:
                # masked-mean grads are already 0, but momentum/Adam state and
                # the prox term would still move params — and the compute
                # itself is pure padding waste.
                has_data = jnp.sum(mb) > 0

                def real_step(carry):
                    params, extra, opt_state = carry
                    step_rng = jax.random.fold_in(ep_rng, sidx)
                    (_, (new_extra, task_l, correct, total)), grads = grad_fn(
                        params, extra, xb, yb, mb, step_rng
                    )
                    updates, new_opt_state = opt.update(
                        grads, opt_state, params
                    )
                    new_params = optax.apply_updates(params, updates)
                    mets = jnp.stack(
                        [task_l * total, correct, total, jnp.float32(1)]
                    )
                    return (new_params, new_extra, new_opt_state), mets

                if skip_empty_steps:
                    # Real skipped branch: the predicate is a scalar in the
                    # sequential ("scan") client schedule, so lax.cond
                    # genuinely skips the fwd/bwd — padded steps cost
                    # ~nothing, which is what lets fused round chunks pad
                    # every round to a shared step count for free.
                    def skip_step(carry):
                        return carry, jnp.zeros((4,), jnp.float32)

                    return jax.lax.cond(has_data, real_step, skip_step, carry)

                # Batched schedules (vmap clients, shard_map mesh): the
                # predicate is per-client, a branch is impossible — compute
                # and where-gate every carry leaf instead.
                (new_params, new_extra, new_opt_state), mets = real_step(carry)
                params, extra, opt_state = carry

                def keep(new, old):
                    return jax.tree_util.tree_map(
                        lambda n, o: jnp.where(has_data, n, o), new, old
                    )

                return (
                    keep(new_params, params),
                    keep(new_extra, extra),
                    keep(new_opt_state, opt_state),
                ), mets * has_data.astype(jnp.float32)

            (params, extra, opt_state), mets = jax.lax.scan(
                step_body,
                (params, extra, opt_state),
                (xe, ye, me, jnp.arange(S)),
            )
            return (params, extra, opt_state), mets.sum(axis=0)

        opt_state = opt.init(params0)
        (params, extra, _), mets = jax.lax.scan(
            epoch_body, (params0, extra0, opt_state), jnp.arange(epochs)
        )
        mets = mets.sum(axis=0)
        # "steps" = effective local optimizer steps (all-padding steps are
        # gated no-ops and not counted) — FedNova's τ_i normalizer.
        metrics = {
            "loss_sum": mets[0],
            "correct": mets[1],
            "count": mets[2],
            "steps": mets[3],
        }
        return {"params": params, **extra}, metrics

    if external_prox:
        def local_train(prox_ref_params, variables, x, y, mask, rng):
            return _local_train(variables, x, y, mask, rng, prox_ref=prox_ref_params)
    else:
        def local_train(variables, x, y, mask, rng):
            return _local_train(variables, x, y, mask, rng)

    return local_train
