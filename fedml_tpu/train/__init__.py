from fedml_tpu.train.client import make_local_train
from fedml_tpu.train.evaluate import make_eval_fn
from fedml_tpu.train import losses

__all__ = ["make_local_train", "make_eval_fn", "losses"]
