"""Centralized (non-federated) data-parallel trainer.

The reference's centralized baseline is a torch DDP/NCCL loop
(fedml_experiments/centralized/main.py:54-67,123 — one process per GPU,
`DistributedDataParallel` wrapping, `DistributedSampler.set_epoch` reshuffle,
fedml_api/centralized/centralized_trainer.py:43-45). The TPU-native analog
needs no process groups or gradient hooks: the train step is jitted with the
batch axis sharded over a `jax.sharding.Mesh` and params replicated — XLA
inserts the gradient all-reduce over ICI itself. One code path serves
single-chip and pod-scale DP.

This is also the non-federated accuracy baseline the benchmark compares
against (VERDICT r1 missing #5), and the "centralized" side of the
federated==centralized oracle as a reusable component instead of test-inline
code."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.config import RunConfig
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import (
    build_client_optimizer,
    make_mixed_forward,
    make_task_loss,
)
from fedml_tpu.train.evaluate import make_eval_fn, pad_to_batches


def make_centralized_epoch(
    model: ModelDef,
    config: RunConfig,
    task: str = "classification",
    mesh: Optional[Mesh] = None,
    batch_axis: Optional[str] = None,
):
    """Build the jitted one-epoch trainer.

    Returned fn: ``(params, extra, opt_state, x, y, mask, rng) ->
    (params', extra', opt_state', metrics)`` with x [S, B, *feat] — a
    `lax.scan` of optimizer steps over the S pre-batched minibatches.
    Unlike the per-client local-train scan (train/client.py), optimizer
    state is an explicit carry so momentum/Adam moments persist across
    epochs (the centralized semantics the reference gets from a long-lived
    torch optimizer, centralized_trainer.py).

    With ``mesh``, the batch dimension B is sharded over ``batch_axis``
    (default: the mesh's first axis) and params are replicated — plain DP;
    XLA emits the psum for the gradient reduction."""
    tc = config.train
    opt = build_client_optimizer(tc)
    task_loss = make_task_loss(task)
    fwd = make_mixed_forward(model, tc)

    def loss_fn(params, extra, xb, yb, mb, step_rng):
        logits, new_extra = fwd(params, extra, xb, step_rng)
        loss, correct, total = task_loss(logits, yb, mb)
        return loss, (new_extra, correct, total)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def epoch_fn(params, extra, opt_state, x, y, mask, rng):
        def step(carry, inp):
            params, extra, opt_state = carry
            xb, yb, mb, sidx = inp
            (loss, (extra, correct, total)), grads = grad_fn(
                params, extra, xb, yb, mb, jax.random.fold_in(rng, sidx)
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, extra, opt_state), jnp.stack(
                [loss * total, correct, total]
            )

        S = mask.shape[0]
        (params, extra, opt_state), mets = jax.lax.scan(
            step, (params, extra, opt_state), (x, y, mask, jnp.arange(S))
        )
        sums = mets.sum(axis=0)
        metrics = {"loss_sum": sums[0], "correct": sums[1], "count": sums[2]}
        return params, extra, opt_state, metrics

    if mesh is None:
        return jax.jit(epoch_fn, donate_argnums=(0, 1, 2))
    axis = batch_axis or mesh.axis_names[0]
    rep = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P(None, axis))  # [S, B, ...]: shard B
    return jax.jit(
        epoch_fn,
        in_shardings=(rep, rep, rep, data_sh, data_sh, data_sh, rep),
        out_shardings=(rep, rep, rep, rep),
        donate_argnums=(0, 1, 2),
    )


class CentralizedTrainer:
    """Pooled-data trainer over an optional device mesh (ref
    fedml_api/centralized/centralized_trainer.py + centralized/main.py).

    Pools all client shards (``FederatedDataset.centralized_train``),
    reshuffles per epoch with an epoch-seeded PRNG (the reference's
    ``sampler.set_epoch`` determinism, centralized_trainer.py:43-45), and
    runs the jitted DP epoch."""

    def __init__(
        self,
        config: RunConfig,
        data,
        model: ModelDef,
        task: str = "classification",
        mesh: Optional[Mesh] = None,
        log_fn=None,
    ):
        self.config, self.model, self.task, self.mesh = config, model, task, mesh
        self.data = data
        self.log_fn = log_fn or (lambda row: None)
        x, y = data.centralized_train()
        self._x = np.asarray(x)
        self._y = np.asarray(y)
        n_dev = 1 if mesh is None else int(np.prod(list(mesh.shape.values())))
        bs = config.data.batch_size
        if bs == -1:
            bs = len(self._x)  # full batch
        # batch must tile over the mesh; round up and let the mask pad
        self.batch_size = -(-bs // n_dev) * n_dev
        self.epoch_fn = make_centralized_epoch(model, config, task, mesh)
        self.eval_fn = make_eval_fn(model, task)
        variables = model.init(
            jax.random.fold_in(jax.random.PRNGKey(config.seed), 0)
        )
        self.params = variables["params"]
        self.extra = {k: v for k, v in variables.items() if k != "params"}
        self.opt_state = build_client_optimizer(config.train).init(self.params)
        self._rng = jax.random.PRNGKey(config.seed)

    @property
    def global_vars(self):
        return {"params": self.params, **self.extra}

    def train_epoch(self, epoch: int) -> dict:
        rng = np.random.default_rng((self.config.seed, epoch))
        perm = rng.permutation(len(self._x))
        x, y, mask = pad_to_batches(
            self._x[perm], self._y[perm], self.batch_size
        )
        self.params, self.extra, self.opt_state, metrics = self.epoch_fn(
            self.params,
            self.extra,
            self.opt_state,
            x,
            y,
            mask,
            jax.random.fold_in(self._rng, epoch),
        )
        count = float(metrics["count"])
        return {
            "epoch": epoch,
            "Train/Loss": float(metrics["loss_sum"]) / max(count, 1.0),
            "Train/Acc": float(metrics["correct"]) / max(count, 1.0),
        }

    def evaluate(self) -> Tuple[float, float]:
        # cap the eval batch: under batch_size=-1 (full train batch) padding
        # the test set to train-set size would waste compute / blow HBM
        x, y, mask = pad_to_batches(
            np.asarray(self.data.test_x),
            np.asarray(self.data.test_y),
            max(min(self.batch_size, 256), 1),
        )
        m = self.eval_fn(self.global_vars, x, y, mask)
        count = float(m["count"])
        return (
            float(m["loss_sum"]) / max(count, 1.0),
            float(m["correct"]) / max(count, 1.0),
        )

    def train(self, epochs: Optional[int] = None) -> dict:
        epochs = epochs if epochs is not None else self.config.fed.comm_round
        row = {}
        for e in range(epochs):
            row = self.train_epoch(e)
            if (e + 1) % self.config.fed.frequency_of_the_test == 0 or (
                e == epochs - 1
            ):
                loss, acc = self.evaluate()
                row.update({"Test/Loss": loss, "Test/Acc": acc})
            self.log_fn(row)
        return row
