"""Jit-compiled evaluation (ref: server-side test,
FedAVGAggregator.py:100-157 / my_model_trainer_classification.py:56-86)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import make_task_loss


def pad_to_batches(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Host-side: pad test arrays to a whole number of batches + mask."""
    n = x.shape[0]
    steps = (n + batch_size - 1) // batch_size
    cap = steps * batch_size
    xp = np.zeros((cap,) + x.shape[1:], dtype=x.dtype)
    yp = np.zeros((cap,) + y.shape[1:], dtype=y.dtype)
    mp = np.zeros((cap,), dtype=np.float32)
    xp[:n], yp[:n], mp[:n] = x, y, 1.0
    return (
        xp.reshape((steps, batch_size) + x.shape[1:]),
        yp.reshape((steps, batch_size) + y.shape[1:]),
        mp.reshape((steps, batch_size)),
    )


def make_eval_fn(model: ModelDef, task: str = "classification"):
    """Returns jitted ``eval_fn(variables, x, y, mask) -> {loss_sum, correct,
    count}`` over batched inputs x [S, B, ...].

    Deduped through the process-wide ProgramCache (fedml_tpu/compile/):
    every API instance over the same (model, task) shares ONE jitted eval
    program instead of recompiling per constructor call."""
    task_loss = make_task_loss(task)

    def builder():
        @jax.jit
        def eval_fn(variables, x, y, mask):
            def body(carry, inp):
                xb, yb, mb = inp
                logits, _ = model.apply(variables, xb, train=False)
                loss, correct, total = task_loss(logits, yb, mb)
                return carry + jnp.stack([loss * total, correct, total]), None

            sums, _ = jax.lax.scan(body, jnp.zeros(3), (x, y, mask))
            return {"loss_sum": sums[0], "correct": sums[1], "count": sums[2]}

        return eval_fn

    from fedml_tpu.compile import get_program_cache, model_fingerprint

    return get_program_cache().get_or_build(
        "eval",
        {
            "kind": "eval",
            "model": model_fingerprint(model),
            "task": task,
        },
        builder,
    )


def metrics_to_loss_acc(m) -> Tuple[float, float]:
    """{loss_sum, correct, count} sums → (mean loss, accuracy). The one
    derivation shared by every eval surface."""
    count = float(m["count"])
    return (
        float(m["loss_sum"]) / max(count, 1e-9),
        float(m["correct"]) / max(count, 1e-9),
    )


def evaluate(model: ModelDef, variables, x, y, batch_size: int = 256, task="classification", eval_fn=None):
    """Convenience host wrapper: returns (loss, accuracy)."""
    xb, yb, mb = pad_to_batches(np.asarray(x), np.asarray(y), batch_size)
    fn = eval_fn or make_eval_fn(model, task)
    return metrics_to_loss_acc(fn(variables, xb, yb, mb))
