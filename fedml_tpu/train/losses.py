"""Mask-weighted losses and metrics.

The reference computes per-batch mean cross-entropy
(my_model_trainer_classification.py:34-53) and counts corrects for accuracy
(:56-86). Here every loss/metric is a mask-weighted mean so zero-padded
examples (see data/base.py) contribute nothing."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def _safe_div(num, den):
    return num / jnp.maximum(den, 1e-9)


def masked_softmax_ce(logits, labels, mask):
    """Mean CE over masked examples. labels: int [B]; mask: float [B]."""
    per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return _safe_div(jnp.sum(per_ex * mask), jnp.sum(mask))


def masked_accuracy_stats(logits, labels, mask):
    """Returns (correct_count, total_count) — the reference's metric schema
    {test_correct, test_total} (my_model_trainer_classification.py:60-64)."""
    pred = jnp.argmax(logits, axis=-1)
    correct = jnp.sum((pred == labels).astype(jnp.float32) * mask)
    return correct, jnp.sum(mask)


def masked_sigmoid_bce(logits, labels, mask):
    """Multi-label BCE for tag prediction (ref
    my_model_trainer_tag_prediction.py: BCELoss). labels: float [B, C]."""
    per_ex = jnp.sum(
        optax.sigmoid_binary_cross_entropy(logits, labels), axis=-1
    )
    return _safe_div(jnp.sum(per_ex * mask), jnp.sum(mask))


def masked_seq_ce(logits, labels, mask, pad_token: int = 0):
    """Next-word/char prediction CE over sequences, ignoring pad tokens
    (ref my_model_trainer_nwp.py: criterion ignores padding idx 0).

    logits [B, T, V], labels int [B, T], mask float [B] (example mask)."""
    per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    tok_mask = (labels != pad_token).astype(jnp.float32) * mask[:, None]
    return _safe_div(jnp.sum(per_tok * tok_mask), jnp.sum(tok_mask))


def masked_seq_accuracy_stats(logits, labels, mask, pad_token: int = 0):
    pred = jnp.argmax(logits, axis=-1)
    tok_mask = (labels != pad_token).astype(jnp.float32) * mask[:, None]
    correct = jnp.sum((pred == labels).astype(jnp.float32) * tok_mask)
    return correct, jnp.sum(tok_mask)


SEG_IGNORE_INDEX = 255  # ref fedseg CE ignore_index (MyModelTrainer.py)


def masked_pixel_ce(logits, labels, mask, ignore_index: int = SEG_IGNORE_INDEX):
    """Per-pixel CE for segmentation, skipping ignore-index pixels
    (ref fedseg/MyModelTrainer.py criterion: CrossEntropyLoss(ignore_index=255)).

    logits [B, H, W, C], labels int [B, H, W], mask float [B]."""
    safe_labels = jnp.where(labels == ignore_index, 0, labels)
    per_px = optax.softmax_cross_entropy_with_integer_labels(logits, safe_labels)
    px_mask = (labels != ignore_index).astype(jnp.float32) * mask[:, None, None]
    return _safe_div(jnp.sum(per_px * px_mask), jnp.sum(px_mask))


def masked_pixel_accuracy_stats(logits, labels, mask, ignore_index: int = SEG_IGNORE_INDEX):
    pred = jnp.argmax(logits, axis=-1)
    px_mask = (labels != ignore_index).astype(jnp.float32) * mask[:, None, None]
    correct = jnp.sum((pred == labels).astype(jnp.float32) * px_mask)
    return correct, jnp.sum(px_mask)


def tree_sq_norm(tree):
    return sum(jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(tree))


def tree_sq_dist(a, b):
    return sum(
        jnp.sum(jnp.square(x - y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )
