"""Device-side data augmentation — random crop, horizontal flip, Cutout.

The reference augments on the host via torchvision transforms + its own
Cutout (fedml_api/data_preprocessing/base.py:136-146: RandomCrop(32, pad 4),
RandomHorizontalFlip, Cutout(16) for the CIFAR/CINIC loaders). On TPU the
host is the wrong place: per-sample torch-style transforms would serialize
on CPU and re-ship the batch every step. Here augmentation is a pure
jit-compiled function applied INSIDE the training step (hooked into
train/client.make_mixed_forward), so it fuses with the forward pass and
the HBM-resident data store keeps working — the stored samples stay
canonical, each epoch sees fresh randomness via the step PRNG.

All ops are static-shape: pad + per-sample dynamic_slice (crop), where-mask
(flip), coordinate-compare mask (cutout)."""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp


def _crop_one(rng, img, pad: int, fill):
    H, W, C = img.shape
    padded = jnp.pad(img, ((pad, pad), (pad, pad), (0, 0)))
    if fill is not None:
        border = jnp.pad(
            jnp.ones((H, W, 1), img.dtype), ((pad, pad), (pad, pad), (0, 0))
        )
        padded = jnp.where(
            border > 0, padded, jnp.asarray(fill, img.dtype)
        )
    oy = jax.random.randint(rng, (), 0, 2 * pad + 1)
    ox = jax.random.randint(jax.random.fold_in(rng, 1), (), 0, 2 * pad + 1)
    return jax.lax.dynamic_slice(padded, (oy, ox, 0), (H, W, C))


def _flip_one(rng, img):
    return jnp.where(jax.random.bernoulli(rng), img[:, ::-1, :], img)


def _cutout_one(rng, img, size: int):
    """Zero a size×size square at a random center (clipped at the edges —
    the reference Cutout's np.clip semantics)."""
    H, W, _ = img.shape
    cy = jax.random.randint(rng, (), 0, H)
    cx = jax.random.randint(jax.random.fold_in(rng, 1), (), 0, W)
    ys = jnp.arange(H)
    xs = jnp.arange(W)
    half = size // 2
    keep_y = (ys < cy - half) | (ys >= cy + half)
    keep_x = (xs < cx - half) | (xs >= cx + half)
    keep = keep_y[:, None] | keep_x[None, :]
    return img * keep[:, :, None].astype(img.dtype)


def make_augment(
    crop_padding: int = 4,
    flip: bool = True,
    cutout_size: int = 16,
    crop_fill=None,
) -> Callable:
    """Returns ``augment(rng, x)`` for x [B, H, W, C]: per-sample random
    crop / horizontal flip / Cutout, vmapped over the batch.

    ``crop_fill``: border value for the crop padding (scalar or [C]).
    ``None`` pads with 0 — the MEAN pixel when inputs are already
    normalized, which is a deliberate deviation from the reference pipeline
    (RandomCrop pads black BEFORE Normalize, so its borders are
    (0-mean)/std per channel); pass that value here for exact parity."""

    def one(rng, img):
        if img.ndim != 3:
            raise ValueError(
                f"augmentation expects image samples [H, W, C]; got shape "
                f"{img.shape} — disable TrainConfig.augment for non-image "
                "tasks"
            )
        if crop_padding:
            img = _crop_one(
                jax.random.fold_in(rng, 0), img, crop_padding, crop_fill
            )
        if flip:
            img = _flip_one(jax.random.fold_in(rng, 1), img)
        if cutout_size:
            img = _cutout_one(jax.random.fold_in(rng, 2), img, cutout_size)
        return img

    def augment(rng, x):
        keys = jax.random.split(rng, x.shape[0])
        return jax.vmap(one)(keys, x)

    return augment


def resolve_augment(name: str) -> Optional[Callable]:
    """TrainConfig.augment → augment fn. "none" → None; "cifar" → the
    reference's CIFAR policy shape (crop pad 4 + flip + Cutout 16,
    base.py:136-146; crop borders are mean-pixel, see make_augment's
    crop_fill note); "crop_flip" → without Cutout."""
    if name in ("", "none", None):
        return None
    if name == "cifar":
        return make_augment(crop_padding=4, flip=True, cutout_size=16)
    if name == "crop_flip":
        return make_augment(crop_padding=4, flip=True, cutout_size=0)
    raise ValueError(f"unknown augment policy {name!r} (none|cifar|crop_flip)")
