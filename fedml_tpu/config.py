"""Typed run configuration.

One typed config object replacing the reference's three coexisting generations
(attrs RunConfig at fedml_core/trainer/model_trainer.py:7-38, click CLIs at
fedml_experiments/distributed/fedavg/main_fedavg.py:24-57, legacy argparse).
Frozen dataclasses so configs are hashable and safe to close over in jit.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# Choice tuples mirroring fedml_experiments/base.py:18-46.
PARTITION_METHODS = ("hetero", "homo", "hetero-fix")
CLIENT_OPTIMIZERS = ("sgd", "adam")
SERVER_OPTIMIZERS = ("sgd", "momentum", "adam", "yogi", "adagrad")


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset + partitioning (ref RunConfig.dataset fields)."""

    dataset: str = "synthetic"
    data_dir: str = "./data"
    partition_method: str = "hetero"  # LDA label-skew
    partition_alpha: float = 0.5
    batch_size: int = 32
    # Bucket padded per-client sample counts to multiples of this to bound the
    # number of distinct jit shapes (see data/base.py).
    pad_bucket: int = 1
    # Keep the whole dataset resident in device HBM and gather sampled
    # clients on-device each round (data/device_store.py) — avoids the
    # per-round host->device batch transfer. Auto-falls-back to host
    # stacking when the dataset exceeds the HBM budget guard.
    device_cache: bool = True


@dataclasses.dataclass(frozen=True)
class FedConfig:
    """Federation topology/round structure (ref RunConfig federation fields)."""

    client_num_in_total: int = 10
    client_num_per_round: int = 10
    comm_round: int = 10
    epochs: int = 1  # local epochs per round
    frequency_of_the_test: int = 1
    ci: bool = False  # CI short-circuit (ref FedAVGAggregator.py:119-126)
    # Hierarchical FL (ref standalone/hierarchical_fl/trainer.py:43-69):
    # clients → group_num groups; each global round runs group_comm_round
    # FedAvg sub-rounds inside every group before the cross-group average.
    group_num: int = 1
    group_comm_round: int = 1
    # Client selection policy (scheduler/policies.py registry): "uniform"
    # (reference-parity round-seeded draw), "weighted" (by local sample
    # counts), "power_of_choice" (loss-biased d-choose-k, Cho et al. 2020),
    # "straggler_aware" (avoids telemetry-flagged stragglers). All
    # round-keyed and seed-deterministic; uniform/weighted select
    # identical cohorts across the simulation and transport runtimes,
    # the adaptive two share the rule but feed on runtime-local signals
    # (docs/SCHEDULING.md).
    selection: str = "uniform"
    # Select ceil(client_num_per_round * factor) clients per round —
    # deadline/quorum rounds still close with ~k useful uploads when part
    # of the cohort drops. 1.0 = off. Transport runners spawn one worker
    # per overprovisioned slot.
    overprovision_factor: float = 1.0
    # Fault-injection plan (scheduler/faults.py): inline JSON or a path to
    # a JSON file ({seed, default, clients: {id: {dropout_p, slowdown_s,
    # crash_at_round, flaky_upload_p}}}); "" = no injected faults.
    # Deterministic per (plan seed, client, round), so CI can exercise the
    # deadline/quorum and staleness recovery paths on purpose.
    fault_plan: str = ""
    # Straggler tolerance for the transport runtime (the reference's
    # aggregator barrier waits forever — FedAVGAggregator.py:43-49, SURVEY §5
    # "no straggler mitigation"). deadline_s > 0: after broadcasting, the
    # server waits at most deadline_s for uploads; once the deadline passes
    # and at least min_clients have reported, it aggregates the partial set
    # and discards late round-tagged uploads. 0 = wait for all (ref parity).
    deadline_s: float = 0.0
    min_clients: int = 1
    # Fused round chunks (vmap runtime + HBM data store only): run up to
    # this many rounds as ONE jitted lax.scan — zero host round-trips inside
    # the chunk. 1 = eager per-round dispatch. Chunks never span an eval
    # round, so observed metrics are identical to the eager loop.
    fused_rounds: int = 1
    # How the round planner decides fused-vs-eager when fused_rounds > 1
    # (algorithms/round_planner.py). "static": legacy — always fuse where
    # structurally possible. "measured": probe BOTH schedules over the
    # first rounds (costs read from the flight recorder's folded phase
    # records, device-synced during the probe) and commit to the measured
    # winner per (algorithm, shape-class, cohort) — no config heuristic
    # decides the schedule, a measurement does. Numerics are identical
    # either way (fused == eager is a test contract); only wall clock
    # differs.
    fused_plan: str = "static"
    # Round pipeline (eager rounds): while round r's programs execute on
    # device (JAX dispatch is async), the host prepares round r+1 —
    # cohort selection, batch gather/stack, H2D placement — and stashes
    # the placed batch for the round boundary (the _warm_placed commit
    # contract warmup already uses). Inputs are pure in
    # (round, config.seed, rng), so numerics are BYTE-IDENTICAL to the
    # serial schedule (tests/test_pipeline.py). "auto" (default)
    # pipelines wherever that purity holds and degrades to serial
    # automatically: adaptive selection (power_of_choice /
    # straggler_aware need round r's signals before selecting r+1),
    # active fault plans that shrink cohorts, fused chunks (the chunk
    # already amortizes dispatch on device), and planner probe rounds
    # (their folds must measure the serial schedule). "on" is an alias
    # of "auto" (the degradations are correctness rules, not
    # preferences); "off" forces the serial schedule. Overlap is
    # measured and folded per round as flight `overlap_s`.
    pipeline: str = "auto"
    # Eval rounds evaluate on every client's local train/test shards
    # (ref _local_test_on_all_clients, fedavg_api.py:117-180) instead of the
    # central test set.
    eval_on_clients: bool = False
    # Asynchronous buffered aggregation knobs, consumed by the FedBuff
    # runtime (algorithms/fedbuff.py, selected via the fedbuff entry
    # points / CLI --algorithm fedbuff — beyond the reference, whose
    # aggregator barrier waits for every worker forever,
    # FedAVGAggregator.py:43-49). Under FedBuff the server never barriers:
    # every upload is answered immediately with the current model, and the
    # global model advances whenever the buffer holds async_buffer_k client
    # deltas, each discounted by staleness (1+tau)^(-async_staleness_exp)
    # and scaled by async_server_lr. comm_round then counts SERVER STEPS
    # (buffer flushes), not synchronous rounds. The synchronous runtimes
    # ignore these fields.
    async_buffer_k: int = 0
    async_staleness_exp: float = 0.5
    async_server_lr: float = 1.0
    # How the round executes the sampled clients' local trainings on one
    # chip: "vmap" batches them (one program, grouped convs/batched matmuls
    # — best for small models where per-step overhead dominates), "scan"
    # runs them sequentially (each client's convs keep full MXU tiling —
    # measured 1.8x faster for conv models whose channel dims are small
    # relative to the 128-lane MXU, e.g. the cross-silo ResNet-56 round:
    # 339 ms -> 190 ms bf16 on v5e, examples/probe_resnet_bf16.py).
    # "auto" picks scan for conv models with a client param copy >= 1 MB.
    client_parallelism: str = "auto"
    # Where stateful algorithms (SCAFFOLD control variates, Ditto personal
    # models) keep their N × |params| per-client state: "device" pins the
    # stacked pytree in HBM (gather/scatter inside the jitted round),
    # "mmap" spills it to a disk-backed store (cohort rows ride to device
    # per round — the same disk→host→HBM tiering as data/mmap_store.py),
    # "sharded" spills to the record-major fixed-stride tier
    # (population/state_tier.py — one contiguous record per client,
    # sharded files; the million-client form), "auto" picks device while
    # the stack fits state_budget_bytes and spills beyond it (sharded
    # at/above PopulationConfig.ocohort_threshold clients, mmap below).
    # Round 3 REFUSED past the budget (VERDICT r3 Weak #3); now it
    # spills instead.
    state_store: str = "auto"
    state_budget_bytes: int = 8 << 30
    state_dir: str = ""  # "" = a fresh temp dir per run


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Local (client) optimizer settings (ref MyModelTrainer.get_optimizer)."""

    client_optimizer: str = "sgd"
    lr: float = 0.03
    wd: float = 0.0
    momentum: float = 0.0
    # FedProx proximal term; 0 = plain FedAvg. The reference's distributed
    # fedprox omits mu entirely (SURVEY §2b) — fixed here.
    prox_mu: float = 0.0
    # Mixed-precision policy: params + optimizer state stay float32 (master
    # weights); forward/backward run in this dtype. "bfloat16" is the TPU
    # MXU-native dtype (the reference is fp32-only torch).
    compute_dtype: str = "float32"
    # Device-side augmentation policy applied inside the jitted train step
    # (train/augment.py): "none" | "cifar" (crop pad-4 + flip + Cutout 16,
    # the reference's CifarDataLoader transforms, base.py:136-146) |
    # "crop_flip".
    augment: str = "none"


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Server-side optimizer for the FedOpt family
    (ref fedml_api/distributed/fedopt/FedOptAggregator.py:95-117)."""

    server_optimizer: str = "sgd"
    server_lr: float = 1.0
    server_momentum: float = 0.0
    tau: float = 1e-3  # adaptivity for yogi/adam


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """Cross-silo transport options (core/). The reference ships raw
    JSON-list tensors with no compression option anywhere; here the binary
    wire can additionally carry compressed client UPLINK updates
    (core/compression.py): the client sends encode(w_local − w_round) and
    the server reconstructs w_round + decode(...) before aggregating.
    Downlink (broadcast) is exact by default; ``downlink_compression``
    optionally ships the round's model itself int8-quantized — encoded
    ONCE per round through the same codec registry, with both ends
    training/decoding against the identical dequantized tree."""

    # "none" | "int8" (per-tensor linear quantization) | "int4" (packed
    # low-bit: 4-bit levels, two per byte — ~8x; pair with
    # error_feedback) | "topk" (magnitude sparsification at topk_frac
    # density) | "topk8" (top-k with int8-quantized values).
    compression: str = "none"
    topk_frac: float = 0.01
    # Downlink (broadcast) quantization, transport runtimes: "none"
    # ships the fp32 model; "int8" encodes it once per round
    # (core/compression.py encode_int8 — per-tensor symmetric scales)
    # and every worker's envelope carries the SAME payload. The server
    # keeps the dequantized tree as the round's reference — clients
    # train from it and uplink deltas encode/decode against it on both
    # ends, so quantized downlink composes with every uplink codec.
    # Payload-vs-raw bytes are metered per broadcast (comm/downlink_*).
    downlink_compression: str = "none"
    # Lossy codecs (topk/topk8/int4/int8): per-client residual memory
    # (error feedback) — dropped coordinates AND quantization error
    # accumulate and ship in later rounds instead of being lost. Off by
    # default (stateless-client parity with the reference).
    error_feedback: bool = False
    # Transport send retry (core/retry.py, applied once in the
    # BaseCommManager send template): a failed send is retried up to this
    # many times under seed-deterministic jittered exponential backoff.
    # 0 = legacy single-attempt sends. At-least-once safe: FedBuff
    # dedupes restated uploads on the dispatch tag, the sync server on
    # (client, round)/worker slot.
    send_retries: int = 0
    send_backoff_s: float = 0.05  # backoff base (doubles per retry)
    send_backoff_max_s: float = 2.0  # per-sleep cap
    # Total wall-clock a logical send may spend across attempts + backoff
    # sleeps; the send gives up early when the next sleep would cross it.
    # 0 = attempts cap only.
    send_retry_deadline_s: float = 0.0
    # Per-RPC deadline for grpc sends (was a hard-coded 30.0 in
    # grpc_comm._send). With send_retries > 0 the retry layer owns
    # reconnects: every attempt — including first contact, which still
    # waits for the peer's server to bind — is capped here instead of
    # the legacy one-shot 120 s wait_for_ready handshake.
    send_timeout_s: float = 30.0
    # Transport chaos: probability an individual send ATTEMPT fails with
    # an injected transient error before reaching the wire — pure in
    # (seed, send seq, attempt), so a flaky-transport run replays
    # identically. The eventual successful attempt delivers exactly once
    # (numerics identical to a fault-free run). Requires send_retries > 0.
    send_fault_p: float = 0.0
    # Boundary-wire quantization for the split/vertical runtimes
    # (fedml_tpu/splitfed/codec.py): per-batch activations, activation
    # grads, and VFL logit contributions ship int8/int4-quantized through
    # the same codec registry the model path uses (topk variants are
    # delta-sparsity codecs — activations are dense, so they're
    # rejected). "none" ships fp32 tensors. Metered per boundary message
    # (comm/uplink_* for acts/contribs, comm/downlink_* for grads), so
    # the cut factor is read off comm/*, never asserted.
    activation_compression: str = "none"
    # Per-stream residual memory over the boundary tensors: each
    # direction of each (peer, shape) stream folds its quantization
    # error into the next same-shape tensor before encoding (the split
    # analogue of error_feedback's per-client residual).
    activation_error_feedback: bool = False
    # Secure aggregation in the round loop (ref distributed turboaggregate):
    # clients upload pairwise-masked field vectors of their weighted
    # deltas; the server only ever sums masked uploads, and a quorum round
    # (deadline_s) triggers dropout mask recovery. Protocol SIMULATION —
    # the DH registry is derived deterministically from the run seed (see
    # secagg/secure_aggregation.py SECURITY NOTE); mutually exclusive with
    # compression.
    secure_agg: bool = False
    # gRPC server executor size (core/grpc_comm.py — was a hard-coded
    # ThreadPoolExecutor(max_workers=8)). 0 = auto: sized from the
    # expected cohort (the rank's ip_config table), capped — handler
    # work is a queue put, so a small pool serves thousands of streams;
    # the bound is what the fleet gate ASSERTS (examples/ci.sh).
    grpc_max_workers: int = 0
    # Inbound stream budget (server-side backpressure): when > 0, a
    # received RPC is REFUSED (RESOURCE_EXHAUSTED) while more than this
    # many messages sit undrained in the receive queue — graceful
    # refusal instead of unbounded queue growth; the refused sender
    # redials under its retry policy (core/retry.py) and both ends
    # meter the refusal (comm/refused, comm/send_refused). 0 = off.
    grpc_stream_budget: int = 0
    # gRPC channel/server max message size in MB (was the module-constant
    # 1000 MB mirroring the reference's grpc_comm_manager.py:35-39).
    grpc_max_message_mb: int = 1000
    # gRPC keepalive ping interval in seconds; 0 = transport default
    # (no explicit keepalive options). Long-lived fleet channels set
    # this so half-open connections die instead of wedging a worker.
    grpc_keepalive_s: float = 0.0
    # MiniMqttBroker connection cap (core/mqtt_broker.py): past it a
    # CONNECT is answered CONNACK 0x03 (server unavailable) and closed
    # instead of growing one reader thread per connection without
    # bound; refusals are metered (comm/refused). 0 = unbounded
    # (legacy behavior).
    mqtt_max_connections: int = 0
    # Client telemetry beacons (telemetry/wire.py): a bounded ~200 B
    # summary of local measurements (train s, encode s, retries, codec,
    # DeviceProfile tier, RSS) piggybacked as ARG_TELEMETRY on model
    # uploads. Observability only — it rides the envelope, never the
    # model path, so numerics are byte-identical on or off; bytes are
    # metered apart from model bytes (comm/beacon_bytes).
    beacons: bool = True


@dataclasses.dataclass(frozen=True)
class CompileConfig:
    """Compile-runtime knobs (fedml_tpu/compile/ — the reference framework
    is PyTorch eager and has no compilation cost dimension at all)."""

    # AOT-compile the run's programs before round 0
    # (``jit(...).lower(...).compile()``, compile/warmup.py): round + eval
    # + server-optimizer programs on vmap/mesh, the shared client
    # local-train program on the sync transports (so --deadline_s rounds
    # start with compilation already paid). Numerics are identical to a
    # cold run — warmup only lowers/compiles, it executes nothing.
    warmup: bool = False
    # Persistent XLA compile-cache directory served by the hardened store
    # (compile/persistent.py: atomic writes, sha256 integrity check with
    # quarantine, advisory file lock). "" = no persistent cache.
    cache_dir: str = ""
    # Only persist compiles at least this slow. The conservative 2 s
    # default matches tests/conftest.py: aggressive thresholds (0.3-0.5 s)
    # corrupted the heap on this jaxlib under the STOCK cache (ROADMAP
    # "compile-cache hygiene"); the hardened store tolerates 0 (the
    # zero-cold-start CI stage runs it), but the default stays safe.
    min_compile_time_s: float = 2.0
    # Persistent SERIALIZED-EXECUTABLE store (compile/executable_cache.py)
    # served through the hardened store: --warmup exports every AOT
    # executable it compiles, keyed by (program digest, shape class,
    # environment fingerprint), and a fresh process deserializes its
    # whole warmup set instead of compiling it — zero-cold-start serving.
    # Version/backend/code skew lands on a different key (clean miss,
    # recompile), never wrong numerics. "" = off.
    executable_cache: str = ""
    # Recompile budget (fedml_tpu/analysis/sentinel.py): fail the run when
    # more than this many XLA backend compiles happen — the tripwire for
    # cache-key instabilities that silently recompile every round. Counts
    # every ACTUAL backend compile (including small utility programs —
    # but NOT persistent-cache hits or deserialized executables, which
    # compile nothing: a fully warm process passes budget 0, the
    # zero-cold-start CI gate). Budgets are coarse upper bounds asserting
    # "no compile storm", not exact program counts. None = unlimited (no
    # sentinel).
    recompile_budget: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class PopulationConfig:
    """Population-scale runtime knobs (fedml_tpu/population/ — the
    O(cohort) machinery for 1M+ client registries, docs/POPULATION.md).

    Every field here steers HOST-SIDE data structures (samplers, mmap
    index/state layout, telemetry bounds); none can reach a compiled
    program, so the whole section is classified KNOWN_BENIGN in the
    digest audit (analysis/digest_audit.py)."""

    # Client count at/above which the O(cohort) selection paths engage
    # (alias-table weighted draw, rejection-sampled candidate pools,
    # rejection-sampled straggler avoidance). Below it the legacy exact
    # numpy draws run — identical cohorts to every historical run.
    ocohort_threshold: int = 65536
    # PopulationIndex (population/index.py): back the packed per-client
    # metadata arrays with an on-disk memmap once they exceed this many
    # bytes (0 = always in RAM). Only matters when index_dir is set.
    index_mmap_bytes: int = 64 << 20
    index_dir: str = ""  # "" = keep the packed index in RAM
    # Sharded state tier (population/state_tier.py): clients per shard
    # file = 1 << state_shard_bits (default 65536/shard — 1M clients
    # land in 16 record files).
    state_shard_bits: int = 16
    # power_of_choice bias map bound (scheduler/policies.py): the
    # scheduler keeps at most this many last-known client losses
    # (insertion-ordered eviction). Bounds the "sched" checkpoint slot —
    # an unbounded map grows O(N) at million-client populations.
    loss_map_capacity: int = 65536
    # How many most-recent rounds of the selection memo the scheduler
    # checkpoint persists (resume only ever re-selects the in-flight
    # round; the full memo would grow O(rounds) in the checkpoint).
    selection_memo_rounds: int = 64
    # Health registry bounds (telemetry/health.py): full-fidelity
    # (timing window + dedupe memory) client records are an LRU active
    # set of at most this many recently-seen clients; evicted records
    # spill to a compact aggregate (~100 B/client).
    health_active_clients: int = 65536
    # Registry-wide byte budget for the full-fidelity fault-event log
    # backing FaultTrace export. Past it the registry keeps exact fault
    # TALLIES but stops recording events and marks affected clients
    # trace_incomplete (FaultPlan.from_trace refuses them — a partial
    # fleet must never replay silently).
    health_trace_budget_bytes: int = 16 << 20
    # Round flight recorder bounds (telemetry/flight.py): the per-round
    # ring keeps at most flight_rounds folded records AND never more
    # than flight_budget_bytes of them (whichever bound is tighter wins
    # — a month-long serve tenant stays O(K), never O(rounds), exactly
    # like the fault-event log above).
    flight_rounds: int = 64
    flight_budget_bytes: int = 64 << 10


@dataclasses.dataclass(frozen=True)
class AdminConfig:
    """Control-plane knobs a tenant carries to the serve layer
    (fedml_tpu/serve/: placement.py, admission.py, admin.py —
    docs/SERVING.md). Single runs ignore them.

    Every field is HOST-SIDE service policy — which slice a tenant is
    scheduled on and what the admission door requires — and none can
    reach a compiled program, so the section is classified KNOWN_BENIGN
    in the digest audit (analysis/digest_audit.py), exactly like
    PopulationConfig."""

    # Placement pin: run this tenant on slice index N of the service's
    # device slices (serve --device_slices). -1 = let the placer bin-pack
    # onto the least-loaded slice. Pinning two same-model-family tenants
    # to ONE slice preserves their cross-tenant executable sharing (XLA
    # compiles per device — crossing slices costs one compile).
    device_slice: int = -1
    # Admission: refuse this tenant when host MemAvailable is below this
    # many MB at the door (0 = no headroom requirement).
    admit_min_headroom_mb: float = 0.0
    # Admission: refuse when the tenant's priced compute — measured
    # per-dispatch XLA cost-analysis flops x cohort size — exceeds this
    # many GFLOP per round (0 = no cap; unpriced candidates pass).
    admit_cost_cap_gflops: float = 0.0


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh spec replacing the reference's gpu_mapping.yaml
    (fedml_api/distributed/utils/gpu_mapping.py:8-39)."""

    # Number of mesh shards along the client axis; None = all local devices.
    client_shards: Optional[int] = None
    axis_name: str = "clients"


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Top-level config threaded through every API (ref RunConfig)."""

    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    fed: FedConfig = dataclasses.field(default_factory=FedConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    server: ServerConfig = dataclasses.field(default_factory=ServerConfig)
    comm: CommConfig = dataclasses.field(default_factory=CommConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    compile: CompileConfig = dataclasses.field(default_factory=CompileConfig)
    population: PopulationConfig = dataclasses.field(
        default_factory=PopulationConfig
    )
    admin: AdminConfig = dataclasses.field(default_factory=AdminConfig)
    model: str = "lr"
    seed: int = 0

    def replace(self, **kw) -> "RunConfig":
        return dataclasses.replace(self, **kw)
