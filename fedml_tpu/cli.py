"""Unified CLI — the L5 experiment-driver layer (ref:
fedml_experiments/distributed/fedavg/main_fedavg.py:24-131 click flags +
fed_launch/main.py unified launcher + the 19 main_*.py drivers).

One command covers what the reference spreads over 19 drivers: flag surface
mirrors main_fedavg.py:24-57 (model/dataset/partition/optimizer/round flags),
`--algorithm` replaces the per-algorithm driver files, and `--runtime`
replaces `--backend MPI|GRPC|MQTT|TRPC` with the TPU-native choices:
``vmap`` (single-chip simulator, ref standalone/*), ``mesh`` (sharded
multi-chip SPMD, ref distributed/* over MPI), ``loopback`` (threaded
actor federation, transport parity path). GPU-mapping YAML flags become
`--client_shards` (mesh spec, SURVEY §5 config point)."""

from __future__ import annotations

import json
from pathlib import Path

import click

from fedml_tpu.config import (
    DataConfig,
    FedConfig,
    MeshConfig,
    RunConfig,
    ServerConfig,
    TrainConfig,
)

ALGORITHMS = ("fedavg", "fedopt", "fedprox", "fednova", "hierarchical", "fedavg_robust")
RUNTIMES = ("vmap", "mesh", "loopback")


@click.command()
@click.option("--model", default="lr", help="Model name (models/registry.py)")
@click.option("--dataset", "dataset_name", default="synthetic", help="Dataset name (data/registry.py)")
@click.option("--data_dir", type=click.Path(path_type=Path), default=Path("./data"))
@click.option("--partition_method", type=click.Choice(("hetero", "homo", "hetero-fix")), default="hetero")
@click.option("--partition_alpha", type=float, default=0.5)
@click.option("--client_num_in_total", type=int, default=10)
@click.option("--client_num_per_round", type=int, default=10)
@click.option("--batch_size", type=int, default=32, help="-1 = full batch")
@click.option("--client_optimizer", type=click.Choice(("sgd", "adam")), default="sgd")
@click.option("--lr", type=float, default=0.03)
@click.option("--wd", type=float, default=0.0)
@click.option("--momentum", type=float, default=0.0)
@click.option("--epochs", type=int, default=1)
@click.option("--comm_round", type=int, default=10)
@click.option("--frequency_of_the_test", type=int, default=1)
@click.option("--algorithm", type=click.Choice(ALGORITHMS), default="fedavg")
@click.option("--runtime", type=click.Choice(RUNTIMES), default="vmap")
@click.option("--client_shards", type=int, default=None, help="Mesh shards (runtime=mesh); default all devices")
@click.option("--server_optimizer", default="sgd", help="FedOpt server optimizer")
@click.option("--server_lr", type=float, default=1.0)
@click.option("--server_momentum", type=float, default=0.0)
@click.option("--prox_mu", type=float, default=0.01, help="FedProx proximal term (algorithm=fedprox)")
@click.option("--group_num", type=int, default=2, help="hierarchical: number of groups")
@click.option("--group_comm_round", type=int, default=1)
@click.option("--seed", type=int, default=0)
@click.option("--log_dir", type=click.Path(path_type=Path), default=None)
@click.option("--checkpoint_path", type=click.Path(path_type=Path), default=None,
              help="Save (params, round) here on every test round and at the end")
@click.option("--ci", is_flag=True, default=False, help="CI short-circuit (1 round smoke)")
def main(**opt):
    """Train a federated model on TPU."""
    run(**opt)


def build_config(opt) -> RunConfig:
    return RunConfig(
        data=DataConfig(
            dataset=opt["dataset_name"],
            data_dir=str(opt["data_dir"]),
            partition_method=opt["partition_method"],
            partition_alpha=opt["partition_alpha"],
            batch_size=opt["batch_size"],
        ),
        fed=FedConfig(
            client_num_in_total=opt["client_num_in_total"],
            client_num_per_round=opt["client_num_per_round"],
            comm_round=1 if opt["ci"] else opt["comm_round"],
            epochs=opt["epochs"],
            frequency_of_the_test=opt["frequency_of_the_test"],
            ci=opt["ci"],
            group_num=opt["group_num"],
            group_comm_round=opt["group_comm_round"],
        ),
        train=TrainConfig(
            client_optimizer=opt["client_optimizer"],
            lr=opt["lr"],
            wd=opt["wd"],
            momentum=opt["momentum"],
            prox_mu=opt["prox_mu"] if opt["algorithm"] == "fedprox" else 0.0,
        ),
        server=ServerConfig(
            server_optimizer=opt["server_optimizer"],
            server_lr=opt["server_lr"],
            server_momentum=opt["server_momentum"],
        ),
        mesh=MeshConfig(client_shards=opt["client_shards"]),
        model=opt["model"],
        seed=opt["seed"],
    )


def run(**opt):
    from fedml_tpu.data import registry as data_registry
    from fedml_tpu.models import create_model
    from fedml_tpu.utils import MetricsLogger, save_checkpoint

    config = build_config(opt)
    data = data_registry.load(config)
    task = data_registry.task_for_dataset(config.data.dataset)
    sample_shape = tuple(data.client_x[0].shape[1:])
    model = create_model(config.model, config.data.dataset, sample_shape, data.num_classes)

    logger = MetricsLogger(str(opt["log_dir"]) if opt["log_dir"] else None)
    api_cell = []

    def log_fn(row):
        logger.log(row)
        # crash-resumable: persist on every test round, not just at the end
        if opt["checkpoint_path"] and "Test/Acc" in row and api_cell:
            gv = getattr(api_cell[0], "global_vars", None)
            if gv is not None:
                save_checkpoint(
                    str(opt["checkpoint_path"]), gv, round_idx=row["round"]
                )

    api = _build_api(opt["algorithm"], opt["runtime"], config, data, model, task, log_fn)
    api_cell.append(api)

    final = api.train()
    if opt["checkpoint_path"]:
        save_checkpoint(
            str(opt["checkpoint_path"]),
            getattr(api, "global_vars"),
            round_idx=config.fed.comm_round,
        )
    logger.close()
    click.echo(json.dumps({k: v for k, v in (final or {}).items()}))
    return api


def _build_api(algorithm, runtime, config, data, model, task, log_fn):
    if runtime == "loopback":
        if algorithm != "fedavg":
            raise click.UsageError("runtime=loopback currently supports algorithm=fedavg")
        from fedml_tpu.algorithms.fedavg_transport import run_loopback_federation

        class _Runner:
            global_vars = None

            def train(self):
                server = run_loopback_federation(config, data, model, task=task, log_fn=log_fn)
                _Runner.global_vars = server.global_vars
                self.global_vars = server.global_vars
                return server.history[-1] if server.history else {}

        return _Runner()

    if runtime == "mesh":
        from fedml_tpu.parallel import DistributedFedAvgAPI

        if algorithm not in ("fedavg", "fedprox"):
            raise click.UsageError("runtime=mesh currently supports fedavg/fedprox")
        return DistributedFedAvgAPI(config, data, model, task=task, log_fn=log_fn)

    # vmap simulator runtimes (ref standalone/*)
    if algorithm in ("fedavg", "fedprox"):
        from fedml_tpu.algorithms import FedAvgAPI

        return FedAvgAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "fedopt":
        from fedml_tpu.algorithms import FedOptAPI

        return FedOptAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "fednova":
        from fedml_tpu.algorithms import FedNovaAPI

        return FedNovaAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "hierarchical":
        from fedml_tpu.algorithms import HierarchicalFedAvgAPI

        return HierarchicalFedAvgAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "fedavg_robust":
        from fedml_tpu.algorithms.fedavg_robust import RobustFedAvgAPI

        return RobustFedAvgAPI(config, data, model, task=task, log_fn=log_fn)
    raise click.UsageError(f"unknown algorithm {algorithm}")


if __name__ == "__main__":
    main()
