"""Unified CLI — the L5 experiment-driver layer (ref:
fedml_experiments/distributed/fedavg/main_fedavg.py:24-131 click flags +
fed_launch/main.py unified launcher + the 19 main_*.py drivers).

One command covers what the reference spreads over 19 drivers: flag surface
mirrors main_fedavg.py:24-57 (model/dataset/partition/optimizer/round flags),
`--algorithm` replaces the per-algorithm driver files — every algorithm
package is reachable here (the reference's L5 promise) — and `--runtime`
replaces `--backend MPI|GRPC|MQTT|TRPC` with the TPU-native choices:
``vmap`` (single-chip simulator, ref standalone/*), ``mesh`` (sharded
multi-chip SPMD, ref distributed/* over MPI), ``loopback`` (threaded
actor federation, transport parity path). GPU-mapping YAML flags become
`--client_shards` (mesh spec, SURVEY §5 config point). New in round 2:
``--resume`` (round-level checkpoint restore — the upgrade over the
reference's per-algorithm best-model saves, SURVEY §5), ``--compute_dtype
bfloat16`` (MXU-native mixed precision), ``--profile_dir`` (jax.profiler
trace capture)."""

from __future__ import annotations

import json
from pathlib import Path

import click
import numpy as np

from fedml_tpu.config import (
    AdminConfig,
    CommConfig,
    CompileConfig,
    DataConfig,
    FedConfig,
    MeshConfig,
    RunConfig,
    ServerConfig,
    TrainConfig,
)
from fedml_tpu.robustness import BYZANTINE_AGGREGATORS, CLIP_DEFENSES

ALGORITHMS = (
    "centralized",
    "fedavg",
    "fedopt",
    "fedprox",
    "fednova",
    "scaffold",  # beyond the reference: control-variate drift correction
    "fedbuff",  # beyond the reference: barrier-free async aggregation
    "ditto",  # beyond the reference: personalized FL (per-client models)
    "dp_fedavg",  # beyond the reference: client-level DP with RDP ledger
    "qfedavg",  # beyond the reference: q-FFL fair aggregation
    "hierarchical",
    "fedavg_robust",
    "fedgkt",
    "fedgan",
    "fedseg",
    "fednas",
    "split_nn",
    "vertical_fl",
    "decentralized",
    "secagg",
)
RUNTIMES = ("vmap", "mesh", "loopback", "mqtt", "shm", "grpc")


@click.command()
@click.option("--model", default="lr",
              help="Model name (models/registry.py); fedgkt/fednas/split_nn/"
                   "vertical_fl/decentralized/secagg use their own fixed "
                   "architectures and ignore this flag")
@click.option("--dataset", "dataset_name", default="synthetic", help="Dataset name (data/registry.py)")
@click.option("--data_dir", type=click.Path(path_type=Path), default=Path("./data"))
@click.option("--partition_method", type=click.Choice(("hetero", "homo", "hetero-fix")), default="hetero")
@click.option("--partition_alpha", type=float, default=0.5)
@click.option("--client_num_in_total", type=int, default=10)
@click.option("--client_num_per_round", type=int, default=10)
@click.option("--batch_size", type=int, default=32, help="-1 = full batch")
@click.option("--pad_bucket", type=int, default=1,
              help="round per-client step counts up to multiples of this "
                   "(shape-class bucketing: fewer XLA compiles on ragged "
                   "shards at the cost of a little padded compute)")
@click.option("--client_optimizer", type=click.Choice(("sgd", "adam")), default="sgd")
@click.option("--lr", type=float, default=0.03)
@click.option("--wd", type=float, default=0.0)
@click.option("--momentum", type=float, default=0.0)
@click.option("--epochs", type=int, default=1)
@click.option("--comm_round", type=int, default=10)
@click.option("--frequency_of_the_test", type=int, default=1)
@click.option("--eval_on_clients", is_flag=True, default=False,
              help="Eval on every client's local shards "
                   "(ref _local_test_on_all_clients) instead of the central test set")
@click.option("--algorithm", type=click.Choice(ALGORITHMS), default="fedavg")
@click.option("--runtime", type=click.Choice(RUNTIMES), default="vmap")
@click.option("--client_shards", type=int, default=None, help="Mesh shards (runtime=mesh); default all devices")
@click.option("--server_optimizer", default="sgd", help="FedOpt server optimizer")
@click.option("--server_lr", type=float, default=1.0)
@click.option("--server_momentum", type=float, default=0.0)
@click.option("--prox_mu", type=float, default=0.01, help="FedProx proximal term (algorithm=fedprox)")
@click.option("--defense", type=click.Choice(CLIP_DEFENSES + BYZANTINE_AGGREGATORS),
              default="norm_diff_clipping",
              help="fedavg_robust: clip/noise (ref) or Byzantine aggregator")
@click.option("--norm_bound", type=float, default=5.0,
              help="norm_diff_clipping/weak_dp: clip ||w_i - w_g|| to this")
@click.option("--noise_stddev", type=float, default=0.025,
              help="weak_dp: Gaussian noise stddev after averaging")
@click.option("--num_byzantine", type=int, default=1,
              help="assumed Byzantine client count (trimmed_mean trim-k, krum f)")
@click.option("--multi_krum_m", type=int, default=3,
              help="multi_krum: average the m best-scored clients")
@click.option("--attack", type=click.Choice(("none", "backdoor")), default="none",
              help="fedavg_robust: simulate attackers (poisoned shards + "
                   "boosted uploads, ref edge_case_examples) and report "
                   "Backdoor/ASR")
@click.option("--num_attackers", type=int, default=1,
              help="attack=backdoor: clients 0..k-1 are attackers")
@click.option("--attack_boost", type=float, default=10.0,
              help="model-replacement boost γ on attacker uploads")
@click.option("--poison_frac", type=float, default=0.5,
              help="fraction of each attacker shard triggered+relabeled")
@click.option("--target_label", type=int, default=0,
              help="backdoor target class")
@click.option("--group_num", type=int, default=2, help="hierarchical: number of groups")
@click.option("--group_comm_round", type=int, default=1)
@click.option("--compute_dtype", type=click.Choice(("float32", "bfloat16")), default="float32",
              help="Forward/backward dtype; params stay fp32 (master weights)")
@click.option("--augment", type=click.Choice(("none", "cifar", "crop_flip")), default="none",
              help="Device-side augmentation inside the jitted train step")
@click.option("--variant", default=None,
              help="Algorithm sub-variant: decentralized dsgd|pushsum, fednas arch_grad first|second")
@click.option("--seed", type=int, default=0)
@click.option("--log_dir", type=click.Path(path_type=Path), default=None)
@click.option("--checkpoint_path", type=click.Path(path_type=Path), default=None,
              help="Save (params, round) here on every test round and at the end")
@click.option("--resume", is_flag=True, default=False,
              help="Restore from --checkpoint_path and continue from the saved round")
@click.option("--profile_dir", type=click.Path(path_type=Path), default=None,
              help="Capture a jax.profiler device trace of the run into this dir")
@click.option("--telemetry_dir", type=click.Path(path_type=Path), default=None,
              help="Write host-side telemetry here: trace.json (Chrome "
                   "trace events — round/broadcast/local_train/aggregate/"
                   "eval spans, viewable in Perfetto next to the "
                   "--profile_dir device trace), health.json (per-client "
                   "participation/train-time/straggler registry) and "
                   "flight.json (the last-K-rounds flight-recorder ring: "
                   "per-round phase wall times + rolling p50/p95)")
@click.option("--prom_port", type=int, default=None,
              help="Serve Prometheus text exposition on "
                   "http://127.0.0.1:PORT/metrics for the duration of the "
                   "run (comm byte/message counters, latency histograms, "
                   "client health gauges); 0 picks an ephemeral port "
                   "(printed to stderr). Off by default.")
@click.option("--no_device_cache", is_flag=True, default=False,
              help="Disable the HBM-resident data store (data/device_store.py)")
@click.option("--fused_rounds", type=int, default=1,
              help="Run up to N rounds as one on-device lax.scan chunk "
                   "(fedavg/fedprox + vmap runtime; needs the device cache)")
@click.option("--fused_plan", type=click.Choice(("static", "measured")),
              default="static",
              help="fused_rounds > 1: 'static' always fuses where possible "
                   "(legacy); 'measured' probes BOTH schedules over the "
                   "first rounds (flight-recorder phase costs) and commits "
                   "to the measured winner (algorithms/round_planner.py)")
@click.option("--pipeline", type=click.Choice(("off", "auto", "on")),
              default="auto",
              help="Round pipelining (sim runtimes): while round r runs on "
                   "device, prepare round r+1's cohort/batch/placement on "
                   "the host (algorithms/fedavg.py _pipeline_prepare). "
                   "Numerics are byte-identical to serial; adaptive "
                   "selection policies, active fault plans, fused chunks "
                   "and planner probe rounds degrade to serial "
                   "automatically. 'on' is an explicit alias of 'auto'")
@click.option("--client_parallelism", type=click.Choice(("auto", "vmap", "scan")),
              default="auto",
              help="How one chip runs the sampled clients: vmap (batched) "
                   "or scan (sequential — faster for conv models whose "
                   "small channels under-tile the MXU); auto picks per model")
@click.option("--state_store",
              type=click.Choice(("auto", "device", "mmap", "sharded")),
              default="auto",
              help="Where scaffold/ditto keep their per-client state: HBM "
                   "stack (device), disk spill with cohort-only HBM rows "
                   "(mmap: one memmap per pytree leaf; sharded: record-"
                   "major fixed-stride shards for million-client "
                   "populations — population/state_tier.py), or auto by "
                   "size vs --state_budget_bytes and population scale")
@click.option("--state_budget_bytes", type=int, default=8 << 30,
              help="state_store=auto: spill the per-client state to disk "
                   "past this many bytes (default 8 GiB)")
@click.option("--state_dir", type=str, default="",
              help="Directory for the spilled state store (default: a "
                   "fresh temp dir per run)")
@click.option("--straggle_ms", type=float, default=0.0,
              help="Simulated compute heterogeneity for THIS rank's "
                   "clients: sleep this long after every local training "
                   "(drives the straggler/async benchmarks)")
@click.option("--qffl_q", type=float, default=1.0,
              help="algorithm=qfedavg: fairness exponent q (0 = plain "
                   "FedAvg; larger = more uniform accuracy across clients)")
@click.option("--dp_clip", type=float, default=1.0,
              help="algorithm=dp_fedavg: per-client update L2 clip S")
@click.option("--dp_noise_multiplier", type=float, default=1.0,
              help="algorithm=dp_fedavg: noise multiplier z (stddev z*S "
                   "on the clipped-update sum)")
@click.option("--dp_delta", type=float, default=1e-5,
              help="algorithm=dp_fedavg: report epsilon at this delta")
@click.option("--ditto_lambda", type=float, default=0.1,
              help="algorithm=ditto: proximal pull of each personal model "
                   "toward the global model (0 = purely local models)")
@click.option("--async_buffer_k", type=int, default=10,
              help="algorithm=fedbuff: server applies one staleness-"
                   "weighted step whenever this many client deltas have "
                   "buffered (no round barrier; comm_round counts steps)")
@click.option("--staleness_exp", type=float, default=0.5,
              help="algorithm=fedbuff: staleness discount (1+tau)^-exp")
@click.option("--async_server_lr", type=float, default=1.0,
              help="algorithm=fedbuff: global step scale eta_g")
@click.option("--enable_wandb", is_flag=True, default=False,
              help="Start a wandb run and mirror metric rows to it (ref "
                   "main_fedavg.py:93-108); no-op if wandb is not installed")
@click.option("--selection",
              type=click.Choice(("uniform", "weighted", "power_of_choice",
                                 "straggler_aware")),
              default="uniform",
              help="Client selection policy (scheduler/policies.py): "
                   "reference-parity uniform, sample-count weighted, "
                   "loss-biased power-of-choice (Cho et al. 2020), or "
                   "straggler-avoiding (telemetry health registry). "
                   "Round-keyed + seed-deterministic; uniform/weighted "
                   "select identical cohorts across runtimes (see "
                   "docs/SCHEDULING.md for the adaptive policies)")
@click.option("--overprovision_factor", type=float, default=1.0,
              help="Select ceil(k * factor) clients per round so "
                   "deadline/quorum rounds still close with ~k useful "
                   "uploads; transport runtimes spawn one worker per "
                   "overprovisioned slot (1.0 = off)")
@click.option("--fault_plan", type=str, default=None,
              help="Fault-injection plan (scheduler/faults.py): inline "
                   "JSON or a path to a JSON file — per-client dropout_p/"
                   "slowdown_s/crash_at_round/flaky_upload_p, plus device "
                   "profiles ('profiles'/'fleet' keys) and scripted "
                   "per-round events; 'trace:<path>' replays a recorded "
                   "fault_trace.json byte-identically (the file "
                   "--telemetry_dir writes). Deterministic per (plan "
                   "seed, client, round). Sync transport runs with "
                   "participation faults require --deadline_s")
@click.option("--send_retries", type=int, default=0,
              help="Transport runtimes: retry a failed send up to N times "
                   "under seed-deterministic jittered exponential backoff "
                   "(core/retry.py; at-least-once — FedBuff/sync servers "
                   "dedupe re-deliveries). 0 = fail on first error. "
                   "Retry/give-up counts land in summary.json "
                   "(comm/retries, comm/gave_up) and Prometheus")
@click.option("--send_backoff_s", type=float, default=0.05,
              help="Retry backoff base in seconds (doubles per retry, "
                   "jittered, capped at CommConfig.send_backoff_max_s)")
@click.option("--send_timeout_s", type=float, default=30.0,
              help="runtime=grpc: per-RPC send deadline (was hard-coded "
                   "30 s). With --send_retries the retry layer owns "
                   "reconnects, so first contact also fails fast at this "
                   "timeout and retries instead of the one-shot 120 s "
                   "wait_for_ready handshake")
@click.option("--send_fault_p", type=float, default=0.0,
              help="Transport chaos: fail each send ATTEMPT with this "
                   "probability before it reaches the wire — "
                   "deterministic in (seed, send seq, attempt), so a "
                   "flaky-transport run replays identically; the "
                   "surviving attempt delivers exactly once (numerics "
                   "unchanged). Requires --send_retries >= 1")
@click.option("--deadline_s", type=float, default=0.0,
              help="Transport runtimes: straggler deadline — after this many "
                   "seconds the server closes the round on a quorum instead "
                   "of waiting forever (0 = ref-parity wait-for-all)")
@click.option("--min_clients", type=int, default=1,
              help="Minimum uploads required to close a deadline round")
@click.option("--compression", type=click.Choice(("none", "int8", "int4", "topk", "topk8")),
              default="none",
              help="Transport runtimes: compress the client uplink update "
                   "(core/compression.py) — int8/int4 (nibble-packed) "
                   "quantization, top-k sparsification, or topk8 (top-k "
                   "with int8 values) of the round delta")
@click.option("--downlink_compression", type=click.Choice(("none", "int8")),
              default="none",
              help="Transport runtimes: quantize the server->client model "
                   "broadcast int8 (encoded ONCE per round, shared across "
                   "the cohort; ~4x downlink cut). The server keeps the "
                   "dequantized tree as the round's reference, so both "
                   "wire ends train/decode against the identical model; "
                   "metered as comm/downlink_* in summary.json")
@click.option("--topk_frac", type=float, default=0.01,
              help="compression=topk/topk8: fraction of entries kept per tensor")
@click.option("--error_feedback", is_flag=True, default=False,
              help="Lossy codecs (topk/topk8/int4/int8): per-client residual "
                   "memory (EF-SGD) so dropped coordinates and quantization "
                   "error ship in later rounds; practically mandatory for "
                   "the 4-bit grid")
@click.option("--secure_agg", is_flag=True, default=False,
              help="Transport runtimes: pairwise-masked uploads — the "
                   "server only ever sums masked field vectors (ref "
                   "turboaggregate); quorum rounds recover dropout masks")
@click.option("--beacons/--no_beacons", default=True,
              help="Transport runtimes: piggyback a bounded (~200 B) client "
                   "telemetry beacon on each model upload — measured "
                   "train/encode seconds, retry count, codec, DeviceProfile "
                   "tier (telemetry/wire.py). Feeds the server's health "
                   "registry, flight recorder phase splits, and the "
                   "per-tier fleet digests (/fleet, fedml_fleet_*). "
                   "Observability only: numerics are byte-identical with "
                   "beacons off; overhead is metered separately as "
                   "comm/beacon_bytes and never counted as model payload")
@click.option("--warmup", is_flag=True, default=False,
              help="AOT-compile the run's programs before round 0 "
                   "(fedml_tpu/compile/warmup.py): round/eval/server "
                   "programs on vmap/mesh, the shared client local-train "
                   "on loopback/shm/mqtt (so --deadline_s rounds start "
                   "with compilation already paid). Emits compile "
                   "telemetry spans + per-program XLA cost analysis into "
                   "summary.json; numerics are identical to a cold run")
@click.option("--compile_cache_dir", type=click.Path(path_type=Path), default=None,
              help="Enable the hardened persistent XLA compile cache at "
                   "this directory (fedml_tpu/compile/persistent.py: "
                   "atomic writes, sha256 integrity verification with "
                   "quarantine, advisory file lock). Pass a fresh "
                   "directory for a per-run cache; cache hit/miss/"
                   "quarantine counts land in summary.json (compile/*)")
@click.option("--executable_cache", type=click.Path(path_type=Path), default=None,
              help="Persist SERIALIZED AOT executables at this directory "
                   "(compile/executable_cache.py, served through the "
                   "hardened store): --warmup exports every executable it "
                   "compiles, and a fresh process deserializes its whole "
                   "warmup set instead of compiling — zero-cold-start "
                   "restarts/replicas/CI shards. Keyed by program digest "
                   "+ shape class + environment fingerprint, so jaxlib/"
                   "backend/code skew recompiles cleanly. Deserialize "
                   "counts land in summary.json (compile/deserialize_*)")
@click.option("--compile_cache_min_s", type=float, default=2.0,
              help="Only persist HLO compiles at least this slow into "
                   "--compile_cache_dir (default 2.0 — the conservative "
                   "threshold tests/conftest.py uses). 0 persists every "
                   "compile: combined with --executable_cache this is the "
                   "zero-cold-start setting where a repeat process "
                   "reports compile/recompiles == 0")
@click.option("--recompile_budget", type=int, default=None,
              help="Fail the run when more than this many XLA compiles "
                   "happen (fedml_tpu/analysis/sentinel.py) — the tripwire "
                   "for cache-key instabilities that silently recompile "
                   "every round. Counts every ACTUAL backend compile incl. "
                   "small utility programs (persistent-cache hits and "
                   "deserialized executables are not compiles and don't "
                   "count — a fully warm process passes budget 0), so pick "
                   "a coarse upper bound; the observed count always lands "
                   "in summary.json (compile/recompiles). Off by default")
@click.option("--device_slice", type=int, default=-1,
              help="Serve-layer placement pin (AdminConfig.device_slice): "
                   "run this tenant on slice N of the service's device "
                   "slices (serve --device_slices; docs/SERVING.md). -1 = "
                   "bin-pack onto the least-loaded slice. Single runs "
                   "ignore it — the flag exists so tenant-spec keys stay "
                   "the single-run flag surface")
@click.option("--admit_min_headroom_mb", type=float, default=0.0,
              help="Serve-layer admission requirement: refuse this tenant "
                   "when host MemAvailable is below this many MB at the "
                   "admission door (serve/admission.py). 0 = none; single "
                   "runs ignore it")
@click.option("--admit_cost_cap_gflops", type=float, default=0.0,
              help="Serve-layer admission cap: refuse when the tenant's "
                   "priced compute (measured XLA cost-analysis flops x "
                   "cohort) exceeds this many GFLOP/round. 0 = none; "
                   "single runs ignore it")
@click.option("--rank", type=int, default=None,
              help="runtime=grpc: this process's rank (0 = server, 1..K = "
                   "clients; ref main_fedavg_rpc.py --fl_worker_index)")
@click.option("--ip_config", type=click.Path(path_type=Path), default=None,
              help="runtime=grpc: CSV rank,ip table (ref grpc_ipconfig.csv); "
                   "default localhost for all ranks")
@click.option("--base_port", type=int, default=8890)
@click.option("--ci", is_flag=True, default=False, help="CI short-circuit (1 round smoke)")
def main(**opt):
    """Train a federated model on TPU."""
    run(**opt)


def _dp_cfg(opt):
    if opt["algorithm"] != "dp_fedavg":
        return None
    from fedml_tpu.privacy import DpConfig

    clip = opt.get("dp_clip", 1.0)
    z = opt.get("dp_noise_multiplier", 1.0)
    delta = opt.get("dp_delta", 1e-5)
    # parse-time validation: z<=0 would otherwise crash the accountant
    # after data/model setup, and a negative clip would silently INVERT
    # every client update (scale = clip/norm < 0)
    if clip <= 0:
        raise click.UsageError("--dp_clip must be > 0")
    if z <= 0:
        raise click.UsageError(
            "--dp_noise_multiplier must be > 0 (no-noise runs are not DP; "
            "use --algorithm fedavg instead)"
        )
    if not 0.0 < delta < 1.0:
        raise click.UsageError("--dp_delta must be in (0, 1)")
    return DpConfig(clip_norm=clip, noise_multiplier=z, delta=delta)


def _validate_scheduler(config, opt) -> None:
    """Parse-time scheduler/fault-plan validation — a malformed plan or an
    unsatisfiable combination must fail before minutes of data/model
    setup, not as a mid-run hang."""
    from fedml_tpu.scheduler import FaultPlan

    if config.fed.overprovision_factor < 1.0:
        raise click.UsageError("--overprovision_factor must be >= 1.0")
    try:
        plan = FaultPlan.from_config(config)
    except ValueError as e:
        raise click.UsageError(f"--fault_plan: {e}")
    scheduler_engaged = (
        config.fed.selection != "uniform"
        or config.fed.overprovision_factor != 1.0
        or plan is not None
    )
    if opt["algorithm"] == "dp_fedavg" and scheduler_engaged:
        raise click.UsageError(
            "--selection/--overprovision_factor/--fault_plan cannot be "
            "combined with algorithm=dp_fedavg: its cohort is the "
            "run-seeded secret Poisson draw (privacy amplification by "
            "subsampling, privacy/dp_fedavg.py), which bypasses the "
            "scheduler — the flags would be silently ignored"
        )
    if opt["algorithm"] in _LONGTAIL and scheduler_engaged:
        # the long-tail drivers run their own fixed loops (uniform
        # sampling or no sampling at all) — accepting the flags there
        # would silently do nothing
        raise click.UsageError(
            "--selection/--overprovision_factor/--fault_plan have no "
            f"effect for algorithm={opt['algorithm']}: it drives its own "
            "fixed training loop outside the scheduler (supported: the "
            "FedAvg family, fedbuff, hierarchical, fedavg_robust)"
        )
    if config.fed.overprovision_factor != 1.0 and config.comm.secure_agg:
        raise click.UsageError(
            "--overprovision_factor and --secure_agg are incompatible: "
            "clients size the mask registry from client_num_per_round, so "
            "an overprovisioned worker set would not cancel its masks"
        )
    if config.fed.overprovision_factor != 1.0 and opt["algorithm"] == "fedbuff":
        raise click.UsageError(
            "--overprovision_factor is a synchronous quorum-round concept "
            "(select extra clients so deadline rounds close with ~k useful "
            "uploads); fedbuff has no rounds to overprovision — its "
            "workers stream continuously"
        )
    if (
        plan is not None
        and plan.has_participation_faults()
        and opt["runtime"] in ("loopback", "mqtt", "shm", "grpc")
        and opt["algorithm"] != "fedbuff"
        and not config.fed.deadline_s
    ):
        raise click.UsageError(
            "--fault_plan with dropout_p/crash_at_round on a synchronous "
            "transport requires --deadline_s: the all-received barrier "
            "would wait forever for the dropped upload"
        )


def _validate_comm_retry(config, opt) -> None:
    """Parse-time transport-retry validation: chaos without retries is a
    guaranteed mid-run crash, and the vmap/mesh runtimes exchange no
    messages for the flags to act on."""
    comm = config.comm
    if not 0.0 <= comm.send_fault_p < 1.0:
        raise click.UsageError("--send_fault_p must be in [0, 1)")
    if comm.send_retries < 0:
        raise click.UsageError("--send_retries must be >= 0")
    if comm.send_fault_p > 0 and comm.send_retries < 1:
        raise click.UsageError(
            "--send_fault_p injects transient send failures; without "
            "--send_retries >= 1 the first injected failure kills the "
            "sending actor instead of exercising the retry path"
        )
    if comm.send_timeout_s <= 0:
        raise click.UsageError("--send_timeout_s must be > 0")
    if (comm.send_retries or comm.send_fault_p) and opt["runtime"] in (
        "vmap", "mesh"
    ):
        raise click.UsageError(
            "--send_retries/--send_fault_p apply to the transport "
            "runtimes (loopback/shm/grpc/mqtt); vmap/mesh rounds exchange "
            "no messages, so the flags would be silently ignored"
        )


# Algorithms whose round-0 programs warmup_api/warmup_local_train can
# actually enumerate: the standard FedAvgAPI round/eval/server-step family.
# scaffold/ditto/dp_fedavg/hierarchical run bespoke train_round loops
# (their _build_round_fn is None or their cohorts reshape per group/draw),
# so warming there would either no-op or compile a program the run never
# dispatches — strictly worse than no flag. split_nn joined in PR 19:
# its fused/boundary/eval programs are digested ProgramCache factories
# warmed by compile/warmup.py:warmup_splitnn before round 0.
_WARMUP_ALGOS = (
    "fedavg", "fedprox", "fedopt", "fednova", "qfedavg", "fedavg_robust",
    "split_nn",
)


def _validate_compile(config, opt) -> None:
    """--warmup covers the algorithm×runtime combinations whose round-0
    programs can be enumerated up front; anywhere else the flag would
    silently do nothing (or waste a compile) — fail at parse time
    instead."""
    if not config.compile.warmup:
        return
    if opt["algorithm"] == "fedbuff":
        raise click.UsageError(
            "--warmup is not supported for algorithm=fedbuff: its workers "
            "stream continuously and compile on first dispatch; there is "
            "no round-0 barrier to warm against"
        )
    if opt["algorithm"] not in _WARMUP_ALGOS:
        raise click.UsageError(
            f"--warmup is not supported for algorithm={opt['algorithm']}: "
            "its driver builds its programs inside its own training loop, "
            "so there is no round-0 program to enumerate up front "
            f"(supported: {', '.join(_WARMUP_ALGOS)} on vmap/mesh and the "
            "sync transports)"
        )
    if opt["runtime"] == "grpc":
        raise click.UsageError(
            "--warmup is not supported for runtime=grpc: each client "
            "process owns its own programs — run the warmup in-process "
            "via the loopback/shm runtimes, or rely on a shared "
            "--compile_cache_dir to carry compiles across processes"
        )


def _log_compile(logger, baseline, restore=None, sentinel=None) -> None:
    """Forward the run's compile-cache activity (program dedup hits/misses
    + hardened persistent-layer counters) into summary.json — the CI
    oracle the ci.sh warmup smoke asserts on — then reinstate the
    pre-run persistent-cache binding (the row must be logged FIRST: it
    reads the run's installed cache). Called from the run() finally
    blocks so a crashed run can't leave its per-run cache installed in
    a long-lived process; the restore itself is exception-proof. A
    --recompile_budget sentinel is stopped and its counters logged here
    (observability first — the budget CHECK happens later, outside the
    finally, so the raise can't mask the run's own failure)."""
    from fedml_tpu.compile import compile_summary_row

    try:
        if sentinel is not None:
            sentinel.stop()
            logger.log(sentinel.summary_row())
        logger.log(compile_summary_row(baseline))
    finally:
        if restore is not None:
            restore()


def _check_sentinel(sentinel) -> None:
    """Enforce --recompile_budget after the run's telemetry has flushed:
    exceeding the budget fails the CLI run loudly (exit code 1) with the
    per-program compile events in the message."""
    if sentinel is None:
        return
    from fedml_tpu.analysis.sentinel import RecompileBudgetExceeded

    try:
        sentinel.check()
    except RecompileBudgetExceeded as e:
        raise click.ClickException(str(e))


def _checked_buffer_k(opt) -> int:
    """fedbuff's buffer size, validated at parse time (a 0/negative k would
    otherwise surface as a mid-run ValueError after data/model setup); 0
    for every synchronous algorithm."""
    if opt["algorithm"] != "fedbuff":
        return 0
    k = opt.get("async_buffer_k", 10)
    if k <= 0:
        raise click.UsageError("--algorithm fedbuff needs --async_buffer_k > 0")
    return k


def build_config(opt) -> RunConfig:
    return RunConfig(
        data=DataConfig(
            dataset=opt["dataset_name"],
            data_dir=str(opt["data_dir"]),
            partition_method=opt["partition_method"],
            partition_alpha=opt["partition_alpha"],
            batch_size=opt["batch_size"],
            pad_bucket=opt["pad_bucket"],
            device_cache=not opt.get("no_device_cache", False),
        ),
        fed=FedConfig(
            client_num_in_total=opt["client_num_in_total"],
            client_num_per_round=opt["client_num_per_round"],
            comm_round=1 if opt["ci"] else opt["comm_round"],
            epochs=opt["epochs"],
            frequency_of_the_test=opt["frequency_of_the_test"],
            ci=opt["ci"],
            group_num=opt["group_num"],
            group_comm_round=opt["group_comm_round"],
            fused_rounds=opt.get("fused_rounds", 1),
            fused_plan=opt.get("fused_plan", "static"),
            eval_on_clients=opt.get("eval_on_clients", False),
            deadline_s=opt.get("deadline_s", 0.0),
            min_clients=opt.get("min_clients", 1),
            selection=opt.get("selection", "uniform"),
            overprovision_factor=opt.get("overprovision_factor", 1.0),
            fault_plan=opt.get("fault_plan") or "",
            client_parallelism=opt.get("client_parallelism", "auto"),
            async_buffer_k=_checked_buffer_k(opt),
            async_staleness_exp=opt.get("staleness_exp", 0.5),
            async_server_lr=opt.get("async_server_lr", 1.0),
            state_store=opt.get("state_store", "auto"),
            state_budget_bytes=opt.get("state_budget_bytes", 8 << 30),
            state_dir=opt.get("state_dir", ""),
            pipeline=opt.get("pipeline", "auto"),
        ),
        train=TrainConfig(
            client_optimizer=opt["client_optimizer"],
            lr=opt["lr"],
            wd=opt["wd"],
            momentum=opt["momentum"],
            prox_mu=opt["prox_mu"] if opt["algorithm"] == "fedprox" else 0.0,
            compute_dtype=opt.get("compute_dtype", "float32"),
            augment=opt.get("augment", "none"),
        ),
        server=ServerConfig(
            server_optimizer=opt["server_optimizer"],
            server_lr=opt["server_lr"],
            server_momentum=opt["server_momentum"],
        ),
        comm=CommConfig(
            compression=opt.get("compression", "none"),
            downlink_compression=opt.get("downlink_compression", "none"),
            topk_frac=opt.get("topk_frac", 0.01),
            error_feedback=opt.get("error_feedback", False),
            secure_agg=opt.get("secure_agg", False),
            send_retries=opt.get("send_retries", 0) or 0,
            send_backoff_s=opt.get("send_backoff_s", 0.05),
            send_timeout_s=opt.get("send_timeout_s", 30.0),
            send_fault_p=opt.get("send_fault_p", 0.0) or 0.0,
            beacons=opt.get("beacons", True),
        ),
        mesh=MeshConfig(client_shards=opt["client_shards"]),
        compile=CompileConfig(
            warmup=opt.get("warmup", False),
            cache_dir=str(opt.get("compile_cache_dir") or ""),
            min_compile_time_s=opt.get("compile_cache_min_s", 2.0),
            executable_cache=str(opt.get("executable_cache") or ""),
            recompile_budget=opt.get("recompile_budget"),
        ),
        admin=AdminConfig(
            device_slice=int(
                opt["device_slice"]
                if opt.get("device_slice") is not None else -1
            ),
            admit_min_headroom_mb=float(
                opt.get("admit_min_headroom_mb", 0.0) or 0.0
            ),
            admit_cost_cap_gflops=float(
                opt.get("admit_cost_cap_gflops", 0.0) or 0.0
            ),
        ),
        model=opt["model"],
        seed=opt["seed"],
    )


def _telemetry_start(opt, config=None):
    """Start run-scoped telemetry sinks (the tracer itself is always on —
    spans cost microseconds; these flags decide whether anything is
    EXPORTED). Returns an opaque state for _telemetry_finish, or None when
    no telemetry flag is set. ``config`` supplies the flight-recorder
    ring bounds (PopulationConfig.flight_*)."""
    if opt.get("prom_port") is None and opt.get("telemetry_dir") is None:
        return None
    from fedml_tpu.telemetry import FlightRecorder, get_comm_meter, get_tracer

    # run-scoped trace + comm totals: the exported trace.json and the
    # summary.json telemetry row describe THIS run, not whatever earlier
    # runs happened in the same process (CliRunner tests, notebook sweeps)
    get_tracer().reset()
    # fleet digests (telemetry/wire.py): per-tier latency percentiles fed
    # by client beacons — run-scoped like the tracer, for the same reason
    from fedml_tpu.telemetry import get_fleet

    get_fleet().reset()
    state = {"exporter": None, "comm_baseline": get_comm_meter().snapshot()}
    # flight recorder (telemetry/flight.py): fold the run's round spans
    # into the bounded last-K ring — flight/* summary block + flight.json
    # under --telemetry_dir, p50/p95 gauges under --prom_port
    from fedml_tpu.analysis.sentinel import global_recompiles

    flight_kw = dict(
        comm_meter=get_comm_meter(), recompiles_fn=global_recompiles
    )
    state["flight"] = (
        FlightRecorder.from_config(config, **flight_kw)
        if config is not None else FlightRecorder(**flight_kw)
    ).attach(get_tracer())
    if opt.get("prom_port") is not None:
        from fedml_tpu.telemetry import PrometheusExporter

        # compile observability (satellite of fedml_tpu/analysis/): the
        # ProgramCache publishes its hit/miss/bypass gauges on every
        # event; the XLA backend-compile gauge needs the process-wide
        # monitoring listener installed — do it whenever metrics are
        # actually exported, not only under --recompile_budget
        from fedml_tpu.analysis.sentinel import ensure_backend_listener

        ensure_backend_listener()

        state["exporter"] = PrometheusExporter(port=opt["prom_port"]).start()
        # /fleet: the live per-tier beacon digest snapshot, next to
        # /metrics (serve runs get it via RoundIntrospection.install)
        state["exporter"].add_route(
            "/fleet", lambda _path: (200, get_fleet().snapshot())
        )
        click.echo(
            f"telemetry: prometheus metrics on "
            f"http://127.0.0.1:{state['exporter'].port}/metrics",
            err=True,
        )
    return state


def _telemetry_finish(state, opt, logger, health=None):
    """Flush run telemetry: forward comm totals into MetricsLogger (so
    summary.json stays the single CI oracle), write the Chrome trace +
    health registry snapshot into --telemetry_dir, stop the exporter.
    Idempotent — the run paths call it on success (with the runtime's
    health registry) and again from their exception backstop (a crashed
    run must still flush its trace: that is exactly when you want it)."""
    if state is None or state.get("done"):
        return
    state["done"] = True
    from fedml_tpu.telemetry import get_fleet, get_tracer, telemetry_summary

    logger.log(telemetry_summary(baseline=state.get("comm_baseline")))
    fleet_row = get_fleet().summary_row()
    if fleet_row.get("fleet/beacons"):
        logger.log(fleet_row)  # the fleet/* summary block (beacon digests)
    flight = state.get("flight")
    if flight is not None:
        logger.log(flight.summary_row())  # the flight/* summary block
        flight.detach()
    tdir = opt.get("telemetry_dir")
    if tdir:
        tdir = Path(tdir)
        tdir.mkdir(parents=True, exist_ok=True)
        suffix = _telemetry_suffix(opt)
        trace_path = tdir / f"trace{suffix}.json"
        get_tracer().write_chrome_trace(str(trace_path))
        if flight is not None:
            with open(tdir / f"flight{suffix}.json", "w") as f:
                json.dump(
                    {
                        "rounds_folded": flight.rounds_folded,
                        "ring_capacity": flight.capacity,
                        "percentiles": flight.percentiles(),
                        "records": flight.tail(),
                    },
                    f, indent=2,
                )
        if health is not None:
            with open(tdir / f"health{suffix}.json", "w") as f:
                json.dump(health.snapshot(), f, indent=2)
            if hasattr(health, "export_trace") and opt.get("algorithm") != "fedbuff":
                # the observed fleet as a replayable FaultTrace
                # (scheduler/faults.py): --fault_plan trace:<this file>
                # re-injects the exact recorded dropout/slowdown/flaky
                # events, byte-identically (docs/SCHEDULING.md). FedBuff
                # records fault events keyed by DISPATCH TAG, not round —
                # such a trace cannot replay faithfully, so none is
                # written (trace replay targets the round-keyed runtimes)
                health.export_trace(
                    rounds=1 if opt.get("ci") else opt.get("comm_round")
                ).save(str(tdir / f"fault_trace{suffix}.json"))
        click.echo(f"telemetry: wrote {trace_path}", err=True)
    if state.get("exporter") is not None:
        state["exporter"].stop()


def _telemetry_suffix(opt) -> str:
    """Disambiguate telemetry files when several processes share one
    --telemetry_dir: gRPC ranks get .rankN, multi-host SPMD processes get
    .hostK (each then merges cleanly in Perfetto — the tracks are already
    labeled per host). Single-process runs keep the bare names."""
    rank = opt.get("rank")
    if rank is not None:
        return f".rank{rank}"
    try:
        import jax

        if jax.process_count() > 1:
            return f".host{jax.process_index()}"
    except Exception:  # noqa: BLE001 — backend-less finalize must not fail
        pass
    return ""


def _apply_platform_env():
    """Honor JAX_PLATFORMS for CLI runs. This container's sitecustomize
    pins a TPU backend at interpreter startup, so the env var alone never
    wins (the exact pitfall tests/conftest.py and the dryrun bootstrap
    document) — re-apply it through jax.config BEFORE any backend touch so
    `JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8`
    gives CLI mesh runs the virtual device farm, as examples/ci.sh relies
    on."""
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception as e:  # backend already initialized
            import logging

            logging.warning(
                "JAX_PLATFORMS=%s could not be applied (%s) — the backend "
                "was already initialized; the run continues on platform %s",
                plat, e, jax.default_backend(),
            )


def run(**opt):
    _apply_platform_env()
    from fedml_tpu.data import registry as data_registry
    from fedml_tpu.models import create_model
    from fedml_tpu.utils import MetricsLogger, save_checkpoint
    from fedml_tpu.utils.profiling import trace

    config = build_config(opt)
    # validate DP flags BEFORE data/model setup (a z<=0 would otherwise
    # surface as a mid-run crash after minutes of dataset loading); the
    # result is rebuilt at the _build_api call site
    _dp_cfg(opt)
    _validate_scheduler(config, opt)
    _validate_compile(config, opt)
    _validate_comm_retry(config, opt)
    restore_compile_cache = None
    if config.compile.cache_dir:
        # BEFORE any jit: every compile of this run should be eligible
        # for the hardened persistent store (compile/persistent.py).
        # install_run_cache hands back a restore() that reinstates the
        # previous binding when the run completes, so a run embedded in a
        # long-lived process can't hijack later compiles onto its (maybe
        # deleted) cache dir.
        from fedml_tpu.compile import install_run_cache

        _, restore_compile_cache = install_run_cache(
            config.compile.cache_dir,
            min_compile_time_secs=config.compile.min_compile_time_s,
        )
    if config.compile.executable_cache:
        # serialized-executable store (zero-cold-start): like the HLO
        # cache above, installed run-scoped with a composed restore so a
        # crashed/embedded run can't leave it bound process-wide
        from fedml_tpu.compile import install_run_executable_cache

        _, _restore_exec = install_run_executable_cache(
            config.compile.executable_cache
        )
        _restore_hlo = restore_compile_cache

        def restore_compile_cache() -> None:  # noqa: F811 — composed restore
            _restore_exec()
            if _restore_hlo is not None:
                _restore_hlo()

    from fedml_tpu.compile import compile_snapshot

    # baseline for the summary.json compile row: a run embedded in a
    # long-lived process (CliRunner tests, sweeps) reports ITS cache
    # activity, not the process's lifetime totals
    compile_baseline = compile_snapshot()
    sentinel = None
    if config.compile.recompile_budget is not None:
        # --recompile_budget: watch every XLA backend compile from here
        # to the end of the run (fedml_tpu/analysis/sentinel.py); the
        # check fires after telemetry flushes, via _check_sentinel
        from fedml_tpu.analysis.sentinel import RecompileSentinel

        if config.compile.recompile_budget < 0:
            raise click.UsageError("--recompile_budget must be >= 0")
        sentinel = RecompileSentinel(
            budget=config.compile.recompile_budget, label="cli"
        ).start()
    try:
        if opt["runtime"] in ("vmap", "mesh"):
            if config.comm.compression != "none":
                raise click.UsageError(
                    "--compression applies to the transport runtimes "
                    "(loopback/shm/grpc/mqtt); the vmap/mesh runtimes exchange "
                    "no messages, so the flag would be silently ignored"
                )
            if config.comm.downlink_compression != "none":
                raise click.UsageError(
                    "--downlink_compression applies to the transport runtimes "
                    "(loopback/shm/grpc/mqtt); the vmap/mesh runtimes exchange "
                    "no messages, so the flag would be silently ignored"
                )
            if config.fed.deadline_s or config.fed.min_clients != 1:
                raise click.UsageError(
                    "--deadline_s/--min_clients apply to the transport runtimes "
                    "(loopback/shm/grpc/mqtt); vmap/mesh rounds are one SPMD "
                    "program with no uploads to time out on"
                )
        elif config.fed.min_clients != 1 and not config.fed.deadline_s:
            raise click.UsageError(
                "--min_clients only takes effect after a --deadline_s deadline "
                "passes; without one the server still waits for every client"
            )
        if config.comm.secure_agg:
            if opt["runtime"] in ("vmap", "mesh"):
                raise click.UsageError(
                    "--secure_agg applies to the transport runtimes "
                    "(loopback/shm/grpc/mqtt)"
                )
            if config.comm.compression != "none":
                raise click.UsageError(
                    "--secure_agg and --compression are mutually exclusive: "
                    "masked field vectors cannot be sparsified/quantized"
                )
            if config.comm.downlink_compression != "none":
                raise click.UsageError(
                    "--secure_agg and --downlink_compression are mutually "
                    "exclusive: masked uploads are field vectors over the "
                    "exact broadcast reference, which requantizing would break"
                )
        if config.comm.error_feedback:
            from fedml_tpu.core.compression import EF_METHODS

            if config.comm.compression not in EF_METHODS:
                raise click.UsageError(
                    "--error_feedback is a residual memory for lossy codecs; "
                    f"it requires --compression in {EF_METHODS}"
                )
            if config.fed.deadline_s:
                raise click.UsageError(
                    "--error_feedback assumes every upload is aggregated, but "
                    "--deadline_s quorum rounds can discard late uploads — the "
                    "shipped (and residual-cleared) coordinates would be lost"
                )
            if (
                opt["runtime"] == "grpc"
                and config.fed.client_num_per_round != config.fed.client_num_in_total
            ):
                raise click.UsageError(
                    "--error_feedback under runtime=grpc requires full "
                    "participation (client_num_per_round == client_num_in_total): "
                    "residuals live per process and cannot follow a client that "
                    "the sampler re-assigns to another rank"
                )
        data = data_registry.load(config)
        task = data_registry.task_for_dataset(config.data.dataset)
        sample_shape = tuple(data.client_x[0].shape[1:])
        model = create_model(config.model, config.data.dataset, sample_shape, data.num_classes)

        poison_spec = attack_cfg = None
        if opt.get("attack", "none") == "backdoor":
            if opt["algorithm"] != "fedavg_robust" or opt["runtime"] != "vmap":
                raise click.UsageError(
                    "--attack backdoor requires --algorithm fedavg_robust "
                    "--runtime vmap"
                )
            from fedml_tpu.data.edge_cases import PoisonSpec, poison_clients
            from fedml_tpu.robustness.backdoor import AttackConfig

            k = opt.get("num_attackers", 1)
            if not 0 < k < data.num_clients:
                raise click.UsageError(
                    f"--num_attackers must be in [1, {data.num_clients - 1}]"
                )
            poison_spec = PoisonSpec(
                target_label=opt.get("target_label", 0),
                poison_frac=opt.get("poison_frac", 0.5),
            )
            # attacker ids derived ONCE — the poisoned shards and the boosted
            # uploads must target the same client set
            attack_cfg = AttackConfig(
                attacker_ids=tuple(range(k)),
                boost=opt.get("attack_boost", 10.0),
            )
            data = poison_clients(
                data, attacker_ids=attack_cfg.attacker_ids, spec=poison_spec,
                seed=config.seed,
            )

        if opt.get("enable_wandb"):
            from fedml_tpu.utils.metrics import wandb_init

            wandb_init(
                name=f"{opt['algorithm']}-r{opt['comm_round']}"
                f"-e{opt['epochs']}-lr{opt['lr']}",
                config={k: str(v) for k, v in opt.items()},
            )
        logger = MetricsLogger(
            str(opt["log_dir"]) if opt["log_dir"] else None,
            use_wandb=opt.get("enable_wandb", False),
        )
        telemetry = _telemetry_start(opt, config)
        api_cell = []

        def log_fn(row):
            logger.log(row)
            # crash-resumable: persist on every test round, not just at the end.
            # round_idx convention = "next round to run": row["round"] just
            # completed, so the continuation starts at row["round"] + 1.
            if opt["checkpoint_path"] and "Test/Acc" in row and api_cell:
                api = api_cell[0]
                gv = getattr(api, "global_vars", None)
                if gv is not None:
                    save_checkpoint(
                        str(opt["checkpoint_path"]),
                        gv,
                        round_idx=row["round"] + 1,
                        server_opt_state=getattr(api, "server_opt_state", None),
                        algo_state=getattr(
                            api, "checkpoint_state", lambda: None
                        )(),
                        sched_state=_sched_state(api),
                    )

        _validate_variant(opt)
        if opt["runtime"] == "grpc":
            # true multi-process federation: this process is ONE participant
            # (ref main_fedavg_rpc.py per-process drivers + run_*.sh launchers)
            if opt["algorithm"] not in ("fedavg", "fedprox", "fedopt", "fedbuff"):
                raise click.UsageError(
                    "runtime=grpc supports fedavg/fedprox/fedopt/fedbuff"
                )
            try:
                final, grpc_health = _run_grpc_process(
                    config, data, model, task, log_fn, opt
                )
                _telemetry_finish(telemetry, opt, logger, health=grpc_health)
            finally:
                _telemetry_finish(telemetry, opt, logger)
                _log_compile(
                    logger, compile_baseline, restore_compile_cache, sentinel
                )
            _check_sentinel(sentinel)
            logger.close()
            click.echo(json.dumps({k: _jsonable(v) for k, v in (final or {}).items()}))
            return None

        builder = _LONGTAIL.get(opt["algorithm"])
        if builder is not None:
            if opt["resume"]:
                raise click.UsageError(
                    f"--resume is not supported for algorithm={opt['algorithm']}"
                )
            allowed_runtimes = (
                ("vmap", "mesh") if opt["algorithm"] == "centralized" else ("vmap",)
            )
            if opt["runtime"] not in allowed_runtimes:
                raise click.UsageError(
                    f"algorithm={opt['algorithm']} supports only "
                    f"--runtime {'|'.join(allowed_runtimes)}"
                )
            if opt["checkpoint_path"] and opt["algorithm"] != "fedseg":
                # fail loudly rather than let a 50-round run discover at crash
                # time that nothing was ever saved
                raise click.UsageError(
                    f"--checkpoint_path is not supported for algorithm="
                    f"{opt['algorithm']} (supported: the FedAvg family and fedseg)"
                )
            try:
                with trace(str(opt["profile_dir"]) if opt["profile_dir"] else None):
                    final = builder(config, data, model, task, log_fn, opt)
            finally:
                # long-tail drivers have no per-client health registry; the
                # trace/comm totals still flush (on success AND on a crash)
                _telemetry_finish(telemetry, opt, logger)
                _log_compile(
                    logger, compile_baseline, restore_compile_cache, sentinel
                )
            _check_sentinel(sentinel)
            logger.close()
            click.echo(json.dumps({k: _jsonable(v) for k, v in (final or {}).items()}))
            return None

        api = _build_api(
            opt["algorithm"], opt["runtime"], config, data, model, task, log_fn,
            defense=opt.get("defense", "norm_diff_clipping"),
            num_byzantine=opt.get("num_byzantine", 1),
            multi_krum_m=opt.get("multi_krum_m", 3),
            norm_bound=opt.get("norm_bound", 5.0),
            noise_stddev=opt.get("noise_stddev", 0.025),
            attack_cfg=attack_cfg,
            ditto_lambda=opt.get("ditto_lambda", 0.1),
            dp_cfg=_dp_cfg(opt),
            qffl_q=opt.get("qffl_q", 1.0),
        )
        api_cell.append(api)

        if opt["resume"]:
            if opt["runtime"] in ("loopback", "mqtt", "shm"):
                raise click.UsageError(
                    f"--resume is not supported for runtime={opt['runtime']}"
                )
            _restore(api, opt)

        if config.compile.warmup and hasattr(api, "warmup"):
            # vmap/mesh: AOT-compile round/eval/server programs before round 0
            # (the transport _Runner has no .warmup — run_federation takes the
            # flag and warms the shared local-train program instead)
            api.warmup(log_fn=log_fn)

        try:
            with trace(str(opt["profile_dir"]) if opt["profile_dir"] else None):
                final = api.train()
            if getattr(api, "faults", None) is not None:
                # vmap/mesh fault accounting into summary.json (the transport
                # runners log their shared injector themselves)
                log_fn(api.faults.summary_row())
            if getattr(api, "planner", None) is not None:
                # measured fused-vs-eager planner: committed schedule +
                # both arms' probed per-round costs (flight/planner_*) —
                # the ci.sh fused-vs-eager gate reads the winner here
                log_fn(api.planner.summary_row())
            if getattr(api, "pipeline_rounds", 0):
                # round pipeline: rounds whose host prep was hidden behind
                # the previous round's device dispatch (FedConfig.pipeline;
                # the per-round overlap seconds fold into flight/overlap_s)
                log_fn({"fed/pipeline_rounds": int(api.pipeline_rounds)})
            if poison_spec is not None:
                from fedml_tpu.data.edge_cases import attack_success_rate

                final = dict(final or {})
                final["Backdoor/ASR"] = attack_success_rate(
                    model, api.global_vars, data, poison_spec, eval_fn=api.eval_fn
                )
                # persist the attack metric alongside the per-round rows
                log_fn({
                    "round": config.fed.comm_round - 1,
                    "Backdoor/ASR": final["Backdoor/ASR"],
                })
            if opt["checkpoint_path"]:
                save_checkpoint(
                    str(opt["checkpoint_path"]),
                    getattr(api, "global_vars"),
                    round_idx=config.fed.comm_round,
                    server_opt_state=getattr(api, "server_opt_state", None),
                    algo_state=getattr(api, "checkpoint_state", lambda: None)(),
                    sched_state=_sched_state(api),
                )
            _telemetry_finish(
                telemetry, opt, logger, health=getattr(api, "health", None)
            )
        finally:
            # exception backstop: flush the trace and stop the exporter even
            # when the run crashed mid-train (idempotent after the call above);
            # the compile row + cache restore ride the same backstop so a
            # crashed run can't leave its per-run cache installed
            _telemetry_finish(telemetry, opt, logger)
            _log_compile(
                logger, compile_baseline, restore_compile_cache, sentinel
            )
        _check_sentinel(sentinel)
        logger.close()
        click.echo(json.dumps({k: _jsonable(v) for k, v in (final or {}).items()}))
        return api
    except BaseException:
        # a validation/setup failure BEFORE (or inside) a dispatch
        # path's own finally must not leave the per-run compile cache
        # installed process-wide (the CliRunner/sweep hijack the
        # install_run_cache docstring describes). restore() reinstates
        # a fixed prior snapshot, so paths that already restored via
        # _log_compile are unaffected by the second call.
        if restore_compile_cache is not None:
            restore_compile_cache()
        if sentinel is not None:
            sentinel.stop()  # idempotent; drops the cache listener
        raise


_VARIANTS = {
    "decentralized": ("dsgd", "pushsum"),
    "fednas": ("first", "second"),
}


def _validate_variant(opt):
    v = opt.get("variant")
    if v is None:
        return
    allowed = _VARIANTS.get(opt["algorithm"])
    if allowed is None:
        raise click.UsageError(
            f"--variant has no meaning for algorithm={opt['algorithm']}"
        )
    if v not in allowed:
        raise click.UsageError(
            f"--variant for {opt['algorithm']} must be one of {allowed}, got {v!r}"
        )


def _jsonable(v):
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    return v


def _sched_state(api):
    """Scheduler RNG/selection state for the checkpoint's "sched" slot —
    a resumed run re-selects the in-flight round's cohort identically."""
    sched = getattr(api, "scheduler", None)
    return sched.state_dict() if sched is not None else None


def _restore(api, opt):
    """--resume: pour the checkpoint into the API and continue the round
    loop from the saved round (round-seeded sampling makes the continuation
    identical to the uninterrupted run — the kill-and-resume test relies on
    it)."""
    from fedml_tpu.utils.checkpoint import load_checkpoint, restore_like

    if not opt["checkpoint_path"]:
        raise click.UsageError("--resume requires --checkpoint_path")
    loaded_vars, round_idx, _, opt_state, algo_state, sched_state = load_checkpoint(
        str(opt["checkpoint_path"])
    )
    api.global_vars = restore_like(api.global_vars, loaded_vars)
    api.start_round = int(round_idx)
    # Server optimizer state (FedOpt family): restore so Adam/Yogi moments
    # survive the crash — per-round RNG is derived from (seed, round) and
    # needs no persistence.
    if opt_state is not None and getattr(api, "server_opt_state", None) is not None:
        api.server_opt_state = restore_like(api.server_opt_state, opt_state)
    # Algorithm-private state (SCAFFOLD control variates): without this a
    # resumed run silently degenerates to FedAvg until the variates
    # re-learn, breaking the identical-continuation contract above.
    # Scheduler selection memo + loss map: without it a resumed
    # power_of_choice run would re-derive the in-flight cohort from an
    # empty loss map and select differently than the uninterrupted run.
    if sched_state is not None and getattr(api, "scheduler", None) is not None:
        api.scheduler.load_state_dict(sched_state)
    if hasattr(api, "restore_state"):
        if algo_state is None:
            raise click.UsageError(
                "checkpoint has no algorithm state but "
                f"{type(api).__name__} needs it to resume faithfully — "
                "it was written by an older version or a different "
                "algorithm; restarting from round 0 is the only sound "
                "continuation"
            )
        api.restore_state(algo_state)


def _build_api(algorithm, runtime, config, data, model, task, log_fn,
               defense="norm_diff_clipping", num_byzantine=1, multi_krum_m=3,
               norm_bound=5.0, noise_stddev=0.025, attack_cfg=None,
               ditto_lambda=0.1, dp_cfg=None, qffl_q=1.0):
    from fedml_tpu.robustness import RobustConfig

    # one RobustConfig for whichever runtime's robust API is selected —
    # vmap and mesh must see identical defense parameters
    robust = RobustConfig(
        defense_type=defense,
        norm_bound=norm_bound,
        stddev=noise_stddev,
        num_byzantine=num_byzantine,
        multi_krum_m=multi_krum_m,
    )
    if runtime in ("loopback", "mqtt", "shm"):
        if algorithm == "fedbuff":
            from fedml_tpu.algorithms import fedbuff as FB

            runner_fn = {
                "loopback": FB.run_fedbuff_loopback,
                "shm": FB.run_fedbuff_shm,
                "mqtt": FB.run_fedbuff_mqtt,
            }[runtime]

            class _AsyncRunner:
                global_vars = None
                server_opt_state = None
                start_round = 0
                health = None

                def train(self):
                    server = runner_fn(
                        config, data, model, task=task, log_fn=log_fn,
                    )
                    self.global_vars = server.global_vars
                    self.health = server.health
                    return server.history[-1] if server.history else {}

            return _AsyncRunner()
        if algorithm not in ("fedavg", "fedprox", "fedopt"):
            raise click.UsageError(
                f"runtime={runtime} supports fedavg/fedprox/fedopt/fedbuff"
            )
        from fedml_tpu.algorithms.fedavg_transport import (
            run_loopback_federation,
            run_mqtt_federation,
            run_shm_federation,
        )

        runner_fn = {
            "mqtt": run_mqtt_federation,
            "shm": run_shm_federation,
            "loopback": run_loopback_federation,
        }[runtime]

        class _Runner:
            global_vars = None
            server_opt_state = None
            start_round = 0
            health = None

            def train(self):
                server = runner_fn(
                    config, data, model, task=task, log_fn=log_fn,
                    server_opt=algorithm == "fedopt",
                    warmup=config.compile.warmup,
                )
                self.global_vars = server.global_vars
                # expose the FedOpt moments so --checkpoint_path persists
                # them (the vmap --resume path restores from this slot)
                self.server_opt_state = server._server_opt_state
                self.health = server.health
                return server.history[-1] if server.history else {}

        return _Runner()

    if algorithm == "fedbuff":
        raise click.UsageError(
            "algorithm=fedbuff is an async TRANSPORT protocol — run it "
            "with --runtime loopback, shm, or mqtt"
        )
    if runtime == "mesh":
        from fedml_tpu.parallel import DistributedFedAvgAPI, DistributedFedOptAPI

        if algorithm == "fedopt":
            return DistributedFedOptAPI(
                config, data, model, task=task, log_fn=log_fn
            )
        if algorithm == "fedavg_robust":
            from fedml_tpu.parallel import RobustDistributedFedAvgAPI

            return RobustDistributedFedAvgAPI(
                config, data, model, task=task, log_fn=log_fn, robust=robust
            )
        if algorithm == "fednova":
            from fedml_tpu.parallel import DistributedFedNovaAPI

            return DistributedFedNovaAPI(
                config, data, model, task=task, log_fn=log_fn
            )
        if algorithm == "scaffold":
            from fedml_tpu.parallel import DistributedScaffoldAPI

            return DistributedScaffoldAPI(
                config, data, model, task=task, log_fn=log_fn
            )
        if algorithm == "ditto":
            from fedml_tpu.parallel import DistributedDittoAPI

            return DistributedDittoAPI(
                config, data, model, task=task, log_fn=log_fn,
                lam=ditto_lambda,
            )
        if algorithm == "dp_fedavg":
            from fedml_tpu.parallel import DistributedDPFedAvgAPI

            return DistributedDPFedAvgAPI(
                config, data, model, task=task, log_fn=log_fn, dp=dp_cfg,
            )
        if algorithm == "hierarchical":
            from fedml_tpu.parallel import HierarchicalShardedAPI

            # default mesh = hybrid groups×clients from config.fed.group_num
            return HierarchicalShardedAPI(
                config, data, model, task=task, log_fn=log_fn
            )
        if algorithm not in ("fedavg", "fedprox"):
            raise click.UsageError(
                "runtime=mesh currently supports fedavg/fedprox/fedopt/"
                "fednova/scaffold/ditto/dp_fedavg/hierarchical/fedavg_robust"
            )
        return DistributedFedAvgAPI(config, data, model, task=task, log_fn=log_fn)

    # vmap simulator runtimes (ref standalone/*)
    if algorithm in ("fedavg", "fedprox"):
        from fedml_tpu.algorithms import FedAvgAPI

        return FedAvgAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "fedopt":
        from fedml_tpu.algorithms import FedOptAPI

        return FedOptAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "fednova":
        from fedml_tpu.algorithms import FedNovaAPI

        return FedNovaAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "scaffold":
        from fedml_tpu.algorithms.scaffold import ScaffoldAPI

        return ScaffoldAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "ditto":
        from fedml_tpu.algorithms.ditto import DittoAPI

        return DittoAPI(
            config, data, model, task=task, log_fn=log_fn, lam=ditto_lambda,
        )
    if algorithm == "dp_fedavg":
        from fedml_tpu.privacy import DpConfig, DPFedAvgAPI

        return DPFedAvgAPI(
            config, data, model, task=task, log_fn=log_fn, dp=dp_cfg or DpConfig(),
        )
    if algorithm == "qfedavg":
        from fedml_tpu.algorithms.qfedavg import QFedAvgAPI

        return QFedAvgAPI(
            config, data, model, task=task, log_fn=log_fn, q=qffl_q,
        )
    if algorithm == "hierarchical":
        from fedml_tpu.algorithms import HierarchicalFedAvgAPI

        return HierarchicalFedAvgAPI(config, data, model, task=task, log_fn=log_fn)
    if algorithm == "fedavg_robust":
        from fedml_tpu.algorithms.fedavg_robust import RobustFedAvgAPI

        if attack_cfg is not None:
            from fedml_tpu.robustness.backdoor import BackdoorFedAvgAPI

            return BackdoorFedAvgAPI(
                config, data, model, task=task, log_fn=log_fn, robust=robust,
                attack=attack_cfg,
            )
        return RobustFedAvgAPI(
            config, data, model, task=task, log_fn=log_fn, robust=robust,
        )
    raise click.UsageError(f"unknown algorithm {algorithm}")


# ---------------------------------------------------------------------------
# Long-tail drivers: algorithms whose APIs are not FedAvgAPI-shaped. Each
# takes the standard flag surface and runs a complete training loop
# (replacing ref drivers main_fedgkt.py, main_fedgan.py, main_fednas.py,
# main_split_nn.py, main_vfl.py, main_decentralized.py, TA_main).
# ---------------------------------------------------------------------------


def _client_shards_list(data, limit=None):
    ids = range(data.num_clients if limit is None else min(limit, data.num_clients))
    return [(data.client_x[i], data.client_y[i]) for i in ids]


def _run_fedgkt(config, data, model, task, log_fn, opt):
    from fedml_tpu.algorithms.fedgkt import FedGKTAPI

    shape = tuple(data.client_x[0].shape[1:])
    api = FedGKTAPI(
        num_classes=data.num_classes,
        input_shape=shape,
        lr=config.train.lr,
        seed=config.seed,
    )
    clients = _client_shards_list(data, config.fed.client_num_per_round)
    cache = None
    final = {}
    for r in range(config.fed.comm_round):
        cache = api.train_round(
            clients,
            local_epochs=config.fed.epochs,
            server_epochs=config.fed.epochs,
            batch_size=config.data.batch_size,
            server_logits_cache=cache,
        )
        acc = api.evaluate(data.test_x, data.test_y, client_id=0)
        final = {"round": r, "Test/Acc": float(acc)}
        log_fn(final)
    return final


def _run_fedgan(config, data, model, task, log_fn, opt):
    from fedml_tpu.algorithms.fedgan import FedGANAPI

    api = FedGANAPI(config, data, log_fn=log_fn)
    return api.train()


def _run_fedseg(config, data, model, task, log_fn, opt):
    from fedml_tpu.algorithms.fedseg import FedSegAPI

    api = FedSegAPI(
        config,
        data,
        model,
        checkpoint_path=str(opt["checkpoint_path"]) if opt["checkpoint_path"] else None,
        log_fn=log_fn,
    )
    return api.train()


def _run_fednas(config, data, model, task, log_fn, opt):
    from fedml_tpu.algorithms.fednas import FedNASAPI

    shape = tuple(data.client_x[0].shape[1:])
    api = FedNASAPI(
        data,
        num_classes=data.num_classes,
        input_shape=shape,
        batch_size=config.data.batch_size,
        seed=config.seed,
        arch_grad=opt.get("variant") or "first",
    )
    final = {}
    for r in range(config.fed.comm_round):
        geno = api.train_round(
            r,
            client_num_per_round=config.fed.client_num_per_round,
            epochs=config.fed.epochs,
        )
        acc = api.evaluate(data.test_x, data.test_y)
        final = {"round": r, "Test/Acc": float(acc), "genotype": str(geno)}
        log_fn(final)
    return final


def _run_split_nn(config, data, model, task, log_fn, opt):
    from fedml_tpu.algorithms.split_nn import SplitNNAPI, default_split_models

    shape = tuple(data.client_x[0].shape[1:])
    bottom, top = default_split_models(shape, data.num_classes)
    if config.compile.warmup:
        # the split programs (fused step + boundary triple + eval) are
        # ProgramCache factories like the horizontal family's — --warmup
        # AOT-compiles them before round 0 (fedml_tpu/compile/warmup.py)
        from fedml_tpu.compile import warmup_splitnn

        warmup_splitnn(bottom, top, config, data, log_fn=log_fn)
    api = SplitNNAPI(
        bottom, top, lr=config.train.lr, momentum=config.train.momentum,
        wd=config.train.wd, seed=config.seed,
    )
    clients = _client_shards_list(data, config.fed.client_num_per_round)
    final = {}
    for r in range(config.fed.comm_round):
        api.train_ring(
            clients,
            batch_size=config.data.batch_size,
            epochs_per_client=config.fed.epochs,
        )
        acc = api.evaluate(data.test_x, data.test_y)
        final = {"round": r, "Test/Acc": float(acc)}
        log_fn(final)
    return final


def _run_vertical_fl(config, data, model, task, log_fn, opt):
    """VFL over a vertical (feature) split of the dataset: party 0 (guest)
    holds labels, the rest are hosts (ref classical_vertical_fl)."""
    from fedml_tpu.algorithms.vertical_fl import VFLAPI

    x = np.concatenate([cx.reshape(len(cx), -1) for cx in data.client_x], axis=0)
    y = (np.concatenate(data.client_y, axis=0) % 2).astype(np.float32)
    D = x.shape[1]
    splits = [D // 3, D // 3, D - 2 * (D // 3)]
    xs, off = [], 0
    for s in splits:
        xs.append(x[:, off : off + s])
        off += s
    api = VFLAPI(feature_splits=splits, lr=config.train.lr, seed=config.seed)
    final = {}
    for r in range(config.fed.comm_round):
        stats = api.train_epoch(xs, y, batch_size=config.data.batch_size)
        final = {"round": r, "Train/Loss": stats["loss"], "Train/Acc": stats["acc"]}
        log_fn(final)
    return final


def _run_decentralized(config, data, model, task, log_fn, opt):
    """Decentralized online learning over the client topology: each client's
    shard becomes its stream (ref standalone/decentralized)."""
    from fedml_tpu.algorithms.decentralized import DecentralizedAPI
    from fedml_tpu.models import ModelDef
    from fedml_tpu.models.linear import LogisticRegression
    from fedml_tpu.partition.topology import SymmetricTopologyManager

    N = data.num_clients
    T = min(len(cy) for cy in data.client_y)
    x = np.stack([cx[:T].reshape(T, -1) for cx in data.client_x])
    y = np.stack([(cy[:T] % 2).astype(np.float32) for cy in data.client_y])
    topo = SymmetricTopologyManager(N, neighbor_num=min(4, N - 1))
    topo.generate_topology()
    lrmodel = ModelDef(
        LogisticRegression(num_classes=1), (x.shape[-1],), 1, name="lr"
    )
    api = DecentralizedAPI(
        lrmodel,
        topo,
        lr=config.train.lr,
        variant=opt.get("variant") or "dsgd",
        seed=config.seed,
    )
    out = api.run(x, y)
    final = {
        "iterations": int(len(out["losses"])),
        "final_regret": float(out["regret"][-1]),
    }
    log_fn(final)
    return final


def _run_secagg(config, data, model, task, log_fn, opt):
    """One FedAvg round where the upload path goes through the secure
    aggregator (pairwise masking + dropout recovery): verifies the masked
    sum equals the plain sum (ref turboaggregate)."""
    from fedml_tpu.secagg.secure_aggregation import SecureAggregator

    K = config.fed.client_num_per_round
    updates = [
        data.client_x[i].reshape(len(data.client_x[i]), -1).mean(axis=0)
        for i in range(min(K, data.num_clients))
    ]
    N, D = len(updates), len(updates[0])
    agg = SecureAggregator(N, D, seed=config.seed)
    active = list(range(N))
    uploads = {i: agg.client_upload(i, updates[i], active) for i in active}
    # drop one client after masking: survivors recover its masks
    dropped = None
    if N > 2:
        dropped = N - 1
        uploads.pop(dropped)
    total = agg.aggregate(uploads, intended=active)
    expect = np.sum([u for i, u in enumerate(updates) if i != dropped], axis=0)
    err = float(np.max(np.abs(total - expect)))
    final = {
        "clients": N,
        "dropped": dropped,
        "max_abs_error": err,
        "secure_sum_ok": bool(err < 1e-3),
    }
    log_fn(final)
    return final


def _run_centralized(config, data, model, task, log_fn, opt):
    """Non-federated data-parallel baseline (ref
    fedml_experiments/centralized/main.py DDP path): --runtime mesh shards
    the batch over all devices; --comm_round doubles as the epoch count."""
    from fedml_tpu.train.centralized import CentralizedTrainer

    mesh = None
    if opt["runtime"] == "mesh":
        from fedml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(opt["client_shards"], "batch")
    trainer = CentralizedTrainer(
        config, data, model, task=task, mesh=mesh, log_fn=log_fn
    )
    return trainer.train()


def _run_grpc_process(config, data, model, task, log_fn, opt):
    """One federation participant over gRPC: rank 0 = server FSM, rank 1..K
    = client actor. Every process loads the same config/data (deterministic
    partition from the shared seed), mirroring the reference's
    one-process-per-worker model (FedAvgAPI.py:14-27). Returns
    ``(final_row, health)`` — health is the server's client registry on
    rank 0 (fed by broadcast→upload round-trips), None on client ranks."""
    from fedml_tpu.algorithms.fedavg_transport import (
        FedAvgClientManager,
        FedAvgServerManager,
        LocalTrainer,
    )
    from fedml_tpu.core.grpc_comm import GrpcCommManager, read_ip_config

    rank = opt["rank"]
    if rank is None:
        raise click.UsageError("runtime=grpc requires --rank")
    # one worker per scheduler slot (overprovisioned cohorts need
    # ceil(k * factor) client processes — launch scripts must match)
    from fedml_tpu.scheduler import overprovisioned_k

    K = overprovisioned_k(
        config.fed.client_num_per_round,
        config.fed.overprovision_factor,
        config.fed.client_num_in_total,
    )
    if opt["ip_config"]:
        table = read_ip_config(str(opt["ip_config"]))
    else:
        table = {r: "127.0.0.1" for r in range(K + 1)}
    comm = GrpcCommManager(
        rank, table, base_port=opt["base_port"],
        send_timeout_s=config.comm.send_timeout_s,
        max_workers=config.comm.grpc_max_workers,
        stream_budget=config.comm.grpc_stream_budget,
        max_message_mb=config.comm.grpc_max_message_mb,
        keepalive_s=config.comm.grpc_keepalive_s,
    )
    # per-process fault injector (client ranks only): the plan is
    # deterministic in (seed, client, round), so every process injects
    # the same faults; the server infers dropouts from its quorum rounds
    from fedml_tpu.scheduler import FaultInjector

    faults = FaultInjector.from_config(config) if rank != 0 else None
    if opt["algorithm"] == "fedbuff":
        from fedml_tpu.algorithms.fedbuff import (
            FedBuffClientManager,
            FedBuffServerManager,
        )

        if rank == 0:
            server = FedBuffServerManager(
                config, comm, model, data=data, task=task, worker_num=K,
                log_fn=log_fn,
            )
            server.send_init_msg()
            server.run()
            return (
                server.history[-1] if server.history else {}
            ), server.health
        client = FedBuffClientManager(
            config, comm, rank,
            LocalTrainer(
                config, data, model, task,
                straggle_s=opt.get("straggle_ms", 0.0) / 1e3,
            ),
            faults=faults,
        )
        client.run()
        if faults is not None:
            # per-process fault accounting (this rank's summary.json) —
            # the in-process runners log their shared injector instead
            log_fn(faults.summary_row())
        if client.orphaned:
            raise click.ClickException(
                f"async worker rank {rank} orphaned: server unreachable "
                "and no FINISH within its deadline"
            )
        return {"rank": rank, "finished": True}, None
    if rank == 0:
        server = FedAvgServerManager(
            config, comm, model, data=data, task=task, worker_num=K,
            log_fn=log_fn, server_opt=opt["algorithm"] == "fedopt",
        )
        server.send_init_msg()
        server.run()
        if server.deadline_error is not None:
            # release the client processes before surfacing the failure —
            # they would otherwise park on their inboxes
            from fedml_tpu.core.message import Message, MessageType as MT

            for worker in range(1, K + 1):
                try:
                    server.send_message(Message(MT.FINISH, 0, worker))
                except Exception:  # noqa: BLE001
                    pass
            raise RuntimeError(
                "server deadline path failed"
            ) from server.deadline_error
        if opt.get("checkpoint_path"):
            # rank 0 owns the converged params — persist them so gRPC runs
            # can be compared/resumed like the in-process runtimes (the CI
            # wire-fleet gate diffs these arrays across beacons on/off)
            from fedml_tpu.utils import save_checkpoint

            save_checkpoint(
                str(opt["checkpoint_path"]),
                server.global_vars,
                round_idx=config.fed.comm_round,
                server_opt_state=getattr(server, "server_opt_state", None),
            )
        return (server.history[-1] if server.history else {}), server.health
    client = FedAvgClientManager(
        config, comm, rank,
        LocalTrainer(
            config, data, model, task,
            straggle_s=opt.get("straggle_ms", 0.0) / 1e3,
        ),
        faults=faults,
    )
    client.run()
    if faults is not None:
        log_fn(faults.summary_row())  # this rank's summary.json
    return {"rank": rank, "finished": True}, None


_LONGTAIL = {
    "centralized": _run_centralized,
    "fedgkt": _run_fedgkt,
    "fedgan": _run_fedgan,
    "fedseg": _run_fedseg,
    "fednas": _run_fednas,
    "split_nn": _run_split_nn,
    "vertical_fl": _run_vertical_fl,
    "decentralized": _run_decentralized,
    "secagg": _run_secagg,
}


if __name__ == "__main__":
    main()
