"""fedml_tpu — a TPU-native federated-learning framework.

A from-scratch re-design of the capabilities of FedML (reference: GabriJP/FedML)
on JAX/XLA. The reference's MPI/gRPC/MQTT actor runtime
(fedml_core/distributed/communication/) becomes, for the common intra-pod case,
a pure jit-compiled round function sharded over a `jax.sharding.Mesh`; its
PyTorch model zoo (fedml_api/model/) becomes flax modules; its standalone
sequential simulator (fedml_api/standalone/fedavg/fedavg_api.py:40-84) becomes
vmap-over-clients on one chip. A Message/Observer-shaped async transport is kept
for true cross-silo federation.

Subpackages
-----------
- ``config``      typed run configuration (ref: fedml_core/trainer/model_trainer.py:7-38)
- ``partition``   non-IID partitioners + topologies (ref: fedml_core/non_iid_partition/)
- ``data``        federated dataset containers and loaders (ref: fedml_api/data_preprocessing/)
- ``models``      flax model zoo (ref: fedml_api/model/)
- ``train``       jit-compiled local training / evaluation operators
- ``algorithms``  FL algorithms (ref: fedml_api/{distributed,standalone}/)
- ``parallel``    mesh runtime: sharded FedAvg, ring/Ulysses SP, TP, EP, PP
- ``core``        Message/Observer transport (gRPC/MQTT/shm/loopback)
- ``ops``         Pallas TPU kernels (flash attention)
- ``robustness``  defenses (clip/DP, Byzantine aggregators) + backdoor harness
- ``secagg``      field MPC + pairwise-mask secure aggregation
- ``utils``       metrics, checkpoint/resume, profiling
- ``native``      C++ fastpack host ops (ctypes)
"""

__version__ = "0.2.0"

# NOTE: this file deliberately imports nothing. `import fedml_tpu` (and in
# particular `import fedml_tpu.telemetry`, which is jax-free by contract)
# must not pay the jax import. The jax API-compat shims for older jaxlib
# live in fedml_tpu/_jax_compat.py and are installed by the modules that
# actually call the newer APIs (parallel/, the sharded algorithm variants).
