"""Robust aggregation defenses: norm-difference clipping + weak-DP Gaussian
noise (ref: fedml_core/robustness/robust_aggregation.py:4-55).

The reference vectorizes the state dict (excluding BN running stats,
is_weight_param :28), clips the client-minus-global difference to a norm
bound, and optionally adds Gaussian noise. Here the same math runs as pure
tree ops — and, because clients are a stacked axis, the whole defense vmaps
over them inside the jitted round (the reference clips client-by-client in
Python, FedAvgRobustAggregator.py:173-201)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """ref RobustAggregator.__init__ (robust_aggregation.py:33-36), extended
    with Byzantine-robust AGGREGATORS the reference lacks: coordinate-wise
    median / trimmed mean (Yin et al. 2018) and Krum / Multi-Krum (Blanchard
    et al. 2017) — these replace the weighted average rather than clip
    before it."""

    # "norm_diff_clipping" | "weak_dp" | "no_defense"
    # | "median" | "trimmed_mean" | "krum" | "multi_krum"
    defense_type: str = "norm_diff_clipping"
    norm_bound: float = 5.0
    stddev: float = 0.025
    # trimmed_mean: drop this many highest+lowest per coordinate;
    # krum/multi_krum: assumed number of Byzantine clients
    num_byzantine: int = 1
    # multi_krum: average the m best-scored clients
    multi_krum_m: int = 3


BYZANTINE_AGGREGATORS = ("median", "trimmed_mean", "krum", "multi_krum")
CLIP_DEFENSES = ("norm_diff_clipping", "weak_dp", "no_defense")


def _is_weight_leaf(path: str) -> bool:
    """BN running stats are excluded from clipping (ref is_weight_param:28;
    flax: batch_stats live in a separate collection, so a leaf is clippable
    iff its path doesn't enter batch_stats)."""
    return "batch_stats" not in path


def _flatten_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        (jax.tree_util.keystr(path), leaf) for path, leaf in flat
    ]


def tree_weight_norm(tree, ref_tree=None) -> jnp.ndarray:
    """L2 norm over clippable leaves of (tree - ref_tree)
    (ref vectorize_weight + torch.norm, :4-10, 42-45)."""
    total = 0.0
    ref = _flatten_with_paths(ref_tree) if ref_tree is not None else None
    for i, (path, leaf) in enumerate(_flatten_with_paths(tree)):
        if not _is_weight_leaf(path):
            continue
        d = leaf - ref[i][1] if ref is not None else leaf
        total = total + jnp.sum(jnp.square(d.astype(jnp.float32)))
    return jnp.sqrt(total)


def norm_diff_clip_tree(local_tree, global_tree, norm_bound: float):
    """w_g + clip(w_l − w_g): scale the diff by min(1, bound/‖diff‖)
    (ref norm_diff_clipping :38-49). Non-weight leaves pass through."""
    norm = tree_weight_norm(local_tree, global_tree)
    scale = jnp.minimum(1.0, norm_bound / jnp.maximum(norm, 1e-12))

    def clip_leaf(path, l, g):
        if _is_weight_leaf(path):
            return g + (l - g) * scale
        return l

    flat_l = _flatten_with_paths(local_tree)
    flat_g = _flatten_with_paths(global_tree)
    leaves = [clip_leaf(p, l, g) for (p, l), (_, g) in zip(flat_l, flat_g)]
    treedef = jax.tree_util.tree_structure(local_tree)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _kernel_auto() -> bool:
    """Whether the rank-selection Pallas kernel (ops/robust_stats.py)
    replaces XLA's sort lowering for the per-coordinate order statistics:
    TPU only — everywhere else the historical jnp path runs, byte-
    identical to every prior release. Tests drive the kernel explicitly
    through its own module (interpret mode)."""
    return jax.default_backend() == "tpu"


def coordinate_median(stacked_tree, num_samples=None, use_kernel=None):
    """Coordinate-wise median over the leading client axis. Sample weights
    are ignored by construction (median is order-based). BN stats (non
    clippable leaves) keep the weighted mean — averaging running statistics
    is the meaningful reduction for them."""
    if use_kernel is None:
        use_kernel = _kernel_auto()

    def reduce(v):
        if use_kernel and v.ndim >= 1 and v.shape[0] > 1:
            from fedml_tpu.ops.robust_stats import median_1d

            C = v.shape[0]
            return median_1d(
                v.reshape(C, -1), use_kernel=True
            ).reshape(v.shape[1:])
        return jnp.median(v, axis=0)

    return _byzantine_reduce(stacked_tree, num_samples, reduce)


def trimmed_mean(stacked_tree, num_samples=None, trim_k: int = 1, use_kernel=None):
    """Per-coordinate: sort the C client values, drop the ``trim_k``
    largest and smallest, average the rest (Yin et al. 2018)."""
    if use_kernel is None:
        use_kernel = _kernel_auto()

    def reduce(v):
        C = v.shape[0]
        if trim_k < 0 or 2 * trim_k >= C:
            raise ValueError(f"need 0 <= trim_k < C/2; got trim_k={trim_k}, C={C}")
        if use_kernel:
            from fedml_tpu.ops.robust_stats import trimmed_mean_1d

            return trimmed_mean_1d(
                v.reshape(C, -1), trim_k, use_kernel=True
            ).reshape(v.shape[1:])
        s = jnp.sort(v, axis=0)
        return jnp.mean(s[trim_k : C - trim_k], axis=0)

    return _byzantine_reduce(stacked_tree, num_samples, reduce)


def _byzantine_reduce(stacked_tree, num_samples, reduce_fn):
    def leaf(path, v):
        v = v.astype(jnp.float32)
        if _is_weight_leaf(path):
            return reduce_fn(v)
        if num_samples is not None:
            w = num_samples / jnp.maximum(jnp.sum(num_samples), 1e-12)
            return jnp.tensordot(w, v, axes=1)
        return jnp.mean(v, axis=0)

    flat = _flatten_with_paths(stacked_tree)
    leaves = [leaf(p, v) for p, v in flat]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(stacked_tree), leaves
    )


def _client_matrix(stacked_tree):
    """[C, D] flattened clippable weights per client."""
    vecs = [
        v.astype(jnp.float32).reshape(v.shape[0], -1)
        for p, v in _flatten_with_paths(stacked_tree)
        if _is_weight_leaf(p)
    ]
    return jnp.concatenate(vecs, axis=1)


def krum_select(stacked_tree, num_byzantine: int, m: int = 1):
    """Krum scores (Blanchard et al. 2017): for each client, the sum of its
    C − f − 2 smallest squared distances to other clients; returns the
    indices of the ``m`` best-scored clients ([m] int array)."""
    X = _client_matrix(stacked_tree)
    C = X.shape[0]
    closest = C - num_byzantine - 2
    # Blanchard et al.'s admissibility regime: C >= 2f + 3 — with f a
    # majority the f colluders' mutual distances are 0 and Krum picks one.
    if num_byzantine < 0 or 2 * num_byzantine + 3 > C:
        raise ValueError(
            f"krum needs 0 <= byzantine <= (clients − 3)/2; got C={C}, "
            f"f={num_byzantine}"
        )
    if not 1 <= m <= C - num_byzantine - 2:
        raise ValueError(
            f"multi-krum needs 1 <= m <= clients − byzantine − 2 "
            f"(Blanchard et al.); got m={m}, C={C}, f={num_byzantine}"
        )
    # Gram-matrix form: ||x_i - x_j||² = n_i + n_j − 2·x_i·x_j. O(C²+CD)
    # memory instead of materializing the [C, C, D] difference tensor.
    n = jnp.sum(jnp.square(X), axis=1)
    sq = n[:, None] + n[None, :] - 2.0 * (X @ X.T)  # [C, C]
    sq = jnp.maximum(sq, 0.0) + jnp.diag(jnp.full((C,), jnp.inf))  # excl self
    neighbor_d = jnp.sort(sq, axis=1)[:, :closest]
    scores = jnp.sum(neighbor_d, axis=1)
    return jnp.argsort(scores)[:m]


def krum_aggregate(stacked_tree, num_byzantine: int, m: int = 1):
    """Krum (m=1) / Multi-Krum (m>1): average of the selected clients'
    trees — unweighted, per the original algorithm."""
    sel = krum_select(stacked_tree, num_byzantine, m)
    return jax.tree_util.tree_map(
        lambda v: jnp.mean(
            jnp.take(v.astype(jnp.float32), sel, axis=0), axis=0
        ),
        stacked_tree,
    )


def make_byzantine_aggregate(robust: "RobustConfig"):
    """defense_type → ``aggregate_fn(stacked_client_vars, num_samples,
    global_vars=None)`` replacing the weighted average, or None for the
    clip/noise defenses. The order statistics ignore w_t — the third
    argument exists because the round skeletons pass it for aggregates
    that DO need it (DP's fixed-denominator estimator)."""
    d = robust.defense_type
    if d in CLIP_DEFENSES:
        return None
    if d not in BYZANTINE_AGGREGATORS:
        raise ValueError(
            f"unknown defense_type {d!r}; expected one of "
            f"{BYZANTINE_AGGREGATORS + CLIP_DEFENSES}"
        )
    if robust.num_byzantine < 0:
        raise ValueError(f"num_byzantine must be >= 0; got {robust.num_byzantine}")
    builders = {
        "median": lambda cv, ns, g=None: coordinate_median(cv, ns),
        "trimmed_mean": lambda cv, ns, g=None: trimmed_mean(
            cv, ns, trim_k=robust.num_byzantine
        ),
        "krum": lambda cv, ns, g=None: krum_aggregate(
            cv, robust.num_byzantine, m=1
        ),
        "multi_krum": lambda cv, ns, g=None: krum_aggregate(
            cv, robust.num_byzantine, m=robust.multi_krum_m
        ),
    }
    return builders[d]


def add_gaussian_noise(tree, rng, stddev: float):
    """Weak-DP noise on clippable leaves (ref add_noise :51-55)."""
    flat = _flatten_with_paths(tree)
    rngs = jax.random.split(rng, len(flat))
    leaves = [
        leaf + jax.random.normal(r, leaf.shape, jnp.float32) * stddev
        if _is_weight_leaf(path)
        else leaf
        for r, (path, leaf) in zip(rngs, flat)
    ]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(tree), leaves
    )
