"""Robust aggregation defenses: norm-difference clipping + weak-DP Gaussian
noise (ref: fedml_core/robustness/robust_aggregation.py:4-55).

The reference vectorizes the state dict (excluding BN running stats,
is_weight_param :28), clips the client-minus-global difference to a norm
bound, and optionally adds Gaussian noise. Here the same math runs as pure
tree ops — and, because clients are a stacked axis, the whole defense vmaps
over them inside the jitted round (the reference clips client-by-client in
Python, FedAvgRobustAggregator.py:173-201)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """ref RobustAggregator.__init__ (robust_aggregation.py:33-36)."""

    defense_type: str = "norm_diff_clipping"  # or "weak_dp", "no_defense"
    norm_bound: float = 5.0
    stddev: float = 0.025


def _is_weight_leaf(path: str) -> bool:
    """BN running stats are excluded from clipping (ref is_weight_param:28;
    flax: batch_stats live in a separate collection, so a leaf is clippable
    iff its path doesn't enter batch_stats)."""
    return "batch_stats" not in path


def _flatten_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        (jax.tree_util.keystr(path), leaf) for path, leaf in flat
    ]


def tree_weight_norm(tree, ref_tree=None) -> jnp.ndarray:
    """L2 norm over clippable leaves of (tree - ref_tree)
    (ref vectorize_weight + torch.norm, :4-10, 42-45)."""
    total = 0.0
    ref = _flatten_with_paths(ref_tree) if ref_tree is not None else None
    for i, (path, leaf) in enumerate(_flatten_with_paths(tree)):
        if not _is_weight_leaf(path):
            continue
        d = leaf - ref[i][1] if ref is not None else leaf
        total = total + jnp.sum(jnp.square(d.astype(jnp.float32)))
    return jnp.sqrt(total)


def norm_diff_clip_tree(local_tree, global_tree, norm_bound: float):
    """w_g + clip(w_l − w_g): scale the diff by min(1, bound/‖diff‖)
    (ref norm_diff_clipping :38-49). Non-weight leaves pass through."""
    norm = tree_weight_norm(local_tree, global_tree)
    scale = jnp.minimum(1.0, norm_bound / jnp.maximum(norm, 1e-12))

    def clip_leaf(path, l, g):
        if _is_weight_leaf(path):
            return g + (l - g) * scale
        return l

    flat_l = _flatten_with_paths(local_tree)
    flat_g = _flatten_with_paths(global_tree)
    leaves = [clip_leaf(p, l, g) for (p, l), (_, g) in zip(flat_l, flat_g)]
    treedef = jax.tree_util.tree_structure(local_tree)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def add_gaussian_noise(tree, rng, stddev: float):
    """Weak-DP noise on clippable leaves (ref add_noise :51-55)."""
    flat = _flatten_with_paths(tree)
    rngs = jax.random.split(rng, len(flat))
    leaves = [
        leaf + jax.random.normal(r, leaf.shape, jnp.float32) * stddev
        if _is_weight_leaf(path)
        else leaf
        for r, (path, leaf) in zip(rngs, flat)
    ]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(tree), leaves
    )
