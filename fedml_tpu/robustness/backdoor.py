"""Backdoor attack harness: model-replacement boosting vs the robust
defenses — the measurable attack/defense pairing the reference evaluates
with FedAvgRobustAggregator.py:14-60 + edge_case_examples.

Threat model: attacker clients train on locally poisoned shards
(data/edge_cases.py) and BOOST their upload toward model replacement,
``w_i ← w_g + γ·(w_i − w_g)`` — with γ ≈ sampled-client count the boosted
update survives averaging and installs the backdoor in one round. The
norm-difference clipping defense (robust_aggregation.py) bounds exactly the
boosted quantity, which is why it works: clipping reduces ASR while leaving
honest (small-norm) updates untouched.

Everything runs inside the jitted round: the boost is a per-client mask
multiply vmapped over the stacked client axis, slotted as a post_train hook
ahead of the defense (attack happens client-side, defense server-side)."""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import make_fedavg_round
from fedml_tpu.algorithms.fedavg_robust import RobustFedAvgAPI
from fedml_tpu.robustness import (
    RobustConfig,
    add_gaussian_noise,
    make_byzantine_aggregate,
    norm_diff_clip_tree,
)


@dataclasses.dataclass(frozen=True)
class AttackConfig:
    attacker_ids: tuple = ()
    boost: float = 10.0  # γ; ≈ client_num_per_round for full replacement


def make_attacked_robust_round(
    model, config, robust: RobustConfig, attack: AttackConfig,
    task="classification", local_train_fn=None, donate=True,
):
    def post_train(client_vars, global_vars, noise_rng, attack_mask):
        # attacker-side boost: w_i <- w_g + γ(w_i - w_g) for masked clients
        boost = jnp.where(attack_mask > 0, attack.boost, 1.0)
        client_vars = jax.tree_util.tree_map(
            lambda cv, gv: gv + boost.reshape((-1,) + (1,) * (cv.ndim - 1)) * (cv - gv),
            client_vars,
            global_vars,
        )
        # server-side defense
        if robust.defense_type in ("norm_diff_clipping", "weak_dp"):
            client_vars = jax.vmap(
                lambda cv: norm_diff_clip_tree(cv, global_vars, robust.norm_bound)
            )(client_vars)
        return client_vars

    def post_aggregate(new_global, noise_rng, attack_mask):
        if robust.defense_type == "weak_dp":
            return add_gaussian_noise(new_global, noise_rng, robust.stddev)
        return new_global

    return make_fedavg_round(
        model, config, task=task, local_train_fn=local_train_fn,
        donate=donate, post_train=post_train, post_aggregate=post_aggregate,
        aggregate_fn=make_byzantine_aggregate(robust),
    )


class BackdoorFedAvgAPI(RobustFedAvgAPI):
    """RobustFedAvgAPI under active attack: attacker clients' shards should
    be poisoned (data/edge_cases.py); their uploads are boosted inside the
    round; the configured defense then runs server-side."""

    # _place_batch reads self._current_round to build the attack mask, so
    # it is not a pure function of (round, seed, rng) — preparing round
    # r+1 during round r would bake round r's mask into r+1's batch.
    _supports_pipeline = False

    def __init__(self, config, data, model, robust=RobustConfig(), attack=AttackConfig(), **kw):
        self.attack = attack
        self._attacker_set = set(int(a) for a in attack.attacker_ids)
        super().__init__(config, data, model, robust=robust, **kw)

    def _build_round_fn(self, local_train_fn):
        return make_attacked_robust_round(
            self.model, self.config, self.robust, self.attack,
            task=self.task, local_train_fn=local_train_fn, donate=self._donate,
        )

    def train_round(self, round_idx: int):
        self._current_round = round_idx
        return super().train_round(round_idx)

    def _place_batch(self, batch, round_rng):
        base = super(RobustFedAvgAPI, self)._place_batch(batch, round_rng)
        noise_rng = jax.random.fold_in(round_rng, 0x5EED)
        # the round's ACTUAL cohort (memoized _round_plan) — recomputing a
        # uniform draw here would misalign the attack mask whenever the
        # scheduler's policy or a fault plan changed the cohort
        sampled = self._round_plan(getattr(self, "_current_round", 0))[0]
        attack_mask = jnp.asarray(
            np.array(
                [1.0 if int(c) in self._attacker_set else 0.0 for c in sampled],
                np.float32,
            )
        )
        return base + (noise_rng, attack_mask)
