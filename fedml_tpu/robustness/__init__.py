from fedml_tpu.robustness.robust_aggregation import (
    RobustConfig,
    norm_diff_clip_tree,
    add_gaussian_noise,
    tree_weight_norm,
)

__all__ = [
    "RobustConfig",
    "norm_diff_clip_tree",
    "add_gaussian_noise",
    "tree_weight_norm",
]
