from fedml_tpu.robustness.robust_aggregation import (
    BYZANTINE_AGGREGATORS,
    CLIP_DEFENSES,
    RobustConfig,
    coordinate_median,
    krum_aggregate,
    krum_select,
    make_byzantine_aggregate,
    norm_diff_clip_tree,
    add_gaussian_noise,
    tree_weight_norm,
    trimmed_mean,
)

__all__ = [
    "BYZANTINE_AGGREGATORS",
    "CLIP_DEFENSES",
    "RobustConfig",
    "coordinate_median",
    "krum_aggregate",
    "krum_select",
    "make_byzantine_aggregate",
    "norm_diff_clip_tree",
    "add_gaussian_noise",
    "tree_weight_norm",
    "trimmed_mean",
]
