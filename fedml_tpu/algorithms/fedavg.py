"""FedAvg — the flagship algorithm (ref: fedml_api/distributed/fedavg/ +
fedml_api/standalone/fedavg/).

The reference spends ~566 LoC on a server FSM + client managers + MPI wire
(SURVEY §3.1); here the whole communication round is one pure function::

    (global_variables, stacked_client_batch, weights, rng)
        -> (global_variables', metrics)

vmap over the client axis = the standalone simulator
(ref fedavg_api.py:40-84's sequential loop, HOT LOOP of SURVEY §3.2);
the same function jitted with the client axis sharded over a device mesh =
the distributed runtime (ref FedAvgServerManager/ClientManager + MPI).
Aggregation is the sample-weighted average of FedAVGAggregator.py:51-78 as a
single tensordot over the client axis (XLA lowers it to an all-reduce when
sharded) instead of a Python loop over state_dict keys (HOT LOOP #3)."""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import RunConfig
from fedml_tpu.data.base import FederatedDataset, stack_clients
from fedml_tpu.models import ModelDef
from fedml_tpu.telemetry import ClientHealthRegistry, get_tracer
from fedml_tpu.train.client import make_local_train
from fedml_tpu.train.evaluate import make_eval_fn


def weighted_average(stacked_tree, weights):
    """Sample-weighted average over the leading client axis
    (ref FedAVGAggregator.py:51-78: w = n_k/n_total per key)."""
    wsum = jnp.sum(weights)
    return jax.tree_util.tree_map(
        lambda p: jnp.tensordot(weights, p.astype(jnp.float32), axes=1) / wsum,
        stacked_tree,
    )


def client_sampling(round_idx: int, client_num_in_total: int, client_num_per_round: int) -> np.ndarray:
    """Round-seeded sampling for reproducibility — exact parity with
    FedAVGAggregator.py:80-88 (np.random.seed(round_idx) then choice without
    replacement). Back-compat shim: the implementation now lives in the
    scheduler registry as the ``uniform`` policy
    (fedml_tpu/scheduler/policies.py); this delegates so every historical
    import keeps the exact reference semantics."""
    from fedml_tpu.scheduler import select_clients

    return select_clients(
        round_idx, client_num_in_total, client_num_per_round, policy="uniform"
    )


def round_client_rngs(round_rng, num_sampled: int):
    """Per-client PRNG keys for one round. Generated once per round from the
    round-folded key so the stream is independent of how clients are later
    padded/sharded over a mesh (single-chip and N-shard runs see identical
    per-client randomness)."""
    return jax.random.split(round_rng, num_sampled)


def resolve_client_parallelism(mode: str, model: ModelDef) -> str:
    """Resolve FedConfig.client_parallelism="auto" for a model.

    "scan" wins when per-client weights make vmap's convs grouped convs
    whose small channel dims tile the 128-lane MXU badly (measured on v5e,
    examples/probe_resnet_bf16.py / examples/profile_r3.py: cross-silo
    ResNet-56 bf16 round 350 -> 190 ms under scan; the flagship femnist
    CNN is a wash, 34.0 -> 33.1 ms, because its dense head runs at the
    same tiny per-client M either way). Models without under-tiled convs
    or with sub-MB param copies keep "vmap": their per-step time is
    overhead-dominated and one big program wins. The heuristic: any 4-D
    conv kernel with <= 64 output channels (under-tiled on the MXU) and a
    per-client param copy >= 1 MB."""
    if mode == "auto":
        shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        leaves = jax.tree_util.tree_leaves(shapes)
        param_bytes = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves
        )
        small_conv = any(
            len(l.shape) == 4 and l.shape[-1] <= 64 and l.shape[0] <= 7
            for l in leaves
        )
        mode = "scan" if (small_conv and param_bytes >= 1_000_000) else "vmap"
    if mode not in ("vmap", "scan"):
        raise ValueError(
            f"client_parallelism must be 'vmap', 'scan' or 'auto', got {mode!r}"
        )
    return mode


def client_axis_map(local_train: Callable, mode: str, n_broadcast: int = 1) -> Callable:
    """Lift ``local_train`` over the leading client axis — either batched
    (vmap) or sequential (lax.scan). The first ``n_broadcast`` positional
    args broadcast to every client (global state: variables, and e.g.
    SCAFFOLD's server control variate); the rest carry a leading client
    axis. Both schedules return identically stacked outputs; the math is
    the same, only the schedule differs (see resolve_client_parallelism)."""
    if mode == "vmap":

        def vmapped(*args):
            in_axes = (None,) * n_broadcast + (0,) * (len(args) - n_broadcast)
            return jax.vmap(local_train, in_axes=in_axes)(*args)

        return vmapped

    def scanned(*args):
        bcast, per = args[:n_broadcast], args[n_broadcast:]

        def body(_, per_client):
            return None, local_train(*bcast, *per_client)

        _, out = jax.lax.scan(body, None, per)
        return out

    return scanned


def resolve_skip_empty_steps(mode: str, may_pad: Optional[bool]) -> bool:
    """Whether the per-step ``lax.cond`` skip branch should be emitted.

    The cond genuinely skips all-padding steps under the sequential
    ("scan") client schedule — but it is not free: interleaved-min on the
    cross-silo ResNet-56 round, the cond-ful body costs ~3% (188.0 vs
    182.5 ms) when every step is real, presumably because the branch
    boundary blocks XLA from fusing the batch slice into the step. Whether
    a cohort HAS any all-padding step is host-side static knowledge (the
    sampled clients' sample counts vs the bucketed step count), so the
    decision is made per compiled shape class: ``may_pad=False`` drops the
    cond entirely, ``may_pad=True`` keeps it, ``None`` (unknown cohort)
    keeps the safe default under scan. vmap schedules never emit it — a
    per-client predicate cannot branch."""
    if mode != "scan":
        return False
    return True if may_pad is None else bool(may_pad)


def make_fedavg_round_body(
    model: ModelDef,
    config: RunConfig,
    task: str = "classification",
    local_train_fn: Optional[Callable] = None,
    client_mode: Optional[str] = None,
    may_pad: Optional[bool] = None,
):
    """The unjitted plain-FedAvg round body: lifted local trains + weighted
    average. ``(global_vars, x, y, mask, num_samples, client_rngs) ->
    (global_vars', per_client_metrics)``. Shared by the jitted round fn and
    by device-time measurement (utils/profiling.scan_slope_seconds needs an
    unjitted body to repeat inside one program)."""
    mode = client_mode or resolve_client_parallelism(
        config.fed.client_parallelism, model
    )
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task,
        skip_empty_steps=resolve_skip_empty_steps(mode, may_pad),
    )
    lifted = client_axis_map(local_train, mode)

    def round_body(global_vars, x, y, mask, num_samples, client_rngs):
        client_vars, metrics = lifted(global_vars, x, y, mask, client_rngs)
        return weighted_average(client_vars, num_samples), (client_vars, metrics)

    return round_body


def make_fedavg_round(
    model: ModelDef,
    config: RunConfig,
    task: str = "classification",
    local_train_fn: Optional[Callable] = None,
    donate: bool = True,
    post_train: Optional[Callable] = None,
    post_aggregate: Optional[Callable] = None,
    aggregate_fn: Optional[Callable] = None,
    client_mode: Optional[str] = None,
    client_metrics: bool = False,
    robust=None,
):
    """Build the jitted FedAvg round function (vmap over clients, one chip).

    ``local_train_fn`` lets algorithm variants (FedProx via prox_mu, FedNova
    via its own trainer) reuse this round skeleton. ``post_train(client_vars,
    global_vars, *extra)`` transforms the stacked per-client results before
    averaging (robust clipping); ``post_aggregate(new_global, *extra)``
    transforms the average (weak-DP noise); any positional round-fn
    arguments beyond client_rngs are forwarded to both hooks (e.g. a noise
    rng supplied by the API's _place_batch).

    ``client_metrics=True`` additionally returns per-client
    ``client_loss_sum``/``client_count`` vectors (leading client axis)
    alongside the scalar sums — the true per-client loss signal
    ``power_of_choice`` selection biases on (cohort-mean feeding made the
    simulator's bias signal diverge from the transports', ROADMAP item).
    Off by default: callers that combine metric trees across cohorts of
    different sizes (the hierarchical group loop) must not see
    ragged-shaped leaves.

    ``robust`` (a :class:`fedml_tpu.robustness.RobustConfig`) is the
    DESCRIBABLE form of the defense hook triple: the hooks are derived
    inside the builder from the config alone
    (``make_defense_hooks(robust)`` is a pure function of it), so the
    robust round — including the Byzantine aggregators
    median/trimmed-mean/Krum — dedupes through the ProgramCache with
    ``robust`` in the digest instead of bypassing via ``wrap_uncached``
    the way opaque hook closures must. Mutually exclusive with passing
    the hook closures directly.

    The returned callable takes an optional keyword ``may_pad`` — the
    host's static knowledge of whether this cohort has any all-padding
    local step (see :func:`resolve_skip_empty_steps`). Each distinct
    answer compiles its own variant (lazily, at most two); an unknown
    cohort (``None``) gets the safe default."""
    mode = client_mode or resolve_client_parallelism(
        config.fed.client_parallelism, model
    )
    # Program dedup (fedml_tpu/compile/): the jit cache is keyed by the
    # jit OBJECT, so every factory call would otherwise compile its own
    # copy of a structurally identical round. When the program is fully
    # determined by describable fields (no opaque hooks), route through
    # the process-wide ProgramCache; opaque callables bypass it — an
    # over-merged digest would be silent wrong numerics.
    from fedml_tpu.compile import (
        get_program_cache,
        hooks_cacheable,
        model_fingerprint,
    )

    if robust is not None:
        if not hooks_cacheable(post_train, post_aggregate, aggregate_fn):
            raise ValueError(
                "pass either robust= (describable defense config) or "
                "explicit hook closures, not both"
            )
        from fedml_tpu.algorithms.fedavg_robust import make_defense_hooks

        post_train, post_aggregate, aggregate_fn = make_defense_hooks(robust)
        # the hooks are pure functions of the (digested) RobustConfig —
        # only a caller-supplied local train keeps the program opaque
        cacheable = hooks_cacheable(local_train_fn)
    else:
        cacheable = hooks_cacheable(
            local_train_fn, post_train, post_aggregate, aggregate_fn
        )

    def build(skip: bool):
        def builder():
            local_train = local_train_fn or make_local_train(
                model, config.train, config.fed.epochs, task=task,
                skip_empty_steps=skip,
            )
            lifted = client_axis_map(local_train, mode)

            def round_fn(global_vars, x, y, mask, num_samples, client_rngs, *extra):
                client_vars, metrics = lifted(global_vars, x, y, mask, client_rngs)
                if post_train is not None:
                    client_vars = post_train(client_vars, global_vars, *extra)
                # aggregate_fn replaces the weighted average outright (Byzantine-
                # robust aggregators: median/trimmed-mean/Krum; DP's fixed-
                # denominator estimator needs w_t, hence the third argument)
                if aggregate_fn is not None:
                    new_global = aggregate_fn(client_vars, num_samples, global_vars)
                else:
                    new_global = weighted_average(client_vars, num_samples)
                if post_aggregate is not None:
                    new_global = post_aggregate(new_global, *extra)
                agg_metrics = jax.tree_util.tree_map(jnp.sum, metrics)
                if (
                    client_metrics
                    and isinstance(metrics, dict)
                    and "loss_sum" in metrics
                    and "count" in metrics
                ):
                    # per-client loss signal for power_of_choice — the
                    # stacked (pre-sum) vectors ride along with the sums
                    agg_metrics["client_loss_sum"] = metrics["loss_sum"]
                    agg_metrics["client_count"] = metrics["count"]
                return new_global, agg_metrics

            return jax.jit(round_fn, donate_argnums=(0,) if donate else ())

        cache = get_program_cache()
        if not cacheable:
            return cache.wrap_uncached("fedavg_round", builder())
        return cache.get_or_build(
            "fedavg_round",
            {
                "kind": "fedavg_round",
                "model": model_fingerprint(model),
                "train": config.train,
                "epochs": config.fed.epochs,
                "task": task,
                "mode": mode,
                "skip": skip,
                "donate": donate,
                "client_metrics": client_metrics,
                # the whole RobustConfig dataclass (or None) enters the
                # digest — every leaf (defense_type, norm_bound, stddev,
                # num_byzantine/trim_k, multi_krum_m) shapes the traced
                # defense, and the digest audit's drop-field fuzz pins
                # that removing this key fails on exactly those leaves
                # (the scaffold eta_g hazard class)
                "robust": robust,
            },
            builder,
        )

    # A caller-supplied local_train_fn fixed its own skip choice at build
    # time — only the default local train can vary per cohort.
    can_vary = local_train_fn is None and mode == "scan"
    variants: dict = {}

    def variant_for(may_pad: Optional[bool] = None):
        """The underlying jitted round fn for a cohort — for callers that
        need the jit object itself (lower()/cost analysis)."""
        skip = resolve_skip_empty_steps(mode, may_pad if can_vary else None)
        fn = variants.get(skip)
        if fn is None:
            fn = variants[skip] = build(skip)
        return fn

    def dispatch(global_vars, *args, may_pad: Optional[bool] = None):
        return variant_for(may_pad)(global_vars, *args)

    dispatch.supports_may_pad = can_vary
    dispatch.variant_for = variant_for
    dispatch._variants = variants  # introspection for tests
    return dispatch


def make_fedavg_multiround(
    model: ModelDef,
    config: RunConfig,
    steps: int,
    bs: int,
    task: str = "classification",
    local_train_fn: Optional[Callable] = None,
    client_mode: Optional[str] = None,
    may_pad: Optional[bool] = None,
):
    """Fused multi-round FedAvg: T rounds as ONE jitted ``lax.scan`` over the
    HBM-resident data store — zero host round-trips inside the chunk.

    Per-round host work in the eager path (sampling, index building, metric
    fetch, dispatch) dominates small-model rounds, especially through a
    remote-device transport. Here the host precomputes only the per-round
    gather indices (a few KB each; sampling parity with
    FedAVGAggregator.py:80-88 is preserved because sampling stays host-side)
    and the device runs the whole chunk:

        fn(global_vars, flat_x, flat_y, idx_next [T,C,cap],
           mask_next [T,C,cap], num_samples [T,C], round_ids [T], base_rng)
            -> (global_vars', stacked per-round metrics)

    ``idx_next``/``mask_next`` arrive PRE-ROTATED by one round (host-side
    ``roll(-1)`` in ``_fused_plan``): iteration t's xs row is round t+1's
    gather — the double-buffer prefetch — and the last row wraps to round
    0's indices, which the prologue reads back (``idx_next[-1]``) for the
    first batch. Rotating on the host removes the two whole-chunk
    ``jnp.roll`` copies the traced program used to execute per dispatch
    (re-profile finding, ISSUE 14): the bytes shipped are identical, the
    device-side copies are gone.

    Per-round math is identical to :func:`make_fedavg_round` at the same
    (steps, bs): the round body, the fold_in/split PRNG stream, and the
    weighted average are the same code."""
    from fedml_tpu.data.device_store import _gather
    from fedml_tpu.compile import get_program_cache, model_fingerprint

    mode = client_mode or resolve_client_parallelism(
        config.fed.client_parallelism, model
    )
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task,
        skip_empty_steps=resolve_skip_empty_steps(mode, may_pad),
    )
    lifted = client_axis_map(local_train, mode)

    def multi_fn(global_vars, flat_x, flat_y, idx_next, mask_next, num_samples, round_ids, base_rng):
        feat = flat_x.shape[1:]
        lab = flat_y.shape[1:]
        C = idx_next.shape[1]

        def gathered(idx_r, mask_r):
            # shared gather-and-zero-padding contract with the eager path
            x, y = _gather(flat_x, flat_y, idx_r, mask_r)
            return (
                x.reshape((C, steps, bs) + feat),
                y.reshape((C, steps, bs) + lab),
                mask_r.reshape((C, steps, bs)),
            )

        # Double-buffered: each iteration trains on the PRE-GATHERED batch
        # in the carry while gathering the next round's — the gather has no
        # data dependency on this round's result, so XLA is free to overlap
        # it with the round's compute (the eager loop gets the same overlap
        # from async dispatch; without this the fused scan serializes
        # prepare-then-train every round).
        def body(carry, per_round):
            gv, cur = carry
            idx_n, mask_n, ns_r, rid = per_round
            x, y, m = cur
            rng = jax.random.fold_in(base_rng, rid + 1)
            keys = round_client_rngs(rng, C)
            client_vars, metrics = lifted(gv, x, y, m, keys)
            new_global = weighted_average(client_vars, ns_r)
            nxt = gathered(idx_n, mask_n)
            return (new_global, nxt), jax.tree_util.tree_map(
                jnp.sum, metrics
            )

        # the host pre-rotated the index arrays (see docstring): row t is
        # round t+1's gather, row T-1 wraps to round 0's — the prologue
        # batch reads it back here, and the scan's xs rows are already
        # the prefetch stream (no device-side roll copies)
        first = gathered(idx_next[-1], mask_next[-1])
        (gv, _), mets = jax.lax.scan(
            body,
            (global_vars, first),
            (idx_next, mask_next, num_samples, round_ids),
        )
        return gv, mets

    cache = get_program_cache()
    if local_train_fn is not None:
        return cache.wrap_uncached("fedavg_multiround", jax.jit(multi_fn, donate_argnums=(0,)))
    return cache.get_or_build(
        "fedavg_multiround",
        {
            "kind": "fedavg_multiround",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "mode": mode,
            "steps": steps,
            "bs": bs,
            "may_pad": may_pad,
        },
        lambda: jax.jit(multi_fn, donate_argnums=(0,)),
    )


class FedAvgAPI:
    """Standalone FedAvg simulator (ref standalone/fedavg/fedavg_api.py:13-180).

    The reference reuses ``client_num_per_round`` Client objects and re-points
    them at sampled shards each round (fedavg_api.py:47-51); here the analogous
    move is restacking the sampled shards into one padded device batch.
    """

    # Subclasses that read the pre-round global model after the round call
    # (e.g. FedOpt's pseudo-gradient) must disable buffer donation.
    _donate = True
    # Subclasses with their own batch placement (the sharded API pads +
    # shards host arrays over the mesh) disable the HBM-resident store.
    _use_device_store = True
    # Fused multi-round chunks (FedConfig.fused_rounds > 1) are only valid
    # when the round is exactly the plain FedAvg body — subclasses that add
    # per-round host-side work (server optimizer step, robust post hooks)
    # set this False.
    _supports_fused = True
    # Whether this API's round fn may return per-client loss vectors
    # (power_of_choice's true bias signal). Subclasses that combine metric
    # trees across cohorts of different sizes (hierarchical groups) or
    # whose round programs don't emit the vectors (mesh shard_map) fall
    # back to the cohort-mean signal.
    _client_loss_vectors = True
    # Round pipeline (FedConfig.pipeline): subclasses whose train_round
    # bypasses the _round_placed stash contract (hierarchical group
    # loops) or whose _place_batch is not a pure function of
    # (round, config.seed, rng) (the backdoor attack mask reads
    # _current_round) set this False — preparing round r+1 during round
    # r would leak stashes or bake stale state into the batch.
    _supports_pipeline = True

    def __init__(
        self,
        config: RunConfig,
        data: FederatedDataset,
        model: ModelDef,
        task: str = "classification",
        local_train_fn: Optional[Callable] = None,
        log_fn: Optional[Callable[[dict], None]] = None,
    ):
        self.config = config
        self.data = data
        self.model = model
        self.task = task
        self.log_fn = log_fn or (lambda m: None)
        self.rng = jax.random.PRNGKey(config.seed)
        self.global_vars = model.init(jax.random.fold_in(self.rng, 0))
        self._local_train_fn = local_train_fn
        self._fused_fns: dict = {}  # (steps, bs, may_pad) -> jitted multi-round fn
        self._round_plans: dict = {}  # round_idx -> (sampled, steps, bs)
        self._may_pad_cache: dict = {}  # (round_idx, force_steps) -> bool
        self._client_mode = resolve_client_parallelism(
            config.fed.client_parallelism, model
        )
        self.round_fn = self._build_round_fn(local_train_fn)
        self.eval_fn = make_eval_fn(model, task)
        self.history: list = []
        # Resume support: CLI/--resume sets global_vars + start_round from a
        # checkpoint; train() continues the round loop from there (the
        # round-seeded sampling makes the continuation identical to the
        # uninterrupted run).
        self.start_round = 0
        # Telemetry: round-lifecycle spans (round → broadcast/local_train/
        # eval) on the global tracer, and a client health registry updated
        # per round. The vmap/mesh runtimes run the whole cohort as ONE
        # jitted program, so per-client "train time" here is the cohort's
        # shared round wall time — participation/last-seen stay exact, and
        # the transport runtimes refine timing per client.
        self._tracer = get_tracer()
        self.health = ClientHealthRegistry.from_config(config)
        # Scheduler: policy-driven cohort selection (FedConfig.selection /
        # .overprovision_factor, scheduler/policies.py). It shares this
        # API's health registry (straggler_aware consults the straggler
        # flags) and forwards every fresh decision into log_fn, so
        # summary.json records the selected cohort (the CI oracle).
        from fedml_tpu.scheduler import ClientScheduler, FaultInjector

        self.scheduler = ClientScheduler.from_config(
            config,
            num_clients=data.num_clients,
            data=data,
            log_fn=self.log_fn,
            health=self.health,
            tracer=self._tracer,
        )
        # Fault injection (FedConfig.fault_plan): the vmap cohort trains
        # as ONE jitted program, so only participation faults apply here —
        # dropout/crash remove the client from the cohort at selection
        # time (see _apply_participation_faults); timing faults are
        # transport-only.
        self.faults = FaultInjector.from_config(
            config, health=self.health, tracer=self._tracer
        )
        self._fault_cache: dict = {}  # round -> post-fault survivors
        # rounds whose TRUE per-client losses were already fed to the
        # scheduler (train_round's vector fetch) — _log_round must not
        # overwrite them with the cohort mean
        self._client_loss_rounds: set = set()
        # round -> placed device batch, populated by the AOT warmup path
        # AND by the round pipeline (_pipeline_prepare) and consumed
        # (popped) by train_round so neither pays the round's stack + H2D
        # cost twice. The stash is the pipeline's COMMIT POINT: values are
        # pure in (round, config.seed, self.rng), so a stashed batch is
        # byte-identical to the one the serial schedule would build at the
        # round boundary.
        self._warm_placed: dict = {}
        # Round pipeline (FedConfig.pipeline): after round r's async
        # dispatch, the host prepares round r+1's cohort/batch/placement
        # while the device still executes r. _pipeline_overlap holds the
        # measured host seconds hidden per prepared round (attached to
        # that round's span as overlap_s → flight records);
        # pipeline_rounds counts rounds the pipeline prepared ahead.
        if config.fed.pipeline not in ("off", "auto", "on"):
            raise ValueError(
                "FedConfig.pipeline must be 'off', 'auto' or 'on'; got "
                f"{config.fed.pipeline!r}"
            )
        self._pipeline_overlap: dict = {}
        self.pipeline_rounds = 0
        # (start_round, n_rounds) -> (fn, rest): same contract for the
        # fused path — the chunk's gather-index/mask stacking and H2D
        # transfer is paid once at warmup, not again at dispatch. Valid
        # across the warmup->train gap because every rest component is
        # deterministic in (round, config.seed) and self.rng is never
        # reassigned after __init__.
        self._warm_fused: dict = {}
        # Measured fused-vs-eager planner (FedConfig.fused_plan =
        # "measured", algorithms/round_planner.py): probes both schedules
        # over the first rounds — costs read from flight-recorder folds,
        # device-synced during the probe — and commits to the winner per
        # (algorithm, shape-class, cohort). None = legacy static plan.
        self.planner = None
        if (
            config.fed.fused_plan == "measured"
            and self._supports_fused
            and config.fed.fused_rounds > 1
        ):
            from fedml_tpu.algorithms.round_planner import SchedulePlanner

            self.planner = SchedulePlanner(log_fn=self.log_fn).attach(
                self._tracer, config=config
            )
        elif config.fed.fused_plan not in ("static", "measured"):
            raise ValueError(
                "fused_plan must be 'static' or 'measured'; got "
                f"{config.fed.fused_plan!r}"
            )
        self._store = None
        if self._use_device_store and config.data.device_cache:
            from fedml_tpu.data.device_store import DeviceDataStore, fits_on_device

            if fits_on_device(data):
                try:
                    self._store = DeviceDataStore(data)
                except ValueError:
                    # ragged per-client feature shapes cannot concatenate —
                    # the one EXPECTED reason to fall back to host stacking
                    self._store = None
                except Exception:
                    # anything else is a real DeviceDataStore bug: falling
                    # back silently would hide a large perf regression
                    # behind identical results (VERDICT r2 Weak #5)
                    import logging

                    logging.exception(
                        "DeviceDataStore init failed unexpectedly — "
                        "falling back to host stacking (SLOW path); "
                        "investigate, this is not the ragged-shape case"
                    )
                    self._store = None
        self._test_dev = None
        self._local_eval_dev = None  # local_test_on_all_clients cache

    def _build_round_fn(self, local_train_fn):
        return make_fedavg_round(
            self.model,
            self.config,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
            client_mode=self._client_mode,
            client_metrics=self._wants_client_losses(),
        )

    def _wants_client_losses(self) -> bool:
        """True when the round program should emit per-client loss
        vectors: the selection policy feeds on per-client losses AND this
        API's round family supports the vectors. Derived from config (not
        the scheduler object — the round fn is built before it)."""
        return (
            self._client_loss_vectors
            and self.config.fed.selection == "power_of_choice"
        )

    def warmup(self, log_fn=None):
        """AOT-compile this run's programs before round 0
        (``jit(...).lower(...).compile()`` — fedml_tpu/compile/warmup.py):
        the round program for ``start_round``'s cohort shapes (the fused
        chunk program when the planner would fuse), EVERY other
        (steps, bs) shape class the partition can produce (derived via
        ``bucket_steps`` over all client sizes — EAGER rounds 1..R never
        hit a lazy shape-bucket compile), the horizon's fused chunk
        programs (every distinct program × [T, C, cap] signature the
        structural chunk walk reaches, capped — classes/chunks past the
        warmup caps still compile lazily, compile/warmup.py), the eval program, and the
        server-optimizer step when present. When a persistent executable
        cache is installed, warmed programs load from / export to disk,
        so a fresh process warms with zero backend compiles. Emits
        ``compile`` telemetry spans and forwards per-program compile
        seconds + XLA cost analysis (flops/bytes) through ``log_fn`` into
        summary.json. Executes nothing — warm runs are numerically
        identical to cold runs, and warm-from-disk runs byte-identical to
        warm-in-process runs (tests/test_compile.py)."""
        from fedml_tpu.compile import warmup_api

        return warmup_api(self, log_fn=log_fn or self.log_fn)

    def train_round(self, round_idx: int):
        # _round_plan is the one derivation of "this round's cohort" —
        # memoized, shared with the fused chunk planner and _round_may_pad
        sampled, _steps, _bs = self._round_plan(round_idx)
        # "broadcast" = ship the global model + cohort batch to the device
        # (the simulator's analog of the transport path's model broadcast)
        with self._tracer.span(
            "broadcast", round=round_idx, clients=len(sampled)
        ):
            # the AOT warmup path (or the round pipeline, which prepared
            # this round while the previous one executed) already stacked
            # + placed this round's batch — consume it instead of paying
            # the host stack + H2D transfer twice (the inputs are pure
            # functions of (round, rng), so the values are identical
            # either way)
            placed = self._round_placed(round_idx, sampled)
        kw = {}
        if getattr(self.round_fn, "supports_may_pad", False):
            kw["may_pad"] = self._round_may_pad(round_idx)
        # local train + weighted aggregate run fused in ONE jitted program;
        # dispatch is async, so this span's wall time is the host-side
        # dispatch cost, not device time (the device half lives in the
        # --profile_dir jax trace)
        with self._tracer.span(
            "local_train", round=round_idx, clients=len(sampled), fused_aggregate=True
        ):
            self.global_vars, metrics = self.round_fn(
                self.global_vars, *placed, **kw
            )
        if (
            isinstance(metrics, dict)
            and "client_loss_sum" in metrics
            and self.scheduler.wants_client_losses
        ):
            self._report_client_losses(sampled, metrics, round_idx)
        return sampled, metrics

    def _round_placed(self, round_idx: int, sampled):
        """This round's placed device batch: the warmup/pipeline stash
        when one exists (byte-identical by the determinism contract —
        every input is pure in (round, config.seed, self.rng), and
        self.rng is never reassigned after __init__), else built now.
        Shared by FedAvg's train_round and the stateful subclasses
        (SCAFFOLD/Ditto), so the pipeline serves all of them."""
        placed = self._warm_placed.pop(round_idx, None)
        if placed is not None:
            return placed
        batch = self._round_batch(sampled, round_idx)
        rng = jax.random.fold_in(self.rng, round_idx + 1)
        return self._place_batch(batch, rng)

    def _pipeline_prepare(self, next_round: int) -> None:
        """The round pipeline's host stage: while the JUST-DISPATCHED
        round still executes on device (async dispatch), select round
        ``next_round``'s cohort, gather/stack its batch, and issue its
        H2D placement, stashing the result under the ``_warm_placed``
        commit contract. Degrades to serial (returns without stashing)
        whenever preparing ahead could change what the serial schedule
        would do:

        - pipeline "off";
        - adaptive selection (power_of_choice / straggler_aware feed on
          round r's losses/straggler flags before selecting r+1);
        - an active fault plan with participation faults (cohorts shrink
          per round; fault accounting must describe executed rounds);
        - the next segment runs as a fused chunk (it amortizes dispatch
          on device and stacks its own inputs);
        - a planner probe round (its fold must measure the serial
          schedule cost — round_planner.py).

        The measured host seconds land in ``_pipeline_overlap`` and ride
        the next round's span as ``overlap_s`` (flight records)."""
        cfg = self.config
        if (
            cfg.fed.pipeline == "off"
            or not self._supports_pipeline
            or next_round >= cfg.fed.comm_round
        ):
            return
        if next_round in self._warm_placed:
            return  # warmup already stashed it
        if cfg.fed.selection in ("power_of_choice", "straggler_aware"):
            return
        if (
            self.faults is not None
            and self.faults.plan.has_participation_faults()
        ):
            return
        if self._fused_chunk_len(next_round) != 1:
            return
        if self.planner is not None and self.planner.wants_sync(next_round):
            return
        t0 = time.perf_counter()
        sampled, _steps, _bs = self._round_plan(next_round)
        batch = self._round_batch(sampled, next_round)
        rng = jax.random.fold_in(self.rng, next_round + 1)
        self._warm_placed[next_round] = self._place_batch(batch, rng)
        self._pipeline_overlap[next_round] = time.perf_counter() - t0
        self.pipeline_rounds += 1

    def _report_client_losses(self, sampled, metrics, round_idx: int):
        """Feed the scheduler TRUE per-client losses from the round's
        ``client_loss_sum``/``client_count`` vectors — the same per-client
        mean the transport clients attach to their uploads
        (ARG_TRAIN_LOSS), so sim and transport power_of_choice bias on
        identical signals and select identical cohorts. The fetch blocks
        on the round (adaptive policies already run eager, per-round —
        _fused_chunk_len disables chunking for them)."""
        losses = np.asarray(metrics["client_loss_sum"])[: len(sampled)]
        counts = np.asarray(metrics["client_count"])[: len(sampled)]
        for cid, s, c in zip(sampled, losses, counts):
            if c > 0:
                self.scheduler.report_loss(int(cid), float(s) / float(c))
        self._client_loss_rounds.add(int(round_idx))

    def _client_counts(self, sampled):
        if self._store is not None:
            return [int(self._store.counts[i]) for i in sampled]
        return [len(self.data.client_y[i]) for i in sampled]

    def _round_may_pad(self, round_idx: int, force_steps: int = 0) -> bool:
        """Memoized per-round _cohort_may_pad — the fused chunk planner
        asks per round per candidate chunk, and recomputing the count
        loop + bucket math each time would reintroduce the host overhead
        _round_plans was added to remove."""
        key = (round_idx, force_steps)
        v = self._may_pad_cache.get(key)
        if v is None:
            v = self._may_pad_cache[key] = self._cohort_may_pad(
                self._round_plan(round_idx)[0], force_steps
            )
        return v

    def _cohort_may_pad(self, sampled, force_steps: int = 0) -> bool:
        """True iff some sampled client has at least one ALL-padding local
        step — i.e. fewer full batches than the cohort's bucketed step
        count. Host-side static knowledge: picks the round variant with or
        without the per-step cond skip (see resolve_skip_empty_steps).
        ``force_steps`` overrides the bucket (the fused chunk's shared
        step count)."""
        from fedml_tpu.data.base import bucket_steps

        cfg = self.config
        counts = self._client_counts(sampled)
        steps, bs, _ = bucket_steps(
            counts, cfg.data.batch_size, cfg.data.pad_bucket
        )
        steps = max(steps, force_steps)
        return any(-(-int(n) // bs) < steps for n in counts)

    def _stack(self, client_indices, seed: int):
        """Clients as a dense batch: device-store gather (only an index
        matrix crosses the wire) or host stacking fallback. Both paths use
        the same seed/bucket contract, so the math is identical."""
        cfg = self.config
        if self._store is not None:
            return self._store.round_batch(
                client_indices,
                cfg.data.batch_size,
                seed=seed,
                pad_bucket=cfg.data.pad_bucket,
            )
        return stack_clients(
            self.data,
            client_indices,
            cfg.data.batch_size,
            seed=seed,
            pad_bucket=cfg.data.pad_bucket,
        )

    def _round_batch(self, sampled, round_idx: int):
        return self._stack(sampled, self.config.seed * 1_000_003 + round_idx)

    def local_test_on_all_clients(self, round_idx: int = 0) -> Dict[str, float]:
        """Evaluate the global model on every client's local data (ref
        fedavg_api.py:117-180 ``_local_test_on_all_clients``): train metrics
        over all clients' train shards, test metrics over their test shards
        (falling back to the central test set when the dataset has no
        per-client test split). The reference aggregates per-client sums
        with sample weights — identical to pooled evaluation, so the shards
        are concatenated and run through the jitted eval fn in one pass.
        ``fed.ci`` short-circuits to client 0 only (ref :162-167)."""
        from fedml_tpu.train.evaluate import pad_to_batches

        if self._local_eval_dev is None:
            # the pooled shards are round-invariant: pad + place on device
            # ONCE (same reason evaluate_global caches _test_dev)
            ci = self.config.fed.ci
            ids = [0] if ci else range(self.data.num_clients)
            xs = np.concatenate([self.data.client_x[i] for i in ids], axis=0)
            ys = np.concatenate([self.data.client_y[i] for i in ids], axis=0)
            self._local_eval_dev = {
                split: tuple(
                    map(jnp.asarray, pad_to_batches(x, y, 256))
                )
                for split, (x, y) in {
                    "Train": (xs, ys),
                    "Test": self._client_test_pool(ids),
                }.items()
            }
        from fedml_tpu.train.evaluate import metrics_to_loss_acc

        row = {"round": round_idx}
        for split, batches in self._local_eval_dev.items():
            loss, acc = metrics_to_loss_acc(
                self.eval_fn(self.global_vars, *batches)
            )
            row[f"{split}/Loss"], row[f"{split}/Acc"] = loss, acc
        return row

    def _client_test_pool(self, ids):
        if self.data.client_test_x is not None:
            return (
                np.concatenate([self.data.client_test_x[i] for i in ids], axis=0),
                np.concatenate([self.data.client_test_y[i] for i in ids], axis=0),
            )
        return np.asarray(self.data.test_x), np.asarray(self.data.test_y)

    def _eval_batches(self):
        """The central test set as padded device batches, cached (the host
        arrays would otherwise be re-shipped every eval). Shared by
        evaluate_global and the AOT warmup path, so the warmed eval
        program sees exactly the shapes the run will dispatch."""
        from fedml_tpu.train.evaluate import pad_to_batches

        if self._test_dev is None:
            xb, yb, mb = pad_to_batches(
                np.asarray(self.data.test_x), np.asarray(self.data.test_y), 256
            )
            self._test_dev = (jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb))
        return self._test_dev

    def evaluate_global(self):
        """(loss, acc) of the global model on the central test set."""
        from fedml_tpu.train.evaluate import metrics_to_loss_acc

        return metrics_to_loss_acc(
            self.eval_fn(self.global_vars, *self._eval_batches())
        )

    def round_flops(self, round_idx: int = 0):
        """XLA-costed FLOPs of one round call at this round's batch shapes
        (None if the backend exposes no cost model). Lowering reuses the
        jit cache, so this is cheap after the first round has compiled."""
        from fedml_tpu.utils.profiling import compiled_flops

        sampled, _steps, _bs = self._round_plan(round_idx)
        batch = self._round_batch(sampled, round_idx)
        rng = jax.random.fold_in(self.rng, round_idx + 1)
        fn = self.round_fn
        if hasattr(fn, "variant_for"):
            fn = fn.variant_for(self._round_may_pad(round_idx))
        return compiled_flops(
            fn, self.global_vars, *self._place_batch(batch, rng)
        )

    def _spill_pad_ids(self, sampled):
        """(store-gather ids, real count) for the stateful algorithms'
        SPILLED state tier. Defined on the common root so the mesh
        runtime's override (DistributedFedAvgAPI: pad to the shard count,
        dummy id 0) wins in every Distributed* MRO."""
        return np.asarray(sampled, np.int64), len(sampled)

    def _place_cohort_rows(self, rows):
        """Spilled-store cohort rows -> device (mesh override shards them
        over the client axis)."""
        return jax.tree_util.tree_map(jnp.asarray, rows)

    def _place_batch(self, batch, round_rng):
        """Device placement hook — the sharded subclass pads the client axis
        to the mesh and shards these arrays over it."""
        return (
            jnp.asarray(batch.x),
            jnp.asarray(batch.y),
            jnp.asarray(batch.mask),
            jnp.asarray(batch.num_samples),
            round_client_rngs(round_rng, batch.num_clients),
        )

    def _round_plan(self, round_idx: int):
        """(sampled, steps, bs) of one round, memoized: the chunk planner
        walks rounds ahead of execution and train_rounds_fused then visits
        the same rounds — recomputing the round-seeded sampling and the
        bucket math twice per round was the fused path's last measurable
        overhead vs eager."""
        plan = self._round_plans.get(round_idx)
        if plan is None:
            from fedml_tpu.data.base import bucket_steps

            cfg = self.config
            sampled = self._sample_clients(round_idx)
            steps, bs, _ = bucket_steps(
                # an empty cohort (possible under DP's Poisson sampling) still
                # needs a well-formed shape class — shape it like 1 sample
                self._client_counts(sampled) if len(sampled) else [1],
                cfg.data.batch_size,
                cfg.data.pad_bucket,
            )
            plan = (sampled, steps, bs)
            self._round_plans[round_idx] = plan
        return plan

    def _sample_clients(self, round_idx: int) -> np.ndarray:
        """This round's cohort draw, via the scheduler registry
        (FedConfig.selection; the default ``uniform`` policy is the
        reference-parity round-seeded fixed-size draw) — deterministic by
        design, so runs are reproducible and resumable, minus any clients
        the fault plan removes. Algorithms whose GUARANTEES depend on the
        randomness of participation override this (DP-FedAvg draws Poisson
        cohorts from a run-seeded secret stream: privacy amplification by
        subsampling is void if the adversary can predict who participated
        — privacy/dp_fedavg.py)."""
        sel = self.scheduler.select(round_idx)
        if self.faults is not None:
            sel = self._apply_participation_faults(sel, round_idx)
        return sel

    def _apply_participation_faults(self, selected, round_idx: int) -> np.ndarray:
        """Simulator fault semantics (scheduler/faults.py): dropout/crash
        remove the client from the cohort before batching. Memoized per
        round — the chunk planner, train loop, and metric flush all
        re-derive the cohort, and the injector's counters must count each
        fault once. At least one survivor is kept so the round's jitted
        shapes stay well-formed."""
        r = int(round_idx)
        cached = self._fault_cache.get(r)
        if cached is not None:
            return cached
        decisions = [(int(cid), self.faults.decide(int(cid), r)) for cid in selected]
        survivors = [cid for cid, d in decisions if d.participates]
        spared = None
        if not survivors:
            # every selected client faulted: spare the first one so the
            # round stays well-formed — and do NOT record a fault for it
            # (it actually trains; accounting must describe what ran)
            spared = int(selected[0])
            survivors = [spared]
            import logging

            logging.warning(
                "fault plan removed the ENTIRE round-%d cohort; sparing "
                "client %d so the round stays well-formed", r, spared,
            )
        for cid, d in decisions:
            if cid == spared or d.participates:
                continue
            self.faults.record(cid, r, "crash" if d.crashed else "dropout")
        out = np.asarray(survivors, np.int64)
        self._fault_cache[r] = out
        return out

    def _round_steps_class(self, round_idx: int):
        """(steps, bs) bucket of one round's sampled cohort — the jit-shape
        class of that round."""
        sampled, steps, bs = self._round_plan(round_idx)
        return steps, bs

    def _fused_chunk_len(self, round_idx: int, structural: bool = False) -> int:
        """Rounds [round_idx, round_idx+L) that can run as one fused chunk:
        bounded by fused_rounds, the horizon, the next eval round (eval
        fires after rounds where r % frequency == 0), and — under vmap —
        the first steps-class change (round-2's fused feature padded the
        whole chunk to the chunk-max steps, which under vmap cost more in
        padded conv compute than the amortized dispatch saved: BENCH_r02
        fused 13% slower than eager, VERDICT r2 Weak #2). Under the scan
        schedule a chunk may span classes: padding steps are cond-skipped
        (train_rounds_fused compiles the cond in whenever the chunk has
        any), so spanned rounds pay only the ~3% cond tax, not padded
        compute.

        ``structural=True`` returns the structural answer WITHOUT
        consulting the measured planner — the warmup chunk walk
        enumerates every fusable program regardless of which schedule
        the probe later commits (planning a probe segment for a round
        warmup merely inspects would corrupt the probe)."""
        cfg = self.config
        if (
            cfg.fed.fused_rounds <= 1
            or not self._supports_fused
            or self._store is None
            # full-batch mode sets bs = max client size, which varies per
            # round — chunks can't share one (steps, bs) shape
            or cfg.data.batch_size == -1
            # adaptive policies feed on per-round signals (reported losses,
            # straggler flags): the chunk planner derives cohorts AHEAD of
            # execution, which would freeze those signals at planning time
            # and make selection depend on fused_rounds — eager rounds keep
            # the feedback loop per-round (scheduler determinism contract)
            or cfg.fed.selection in ("power_of_choice", "straggler_aware")
            # participation faults shrink cohorts per round: rounds of size
            # k and k-1 share a (steps, bs) class but not a client-axis
            # size, and train_rounds_fused stacks per-round index matrices
            # into one [T, C, cap] array — a ragged C would crash mid-run
            or (
                self.faults is not None
                and self.faults.plan.has_participation_faults()
            )
        ):
            return 1
        L = min(cfg.fed.fused_rounds, cfg.fed.comm_round - round_idx)
        # Under the scan client schedule, padded steps are skipped lax.cond
        # branches (train/client.py step_body), so a chunk can pad every
        # round to the chunk-max step count and span steps classes: the
        # chunk's local train carries the cond whenever any padding exists
        # (chunk_may_pad in train_rounds_fused), which makes the padding
        # itself ~free at the cost of the cond tax (~3% of a round,
        # interleaved-measured) on the chunk's pad-free rounds. Under vmap
        # the padding runs real compute (the round-2 fused regression,
        # VERDICT r2 Weak #2) — cut the chunk at the first class change
        # instead.
        pad_free = self._client_mode == "scan"
        klass = self._round_steps_class(round_idx)
        struct = None
        for off in range(L):
            r = round_idx + off
            if (
                not pad_free
                and off > 0
                and self._round_steps_class(r) != klass
            ):
                L = off
                break
            if r % cfg.fed.frequency_of_the_test == 0:
                # an eval round must be the LAST round of its chunk (eval
                # reads global_vars right after that round)
                struct = off + 1
                break
        if struct is None:
            # round down to a power of two: chunk length is part of the
            # jit shape key, and run lengths are arbitrary — the cap
            # bounds compiles to log2(fused_rounds) lengths per
            # (steps, bs) class
            struct = 1 << (L.bit_length() - 1)
        if struct <= 1 or self.planner is None or structural:
            return struct
        # measured planning: the structural length says fusion is
        # POSSIBLE here; whether it runs fused is the planner's measured
        # decision (probe → commit; idempotent per round, so warmup and
        # the train loop see one answer)
        from fedml_tpu.algorithms.round_planner import PlanKey

        steps, bs = self._round_steps_class(round_idx)
        return self.planner.plan(
            PlanKey(
                algo=type(self).__name__,
                steps=int(steps),
                bs=int(bs),
                cohort=len(self._round_plan(round_idx)[0]),
            ),
            round_idx,
            struct,
        )

    def train_rounds_fused(self, start_round: int, n_rounds: int):
        """Run rounds [start_round, start_round+n_rounds) as one on-device
        scan (see :func:`make_fedavg_multiround`). Returns stacked per-round
        metrics {loss_sum, correct, count, steps: [T]}."""
        plan = self._warm_fused.pop((start_round, n_rounds), None)
        fn, rest = plan if plan is not None else self._fused_plan(
            start_round, n_rounds
        )
        self.global_vars, metrics = fn(self.global_vars, *rest)
        return metrics

    def _fused_plan(self, start_round: int, n_rounds: int):
        """(fused program, its non-model args) for one chunk — the round
        indices/masks/weights plus the jitted multi-round fn from the
        per-shape cache. Split out of :meth:`train_rounds_fused` so the
        AOT warmup path can lower/compile the exact chunk program round 0
        will dispatch without executing it."""
        cfg = self.config
        store = self._store
        if cfg.data.batch_size == -1:
            raise ValueError(
                "fused rounds do not support batch_size=-1 (full batch): "
                "bs varies with each round's max client size"
            )
        per_round = []
        max_steps = bs = 0
        for off in range(n_rounds):
            r = start_round + off
            sampled, steps_r, bs = self._round_plan(r)
            per_round.append((r, sampled))
            if (
                self._client_mode == "vmap"
                and max_steps
                and steps_r != max_steps
            ):
                # under vmap, padded steps run real compute — fusing across
                # a class change would silently pay padded conv compute for
                # every round in the chunk (the round-2 regression); the
                # scan schedule skips padded steps, so there it's free
                raise ValueError(
                    f"rounds {start_round}..{start_round + n_rounds - 1} span "
                    f"steps classes {max_steps} and {steps_r}; fuse only "
                    "within one class under client_parallelism='vmap' "
                    "(see _fused_chunk_len)"
                )
            max_steps = max(max_steps, steps_r)
        idxs, masks, ns = [], [], []
        for r, sampled in per_round:
            idx, mask, _, _, ns_r = store.round_indices(
                sampled, cfg.data.batch_size, seed=cfg.seed * 1_000_003 + r,
                pad_bucket=cfg.data.pad_bucket, force_steps=max_steps,
            )
            idxs.append(idx)
            masks.append(mask)
            ns.append(ns_r)
        # Only the default scan-mode local train can vary its cond on
        # may_pad (make_fedavg_round's can_vary rule) — anywhere else the
        # flag wouldn't change the compiled program, and keying the cache
        # on it would duplicate whole-chunk compiles for nothing.
        can_vary = self._client_mode == "scan" and self._local_train_fn is None
        chunk_may_pad = can_vary and any(
            self._round_may_pad(r, force_steps=max_steps)
            for r, _ in per_round
        )
        key = (max_steps, bs, chunk_may_pad)
        fn = self._fused_fns.get(key)
        if fn is None:
            fn = make_fedavg_multiround(
                self.model, cfg, max_steps, bs, task=self.task,
                local_train_fn=self._local_train_fn,
                client_mode=self._client_mode,
                may_pad=chunk_may_pad,
            )
            self._fused_fns[key] = fn
        # rotate by one round on the HOST (row t = round t+1's indices,
        # last row wraps to round 0's): the scan consumes the rotated
        # stack directly as its prefetch stream and the prologue reads
        # round 0's gather back from the last row — this replaced two
        # whole-chunk device-side jnp.roll copies per dispatch (ISSUE 14
        # re-profile). Same bytes over the wire, zero device copies.
        return fn, (
            store.flat_x,
            store.flat_y,
            jnp.asarray(np.stack(idxs[1:] + idxs[:1])),
            jnp.asarray(np.stack(masks[1:] + masks[:1])),
            jnp.asarray(np.asarray(ns, np.float32)),
            jnp.arange(start_round, start_round + n_rounds, dtype=jnp.int32),
            self.rng,
        )

    def _log_round(self, round_idx: int, metrics, round_time_s: float) -> dict:
        cfg = self.config
        count = float(metrics["count"])
        row = {
            "round": round_idx,
            "Train/Loss": float(metrics["loss_sum"]) / max(count, 1e-9),
            "Train/Acc": float(metrics["correct"]) / max(count, 1e-9),
            "round_time_s": round_time_s,
        }
        # feed power_of_choice: rounds whose program emitted per-client
        # loss vectors already reported TRUE per-client losses
        # (_report_client_losses — sim/transport parity); everything else
        # (fused chunks, mesh/hierarchical rounds) falls back to the
        # cohort mean reported to every participant
        if round_idx not in self._client_loss_rounds:
            for cid in self._round_plan(round_idx)[0]:
                self.scheduler.report_loss(int(cid), row["Train/Loss"])
        if self._is_eval_round(round_idx):
            with self._tracer.span("eval", round=round_idx):
                if cfg.fed.eval_on_clients:
                    local = self.local_test_on_all_clients(round_idx)
                    # local-train metrics describe ALL clients (not just this
                    # round's cohort) — override the cohort sums, ref schema
                    row.update(
                        {k: v for k, v in local.items() if k != "round"}
                    )
                else:
                    row["Test/Loss"], row["Test/Acc"] = self.evaluate_global()
        self.history.append(row)
        self.log_fn(row)
        return row

    def _is_eval_round(self, round_idx: int) -> bool:
        cfg = self.config
        return (
            round_idx % cfg.fed.frequency_of_the_test == 0
            or round_idx == cfg.fed.comm_round - 1
        )

    _METRIC_KEYS = ("correct", "count", "loss_sum", "steps")

    def _pack_metrics(self, metrics) -> "jnp.ndarray":
        """One round's metrics dict -> a [K] device vector (single dispatch,
        issued while the round itself is still in flight), or a [T, K]
        matrix for a fused chunk's stacked metrics."""
        return jnp.stack(
            [jnp.asarray(metrics[k]) for k in self._METRIC_KEYS], axis=-1
        )

    def _flush_pending(self, pending) -> dict:
        """Fetch all deferred per-round metrics in ONE device->host transfer
        and log them in order. Fetching per round costs a full host-device
        round-trip each time (through a remote-device tunnel that is the
        dominant cost of the whole training loop — measured ~400 ms/round
        vs ~35 ms compute); rounds were already packed to device vectors as
        they completed, so the flush is one concat + one transfer."""
        final = {}
        if not pending:
            return final
        host = np.asarray(
            jnp.concatenate(
                [v if v.ndim == 2 else v[None] for _, v, _ in pending]
            )
        )
        rows = []
        for (r, v, dt) in pending:
            n = v.shape[0] if v.ndim == 2 else 1
            for off in range(n):
                rows.append((r + off, dt))
        for (r, dt), vals in zip(rows, host):
            final = self._log_round(
                r, dict(zip(self._METRIC_KEYS, vals)), dt
            )
        pending.clear()
        return final

    def train(self) -> Dict[str, float]:
        cfg = self.config
        final = {}
        round_idx = self.start_round
        pending = []  # (round_idx, device metrics, round_time_s)
        while round_idx < cfg.fed.comm_round:
            L = self._fused_chunk_len(round_idx)
            t0 = time.perf_counter()
            # measured-probe segments sync on the device INSIDE the round
            # span: async dispatch makes an unsynced span measure host
            # dispatch only, and the planner's fused-vs-eager commitment
            # must compare true schedule costs (round_planner.py). Zero
            # rounds pay this after the probe commits.
            probe = self.planner is not None and self.planner.wants_sync(
                round_idx
            )
            if L > 1:
                with self._tracer.span(
                    "round", round=round_idx, fused_rounds=L
                ):
                    metrics = self.train_rounds_fused(round_idx, L)
                    if probe:
                        jax.block_until_ready(self.global_vars)
                dt = (time.perf_counter() - t0) / L
                pending.append((round_idx, self._pack_metrics(metrics), dt))
                first_round, last_round = round_idx, round_idx + L - 1
                round_idx += L
            else:
                # a round the pipeline prepared carries its measured
                # hidden-host-time as span attrs — the flight recorder
                # folds them into the round record (overlap_s), keeping
                # the phase accounting honest under overlap: this span's
                # broadcast phase is ~0 BECAUSE overlap_s was spent
                # during the previous round's device execution
                attrs = {}
                ov = self._pipeline_overlap.pop(round_idx, None)
                if ov is not None:
                    attrs = {"overlap_s": round(ov, 6), "pipeline_depth": 1}
                with self._tracer.span("round", round=round_idx, **attrs):
                    _, metrics = self.train_round(round_idx)
                    if probe:
                        jax.block_until_ready(self.global_vars)
                dt = time.perf_counter() - t0
                pending.append(
                    (round_idx, self._pack_metrics(metrics), dt)
                )
                first_round = last_round = round_idx
                round_idx += 1
            # round pipeline: the dispatched rounds are still executing on
            # device (async dispatch; probe segments already synced inside
            # their span) — prepare the NEXT round's cohort/batch/placement
            # now, so its broadcast phase is host time the device never
            # waits for. Commit point: the _warm_placed stash popped at the
            # round boundary; _pipeline_prepare degrades to serial for
            # adaptive policies, fault plans, fused chunks and probe rounds.
            self._pipeline_prepare(round_idx)
            # health: the cohort trained as one program — every sampled
            # client shares the round's wall time; participation/last-seen
            # are exact per client (_round_plan is memoized, so this costs
            # no re-sampling)
            for r in range(first_round, last_round + 1):
                for cid in self._round_plan(r)[0]:
                    self.health.observe_train(int(cid), r, dt)
            # Flush when the LAST executed round is an eval round — eval
            # must read global_vars exactly as of that round, and
            # _fused_chunk_len guarantees eval rounds terminate their
            # chunk. Also flush periodically so history never lags far
            # behind the device.
            if self._is_eval_round(last_round) or len(pending) >= 64:
                final = self._flush_pending(pending)
        final = self._flush_pending(pending) or final
        return final
