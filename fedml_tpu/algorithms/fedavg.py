"""FedAvg — the flagship algorithm (ref: fedml_api/distributed/fedavg/ +
fedml_api/standalone/fedavg/).

The reference spends ~566 LoC on a server FSM + client managers + MPI wire
(SURVEY §3.1); here the whole communication round is one pure function::

    (global_variables, stacked_client_batch, weights, rng)
        -> (global_variables', metrics)

vmap over the client axis = the standalone simulator
(ref fedavg_api.py:40-84's sequential loop, HOT LOOP of SURVEY §3.2);
the same function jitted with the client axis sharded over a device mesh =
the distributed runtime (ref FedAvgServerManager/ClientManager + MPI).
Aggregation is the sample-weighted average of FedAVGAggregator.py:51-78 as a
single tensordot over the client axis (XLA lowers it to an all-reduce when
sharded) instead of a Python loop over state_dict keys (HOT LOOP #3)."""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import RunConfig
from fedml_tpu.data.base import FederatedDataset, stack_clients
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import make_local_train
from fedml_tpu.train.evaluate import make_eval_fn


def weighted_average(stacked_tree, weights):
    """Sample-weighted average over the leading client axis
    (ref FedAVGAggregator.py:51-78: w = n_k/n_total per key)."""
    wsum = jnp.sum(weights)
    return jax.tree_util.tree_map(
        lambda p: jnp.tensordot(weights, p.astype(jnp.float32), axes=1) / wsum,
        stacked_tree,
    )


def client_sampling(round_idx: int, client_num_in_total: int, client_num_per_round: int) -> np.ndarray:
    """Round-seeded sampling for reproducibility — exact parity with
    FedAVGAggregator.py:80-88 (np.random.seed(round_idx) then choice without
    replacement)."""
    if client_num_per_round > client_num_in_total:
        raise ValueError(
            f"client_num_per_round={client_num_per_round} exceeds "
            f"client_num_in_total={client_num_in_total}"
        )
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total)
    np.random.seed(round_idx)
    return np.random.choice(
        range(client_num_in_total), client_num_per_round, replace=False
    )


def round_client_rngs(round_rng, num_sampled: int):
    """Per-client PRNG keys for one round. Generated once per round from the
    round-folded key so the stream is independent of how clients are later
    padded/sharded over a mesh (single-chip and N-shard runs see identical
    per-client randomness)."""
    return jax.random.split(round_rng, num_sampled)


def make_fedavg_round(
    model: ModelDef,
    config: RunConfig,
    task: str = "classification",
    local_train_fn: Optional[Callable] = None,
    donate: bool = True,
    post_train: Optional[Callable] = None,
    post_aggregate: Optional[Callable] = None,
):
    """Build the jitted FedAvg round function (vmap over clients, one chip).

    ``local_train_fn`` lets algorithm variants (FedProx via prox_mu, FedNova
    via its own trainer) reuse this round skeleton. ``post_train(client_vars,
    global_vars, *extra)`` transforms the stacked per-client results before
    averaging (robust clipping); ``post_aggregate(new_global, *extra)``
    transforms the average (weak-DP noise); any positional round-fn
    arguments beyond client_rngs are forwarded to both hooks (e.g. a noise
    rng supplied by the API's _place_batch)."""
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task
    )

    def round_fn(global_vars, x, y, mask, num_samples, client_rngs, *extra):
        client_vars, metrics = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0)
        )(global_vars, x, y, mask, client_rngs)
        if post_train is not None:
            client_vars = post_train(client_vars, global_vars, *extra)
        new_global = weighted_average(client_vars, num_samples)
        if post_aggregate is not None:
            new_global = post_aggregate(new_global, *extra)
        agg_metrics = jax.tree_util.tree_map(jnp.sum, metrics)
        return new_global, agg_metrics

    return jax.jit(round_fn, donate_argnums=(0,) if donate else ())


class FedAvgAPI:
    """Standalone FedAvg simulator (ref standalone/fedavg/fedavg_api.py:13-180).

    The reference reuses ``client_num_per_round`` Client objects and re-points
    them at sampled shards each round (fedavg_api.py:47-51); here the analogous
    move is restacking the sampled shards into one padded device batch.
    """

    # Subclasses that read the pre-round global model after the round call
    # (e.g. FedOpt's pseudo-gradient) must disable buffer donation.
    _donate = True
    # Subclasses with their own batch placement (the sharded API pads +
    # shards host arrays over the mesh) disable the HBM-resident store.
    _use_device_store = True

    def __init__(
        self,
        config: RunConfig,
        data: FederatedDataset,
        model: ModelDef,
        task: str = "classification",
        local_train_fn: Optional[Callable] = None,
        log_fn: Optional[Callable[[dict], None]] = None,
    ):
        self.config = config
        self.data = data
        self.model = model
        self.task = task
        self.log_fn = log_fn or (lambda m: None)
        self.rng = jax.random.PRNGKey(config.seed)
        self.global_vars = model.init(jax.random.fold_in(self.rng, 0))
        self.round_fn = self._build_round_fn(local_train_fn)
        self.eval_fn = make_eval_fn(model, task)
        self.history: list = []
        # Resume support: CLI/--resume sets global_vars + start_round from a
        # checkpoint; train() continues the round loop from there (the
        # round-seeded sampling makes the continuation identical to the
        # uninterrupted run).
        self.start_round = 0
        self._store = None
        if self._use_device_store and config.data.device_cache:
            from fedml_tpu.data.device_store import DeviceDataStore, fits_on_device

            if fits_on_device(data):
                try:
                    self._store = DeviceDataStore(data)
                except Exception:
                    self._store = None  # ragged feature shapes etc.
        self._test_dev = None

    def _build_round_fn(self, local_train_fn):
        return make_fedavg_round(
            self.model,
            self.config,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )

    def train_round(self, round_idx: int):
        cfg = self.config
        sampled = client_sampling(
            round_idx, self.data.num_clients, cfg.fed.client_num_per_round
        )
        batch = self._round_batch(sampled, round_idx)
        rng = jax.random.fold_in(self.rng, round_idx + 1)
        self.global_vars, metrics = self.round_fn(
            self.global_vars, *self._place_batch(batch, rng)
        )
        return sampled, metrics

    def _stack(self, client_indices, seed: int):
        """Clients as a dense batch: device-store gather (only an index
        matrix crosses the wire) or host stacking fallback. Both paths use
        the same seed/bucket contract, so the math is identical."""
        cfg = self.config
        if self._store is not None:
            return self._store.round_batch(
                client_indices,
                cfg.data.batch_size,
                seed=seed,
                pad_bucket=cfg.data.pad_bucket,
            )
        return stack_clients(
            self.data,
            client_indices,
            cfg.data.batch_size,
            seed=seed,
            pad_bucket=cfg.data.pad_bucket,
        )

    def _round_batch(self, sampled, round_idx: int):
        return self._stack(sampled, self.config.seed * 1_000_003 + round_idx)

    def evaluate_global(self):
        """(loss, acc) of the global model on the central test set, with the
        padded test batches cached on device (the host arrays would
        otherwise be re-shipped every eval)."""
        from fedml_tpu.train.evaluate import pad_to_batches

        if self._test_dev is None:
            xb, yb, mb = pad_to_batches(
                np.asarray(self.data.test_x), np.asarray(self.data.test_y), 256
            )
            self._test_dev = (jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb))
        m = self.eval_fn(self.global_vars, *self._test_dev)
        count = float(m["count"])
        return (
            float(m["loss_sum"]) / max(count, 1e-9),
            float(m["correct"]) / max(count, 1e-9),
        )

    def round_flops(self, round_idx: int = 0):
        """XLA-costed FLOPs of one round call at this round's batch shapes
        (None if the backend exposes no cost model). Lowering reuses the
        jit cache, so this is cheap after the first round has compiled."""
        from fedml_tpu.utils.profiling import compiled_flops

        cfg = self.config
        sampled = client_sampling(
            round_idx, self.data.num_clients, cfg.fed.client_num_per_round
        )
        batch = self._round_batch(sampled, round_idx)
        rng = jax.random.fold_in(self.rng, round_idx + 1)
        return compiled_flops(
            self.round_fn, self.global_vars, *self._place_batch(batch, rng)
        )

    def _place_batch(self, batch, round_rng):
        """Device placement hook — the sharded subclass pads the client axis
        to the mesh and shards these arrays over it."""
        return (
            jnp.asarray(batch.x),
            jnp.asarray(batch.y),
            jnp.asarray(batch.mask),
            jnp.asarray(batch.num_samples),
            round_client_rngs(round_rng, batch.num_clients),
        )

    def train(self) -> Dict[str, float]:
        cfg = self.config
        final = {}
        for round_idx in range(self.start_round, cfg.fed.comm_round):
            t0 = time.perf_counter()
            _, metrics = self.train_round(round_idx)
            count = float(metrics["count"])
            row = {
                "round": round_idx,
                "Train/Loss": float(metrics["loss_sum"]) / max(count, 1e-9),
                "Train/Acc": float(metrics["correct"]) / max(count, 1e-9),
                "round_time_s": time.perf_counter() - t0,
            }
            if (
                round_idx % cfg.fed.frequency_of_the_test == 0
                or round_idx == cfg.fed.comm_round - 1
            ):
                row["Test/Loss"], row["Test/Acc"] = self.evaluate_global()
            self.history.append(row)
            self.log_fn(row)
            final = row
        return final
