"""Split learning — model cut at a layer; clients own the bottom, the server
owns the top; activations flow up, activation-grads flow back, clients take
turns around a relay ring (ref: fedml_api/distributed/split_nn/
{SplitNNAPI.py:9-40, client.py:24-34 forward/backward + ring neighbors
:12-13, server.py:40-60 loss + acts.grad}).

Two runtimes:

- :class:`SplitNNAPI` — the fused simulator: client-bottom and server-top are
  two param groups of one jitted step; jax.grad through the composition IS
  the activation-gradient exchange. The ring relay (one active client at a
  time, weights handed to the next; ref SplitNNAPI relay) becomes a
  sequential pass over clients reusing the same bottom params — semantically
  identical, compiled once.
- :func:`split_step_with_boundary` — the explicit two-party step that cuts
  the vjp exactly where the reference cuts the wire (client uploads acts,
  server returns ∂L/∂acts); used by the transport managers and to verify
  the fused path's math."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.models import ModelDef


def default_split_models(input_shape, num_classes: int, width: int = 32):
    """Default bottom/top cut for the CLI: a conv feature extractor on the
    client, dense head on the server (the reference cuts its CNN the same
    way — clients hold convs, server holds the classifier,
    SplitNNAPI.py:9-40)."""
    import flax.linen as nn

    class Bottom(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            if x.ndim == 2:  # flat features
                return nn.relu(nn.Dense(width)(x))
            x = nn.relu(nn.Conv(width, (3, 3), strides=(2, 2))(x))
            x = nn.relu(nn.Conv(width, (3, 3), strides=(2, 2))(x))
            return x.reshape((x.shape[0], -1))

    class Top(nn.Module):
        @nn.compact
        def __call__(self, a, train=False):
            a = nn.relu(nn.Dense(2 * width)(a))
            return nn.Dense(num_classes)(a)

    bottom = ModelDef(Bottom(), tuple(input_shape), num_classes, name="split_bottom")
    # the top's input width is whatever the bottom emits — derive it from
    # an abstract eval of the cut instead of hand-replicating the conv
    # stride arithmetic (which silently drifts if either changes)
    x_sds = jax.ShapeDtypeStruct((1,) + tuple(input_shape), jnp.float32)
    abstract_vars = jax.eval_shape(bottom.module.init, jax.random.PRNGKey(0), x_sds)
    acts = jax.eval_shape(
        lambda v, x: bottom.module.apply(v, x, train=False), abstract_vars, x_sds
    )
    feat_dim = int(acts.shape[-1])
    top = ModelDef(Top(), (feat_dim,), num_classes, name="split_top")
    return bottom, top


class SplitNNAPI:
    """Fused split-learning simulator over a client ring."""

    def __init__(
        self,
        bottom: ModelDef,
        top: ModelDef,
        lr: float = 0.1,
        momentum: float = 0.9,
        wd: float = 5e-4,
        seed: int = 0,
    ):
        from fedml_tpu.splitfed.programs import (
            make_split_optimizer,
            make_splitnn_fused_step,
        )

        self.bottom = bottom
        self.top = top
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        self.bottom_vars = bottom.init(k1)
        self.top_vars = top.init(k2)
        # ref client optimizer: SGD(0.1, momentum=0.9, wd=5e-4) client.py:18-19
        self.opt = make_split_optimizer(lr, momentum, wd)
        self.opt_state = self.opt.init(
            {"bottom": self.bottom_vars["params"], "top": self.top_vars["params"]}
        )
        # the fused step is a digested ProgramCache factory shared with the
        # transport runtime (fedml_tpu/splitfed/programs.py) — warmed, deduped,
        # and persisted like every other program in the stack
        self._step = make_splitnn_fused_step(
            bottom, top, lr=lr, momentum=momentum, wd=wd
        )

    def train_ring(self, client_data, batch_size: int = 32, epochs_per_client: int = 1):
        """Relay ring: each client in turn runs its epochs with the shared
        bottom weights (ref relay turn-taking, client.py:12-13, run at
        SplitNNAPI.py:30-40)."""
        params = {
            "bottom": self.bottom_vars["params"],
            "top": self.top_vars["params"],
        }
        stats = []
        for x, y in client_data:  # ring order
            n = len(y)
            for _ in range(epochs_per_client):
                for s in range(0, n - batch_size + 1, batch_size):
                    params, self.opt_state, loss, correct = self._step(
                        params,
                        self.opt_state,
                        jnp.asarray(x[s : s + batch_size]),
                        jnp.asarray(y[s : s + batch_size]),
                    )
            stats.append({"loss": float(loss)})
        self.bottom_vars = {"params": params["bottom"]}
        self.top_vars = {"params": params["top"]}
        return stats

    def evaluate(self, x, y, batch_size: int = 128):
        correct = total = 0
        for s in range(0, len(y), batch_size):
            xb = jnp.asarray(x[s : s + batch_size])
            acts, _ = self.bottom.apply(self.bottom_vars, xb, train=False)
            logits, _ = self.top.apply(self.top_vars, acts, train=False)
            correct += int(jnp.sum(jnp.argmax(logits, -1) == jnp.asarray(y[s : s + batch_size])))
            total += len(xb)
        return correct / max(total, 1)


def split_step_with_boundary(
    bottom: ModelDef,
    top: ModelDef,
    bottom_vars: dict,
    top_vars: dict,
    x,
    y,
) -> Tuple[jnp.ndarray, dict, dict]:
    """One forward/backward with the explicit wire boundary: returns
    (loss, bottom_grads, top_grads) where the only values crossing between
    the parties are ``acts`` (client→server) and ``acts_grad``
    (server→client) — the reference's per-batch message
    (client.py:24-34 / server.py:40-60)."""
    # client side
    acts, bottom_vjp = jax.vjp(
        lambda p: bottom.apply({"params": p}, x, train=True)[0],
        bottom_vars["params"],
    )

    # server side: loss + grads wrt (top params, acts)
    def server_loss(tp, a):
        logits, _ = top.apply({"params": tp}, a, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    loss, (top_grads, acts_grad) = jax.value_and_grad(server_loss, argnums=(0, 1))(
        top_vars["params"], acts
    )
    # client backward with the returned activation grads
    (bottom_grads,) = bottom_vjp(acts_grad)
    return loss, bottom_grads, top_grads
