"""FedNova — normalized averaging (ref: fedml_api/standalone/fednova/,
vendored from JYWa/FedNova; fednova.py:10 `FedNova(Optimizer)` with the
`local_normalizing_vec` bookkeeping at :141-170, server aggregation
`FedNovaTrainer.aggregate(params, norm_grads, tau_effs)` at
fednova_trainer.py:97-125).

Clients run heterogeneous numbers of local steps τ_i (ragged shards ⇒ ragged
step counts); plain FedAvg then implicitly over-weights fast clients. FedNova
normalizes each client's cumulative update by its step-accumulation factor
a_i and rescales by the effective τ:

    d_i   = (w_g − w_i) / a_i
    τ_eff = Σ p_i a_i          (p_i = n_i / Σ n)
    w'    = w_g − τ_eff Σ p_i d_i

For vanilla SGD a_i = τ_i; for local momentum ρ, a_i = Σ_{k=1}^{τ_i}
(1−ρ^k)/(1−ρ) = (τ_i − ρ(1−ρ^{τ_i})/(1−ρ))/(1−ρ) — exactly what the
reference's optimizer accumulates step-by-step into `local_normalizing_vec`
(fednova.py:141-170); here it's the closed form of τ_i, which the local-train
scan reports as the "steps" metric (all-padding steps are gated no-ops and
excluded). Unlike the reference (whose fednova is standalone-only), the same
round function vmaps on one chip and shard_maps over a mesh."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu import _jax_compat

_jax_compat.install()  # jax.shard_map / jax.lax.pcast on older jaxlib

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.train.client import make_local_train


def _accum_factor(tau, momentum: float):
    """Closed form of the reference's local_normalizing_vec after tau steps."""
    if momentum:
        rho = momentum
        return (tau - rho * (1.0 - rho**tau) / (1.0 - rho)) / (1.0 - rho)
    return tau


def _validate_and_build(model, config, task, local_train_fn):
    """Shared guard + local-train construction for BOTH FedNova round
    factories (vmap and mesh), so the supported-optimizer surface can
    never diverge between them. The closed-form a_i models plain/momentum
    SGD only; the reference's mu-aware accumulation (fednova.py etamu
    branch) and adaptive client optimizers are not modeled — reject rather
    than silently mis-normalize."""
    if config.train.client_optimizer != "sgd":
        raise ValueError(
            "FedNova requires client_optimizer='sgd' "
            f"(got {config.train.client_optimizer!r})"
        )
    if config.train.prox_mu:
        raise ValueError("FedNova with prox_mu is not supported")
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    return local_train, config.train.momentum


def make_fednova_round(model, config, task="classification", local_train_fn=None, donate=True):
    local_train, momentum = _validate_and_build(model, config, task, local_train_fn)

    def round_fn(global_vars, x, y, mask, num_samples, client_rngs):
        client_vars, metrics = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0)
        )(global_vars, x, y, mask, client_rngs)
        p = num_samples / jnp.sum(num_samples)
        tau = metrics["steps"]  # [C] effective local steps
        a = _accum_factor(tau, momentum)
        # Dummy padded clients: tau = 0 ⇒ a = 0; their p is also 0 — guard
        # the division so 0/0 doesn't poison the sum.
        a_safe = jnp.where(a > 0, a, 1.0)
        tau_eff = jnp.sum(p * a)

        def nova_avg(stacked, g):
            stacked = stacked.astype(jnp.float32)
            # d_i = (w_g − w_i)/a_i ; w' = w_g − τ_eff Σ p_i d_i
            coeff = p * tau_eff / a_safe * (a > 0)
            return g - jnp.tensordot(coeff, g[None] - stacked, axes=1)

        # Only params get the nova update; other collections (BN stats) are
        # plain weighted averages as in FedAvg.
        new_params = jax.tree_util.tree_map(
            lambda s, g: nova_avg(s, g), client_vars["params"], global_vars["params"]
        )
        new_global = {
            k: (
                new_params
                if k == "params"
                else jax.tree_util.tree_map(
                    lambda s: jnp.tensordot(p, s.astype(jnp.float32), axes=1),
                    v,
                )
            )
            for k, v in client_vars.items()
        }
        agg_metrics = jax.tree_util.tree_map(jnp.sum, metrics)
        return new_global, agg_metrics

    # program dedup (fedml_tpu/compile/): one jitted FedNova round per
    # (model, train config, epochs, task) per process
    from fedml_tpu.compile import get_program_cache, model_fingerprint

    cache = get_program_cache()
    builder = lambda: jax.jit(round_fn, donate_argnums=(0,) if donate else ())
    if local_train_fn is not None:
        return cache.wrap_uncached("fednova_round", builder())
    return cache.get_or_build(
        "fednova_round",
        {
            "kind": "fednova_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "donate": donate,
        },
        builder,
    )


class FedNovaAPI(FedAvgAPI):
    _supports_fused = False  # per-round host-side work forbids chunk fusion
    """FedNova simulator — FedAvg round skeleton with normalized averaging."""

    def _build_round_fn(self, local_train_fn):
        return make_fednova_round(
            self.model,
            self.config,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )


def make_sharded_fednova_round(model, config, mesh, task="classification", local_train_fn=None, donate=True):
    """The FedNova round over a client-sharded mesh: p-normalization,
    τ_eff, and the normalized-update tensordot become partial sums + one
    psum each over ICI. Math identical to :func:`make_fednova_round`
    (the mesh-vs-vmap parity test covers it)."""
    from jax.sharding import PartitionSpec as P

    local_train, momentum = _validate_and_build(model, config, task, local_train_fn)
    axis = mesh.axis_names[0]

    def shard_body(global_vars, x, y, mask, num_samples, client_rngs):
        # keep the replicated (invariant) view for the aggregation: the
        # final w' = g − psum(...) must be invariant for out_spec P(); the
        # varying cast is only needed where params mix with sharded data
        g_inv = global_vars
        global_vars = jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"), global_vars
        )
        client_vars, metrics = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0)
        )(global_vars, x, y, mask, client_rngs)
        p = num_samples / jax.lax.psum(jnp.sum(num_samples), axis)
        tau = metrics["steps"]
        a = _accum_factor(tau, momentum)
        a_safe = jnp.where(a > 0, a, 1.0)
        tau_eff = jax.lax.psum(jnp.sum(p * a), axis)
        coeff = p * tau_eff / a_safe * (a > 0)

        def nova_avg(stacked, g):
            stacked = stacked.astype(jnp.float32)
            return g - jax.lax.psum(
                jnp.tensordot(coeff, g[None] - stacked, axes=1), axis
            )

        new_params = jax.tree_util.tree_map(
            nova_avg, client_vars["params"], g_inv["params"]
        )
        new_global = {
            k: (
                new_params
                if k == "params"
                else jax.tree_util.tree_map(
                    lambda s: jax.lax.psum(
                        jnp.tensordot(p, s.astype(jnp.float32), axes=1), axis
                    ),
                    v,
                )
            )
            for k, v in client_vars.items()
        }
        agg_metrics = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(jnp.sum(m), axis), metrics
        )
        return new_global, agg_metrics

    spec = P(axis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(),) + (spec,) * 5,
        out_specs=(P(), P()),
    )

    # program dedup (fedml_tpu/compile/): keyed like the sharded FedAvg
    # round — the mesh fingerprint is part of the program's identity
    from fedml_tpu.compile import (
        get_program_cache,
        mesh_fingerprint,
        model_fingerprint,
    )

    cache = get_program_cache()
    builder = lambda: jax.jit(sharded, donate_argnums=(0,) if donate else ())
    if local_train_fn is not None:
        return cache.wrap_uncached("sharded_fednova_round", builder())
    return cache.get_or_build(
        "sharded_fednova_round",
        {
            "kind": "sharded_fednova_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "mesh": mesh_fingerprint(mesh),
            "donate": donate,
        },
        builder,
    )


# The mesh-runtime driver (DistributedFedNovaAPI) lives in
# parallel/fedavg_sharded.py next to its FedAvg/FedOpt siblings.
