"""FedNova — normalized averaging (ref: fedml_api/standalone/fednova/,
vendored from JYWa/FedNova; fednova.py:10 `FedNova(Optimizer)` with the
`local_normalizing_vec` bookkeeping at :141-170, server aggregation
`FedNovaTrainer.aggregate(params, norm_grads, tau_effs)` at
fednova_trainer.py:97-125).

Clients run heterogeneous numbers of local steps τ_i (ragged shards ⇒ ragged
step counts); plain FedAvg then implicitly over-weights fast clients. FedNova
normalizes each client's cumulative update by its step-accumulation factor
a_i and rescales by the effective τ:

    d_i   = (w_g − w_i) / a_i
    τ_eff = Σ p_i a_i          (p_i = n_i / Σ n)
    w'    = w_g − τ_eff Σ p_i d_i

For vanilla SGD a_i = τ_i; for local momentum ρ, a_i = Σ_{k=1}^{τ_i}
(1−ρ^k)/(1−ρ) = (τ_i − ρ(1−ρ^{τ_i})/(1−ρ))/(1−ρ) — exactly what the
reference's optimizer accumulates step-by-step into `local_normalizing_vec`
(fednova.py:141-170); here it's the closed form of τ_i, which the local-train
scan reports as the "steps" metric (all-padding steps are gated no-ops and
excluded). Unlike the reference (whose fednova is standalone-only), the same
round function vmaps on one chip and shard_maps over a mesh."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.train.client import make_local_train


def _accum_factor(tau, momentum: float):
    """Closed form of the reference's local_normalizing_vec after tau steps."""
    if momentum:
        rho = momentum
        return (tau - rho * (1.0 - rho**tau) / (1.0 - rho)) / (1.0 - rho)
    return tau


def make_fednova_round(model, config, task="classification", local_train_fn=None, donate=True):
    # The closed-form a_i below models plain/momentum SGD only. The
    # reference's mu-aware accumulation (fednova.py etamu branch) and
    # adaptive client optimizers are not modeled — reject rather than
    # silently mis-normalize.
    if config.train.client_optimizer != "sgd":
        raise ValueError(
            "FedNova requires client_optimizer='sgd' "
            f"(got {config.train.client_optimizer!r})"
        )
    if config.train.prox_mu:
        raise ValueError("FedNova with prox_mu is not supported")
    local_train = local_train_fn or make_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    momentum = config.train.momentum

    def round_fn(global_vars, x, y, mask, num_samples, client_rngs):
        client_vars, metrics = jax.vmap(
            local_train, in_axes=(None, 0, 0, 0, 0)
        )(global_vars, x, y, mask, client_rngs)
        p = num_samples / jnp.sum(num_samples)
        tau = metrics["steps"]  # [C] effective local steps
        a = _accum_factor(tau, momentum)
        # Dummy padded clients: tau = 0 ⇒ a = 0; their p is also 0 — guard
        # the division so 0/0 doesn't poison the sum.
        a_safe = jnp.where(a > 0, a, 1.0)
        tau_eff = jnp.sum(p * a)

        def nova_avg(stacked, g):
            stacked = stacked.astype(jnp.float32)
            # d_i = (w_g − w_i)/a_i ; w' = w_g − τ_eff Σ p_i d_i
            coeff = p * tau_eff / a_safe * (a > 0)
            return g - jnp.tensordot(coeff, g[None] - stacked, axes=1)

        # Only params get the nova update; other collections (BN stats) are
        # plain weighted averages as in FedAvg.
        new_params = jax.tree_util.tree_map(
            lambda s, g: nova_avg(s, g), client_vars["params"], global_vars["params"]
        )
        new_global = {
            k: (
                new_params
                if k == "params"
                else jax.tree_util.tree_map(
                    lambda s: jnp.tensordot(p, s.astype(jnp.float32), axes=1),
                    v,
                )
            )
            for k, v in client_vars.items()
        }
        agg_metrics = jax.tree_util.tree_map(jnp.sum, metrics)
        return new_global, agg_metrics

    return jax.jit(round_fn, donate_argnums=(0,) if donate else ())


class FedNovaAPI(FedAvgAPI):
    _supports_fused = False  # per-round host-side work forbids chunk fusion
    """FedNova simulator — FedAvg round skeleton with normalized averaging."""

    def _build_round_fn(self, local_train_fn):
        return make_fednova_round(
            self.model,
            self.config,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )
