"""FedGKT — Group Knowledge Transfer (ref: fedml_api/distributed/fedgkt/
{GKTClientTrainer.py:48+, GKTServerTrainer.py:13-291, utils.py:75-92 KL_Loss,
message_def.py:6-24}).

Clients train a small CNN and upload per-batch (features, logits, labels) —
representations, NOT weights; the server trains the large network on those
features with CE + temperature-scaled KL against the client logits, then
returns its own logits per client so the next local round distills the
server's knowledge back (CE + KL vs server logits). Both KD directions use
the reference's KL: T²·KL(softmax_T(teacher) ‖ softmax_T(student)),
utils.py:75-92."""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.models.gkt_resnet import GKTClientResNet, GKTServerResNet


def kl_loss(student_logits, teacher_logits, temperature: float = 3.0):
    """T²·KL(teacher_T ‖ student_T) (ref KL_Loss.forward, utils.py:86-92)."""
    T = temperature
    log_p = jax.nn.log_softmax(student_logits / T, axis=-1)
    q = jax.nn.softmax(teacher_logits / T, axis=-1) + 1e-7
    return T * T * jnp.mean(jnp.sum(q * (jnp.log(q) - log_p), axis=-1))


class FedGKTAPI:
    """Single-host simulator of the GKT exchange (the reference runs it over
    MPI; the message contents here are exactly the per-client feature/logit/
    label arrays of message_def.py:6-24)."""

    def __init__(
        self,
        num_classes: int = 10,
        input_shape=(32, 32, 3),
        client_blocks: int = 1,
        server_layers=(2, 2),
        lr: float = 0.01,
        temperature: float = 3.0,
        seed: int = 0,
    ):
        self.T = temperature
        self.client_net = GKTClientResNet(num_classes=num_classes, blocks=client_blocks)
        self.server_net = GKTServerResNet(num_classes=num_classes, layers=server_layers)
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        dummy = jnp.zeros((1,) + tuple(input_shape))
        self.client_vars: Dict[int, dict] = {}
        self._client_init = lambda key: self.client_net.init(key, dummy, train=False)
        self._ckeys = k1
        feat = jnp.zeros((1, input_shape[0], input_shape[1], 16))
        self.server_vars = self.server_net.init(k2, feat, train=False)
        self.client_opt = optax.sgd(lr, momentum=0.9)
        self.server_opt = optax.sgd(lr, momentum=0.9)
        self.server_opt_state = self.server_opt.init(self.server_vars["params"])
        self._client_step = jax.jit(self._make_client_step())  # fedlint: disable=uncached-jit -- per-API-instance step over opaque self state; long-tail driver outside the warmup/dedup path
        self._server_step = jax.jit(self._make_server_step())  # fedlint: disable=uncached-jit -- per-API-instance step over opaque self state; long-tail driver outside the warmup/dedup path
        self._extract = jax.jit(  # fedlint: disable=uncached-jit -- per-API-instance inference closure over self.client_net; long-tail driver outside the warmup/dedup path
            lambda cv, x: self.client_net.apply(cv, x, train=False)
        )
        self._server_infer = jax.jit(  # fedlint: disable=uncached-jit -- per-API-instance inference closure over self.server_net; long-tail driver outside the warmup/dedup path
            lambda sv, f: self.server_net.apply(sv, f, train=False)
        )

    def _make_client_step(self):
        net, opt, T = self.client_net, self.client_opt, self.T

        def loss_fn(params, variables, x, y, server_logits, has_teacher):
            (feats, logits), new_vars = net.apply(
                {**variables, "params": params},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
            kd = kl_loss(logits, server_logits, T)
            # round 0 has no server logits yet (ref GKTClientTrainer trains
            # CE-only before the first server response)
            loss = ce + jnp.where(has_teacher, kd, 0.0)
            return loss, new_vars

        def step(variables, opt_state, x, y, server_logits, has_teacher):
            (loss, mutated), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                variables["params"], variables, x, y, server_logits, has_teacher
            )
            updates, opt_state = opt.update(grads, opt_state, variables["params"])
            params = optax.apply_updates(variables["params"], updates)
            return (
                {"params": params, "batch_stats": mutated["batch_stats"]},
                opt_state,
                loss,
            )

        return step

    def _make_server_step(self):
        net, opt, T = self.server_net, self.server_opt, self.T

        def loss_fn(params, variables, feats, y, client_logits):
            logits, new_vars = net.apply(
                {**variables, "params": params},
                feats,
                train=True,
                mutable=["batch_stats"],
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
            kd = kl_loss(logits, client_logits, T)
            return ce + kd, new_vars

        def step(variables, opt_state, feats, y, client_logits):
            (loss, mutated), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                variables["params"], variables, feats, y, client_logits
            )
            updates, opt_state = opt.update(grads, opt_state, variables["params"])
            params = optax.apply_updates(variables["params"], updates)
            return (
                {"params": params, "batch_stats": mutated["batch_stats"]},
                opt_state,
                loss,
            )

        return step

    def train_round(
        self,
        client_data: List[tuple],
        local_epochs: int = 1,
        server_epochs: int = 1,
        batch_size: int = 32,
        server_logits_cache: Optional[Dict[int, np.ndarray]] = None,
    ):
        """One GKT round. client_data: list of (x, y) per client. Returns the
        new per-client server-logits cache (the S2C message content)."""
        cache = server_logits_cache or {}
        uploads = []  # (features, client_logits, labels) per client — C2S msg
        for ci, (x, y) in enumerate(client_data):
            if ci not in self.client_vars:
                self.client_vars[ci] = self._client_init(
                    jax.random.fold_in(self._ckeys, ci)
                )
            variables = self.client_vars[ci]
            opt_state = self.client_opt.init(variables["params"])
            n = len(y)
            s_logits = cache.get(ci)
            for _ in range(local_epochs):
                for s in range(0, n - batch_size + 1, batch_size):
                    xb = jnp.asarray(x[s : s + batch_size])
                    yb = jnp.asarray(y[s : s + batch_size])
                    if s_logits is None:
                        teach = jnp.zeros((batch_size, self.client_net.num_classes))
                        has_t = jnp.asarray(False)
                    else:
                        teach = jnp.asarray(s_logits[s : s + batch_size])
                        has_t = jnp.asarray(True)
                    variables, opt_state, _ = self._client_step(
                        variables, opt_state, xb, yb, teach, has_t
                    )
            self.client_vars[ci] = variables
            feats, logits = self._extract(variables, jnp.asarray(x))
            uploads.append((np.asarray(feats), np.asarray(logits), np.asarray(y)))

        # server: train on all clients' features (ref train_and_distill
        # GKTServerTrainer.py:110-126, 233-291)
        for _ in range(server_epochs):
            for feats, logits, y in uploads:
                n = len(y)
                for s in range(0, n - batch_size + 1, batch_size):
                    self.server_vars, self.server_opt_state, _ = self._server_step(
                        self.server_vars,
                        self.server_opt_state,
                        jnp.asarray(feats[s : s + batch_size]),
                        jnp.asarray(y[s : s + batch_size]),
                        jnp.asarray(logits[s : s + batch_size]),
                    )

        # server logits back to each client (ref message_def.py:24)
        new_cache = {}
        for ci, (feats, _, _) in enumerate(uploads):
            new_cache[ci] = np.asarray(
                self._server_infer(self.server_vars, jnp.asarray(feats))
            )
        return new_cache

    def evaluate(self, x, y, client_id: int = 0, batch_size: int = 128):
        """End-to-end accuracy: client stem features → server net."""
        correct = 0
        variables = self.client_vars[client_id]
        for s in range(0, len(y), batch_size):
            xb = jnp.asarray(x[s : s + batch_size])
            feats, _ = self._extract(variables, xb)
            logits = self._server_infer(self.server_vars, feats)
            correct += int(
                jnp.sum(jnp.argmax(logits, -1) == jnp.asarray(y[s : s + batch_size]))
            )
        return correct / len(y)
