"""Hierarchical (cloud-edge-device) FedAvg — ref:
fedml_api/standalone/hierarchical_fl/{trainer.py:43-69, group.py:24-46}.

Two-level aggregation: clients belong to groups (edge servers); each global
round, every group runs ``group_comm_round`` FedAvg sub-rounds over its
sampled clients starting from the global model, then the cloud averages group
models weighted by group sample counts. With group_comm_round=1 this is
exactly flat FedAvg — the reference's CI oracle for hierarchical FL under any
group split (CI-script-fedavg.sh:52-58), carried over as a test here.

On TPU the group loop maps to ICI-level psum per group + a cross-group
average; here groups run through the same jitted round function with the
group's clients stacked on the client axis. (The reference's version is
broken in the fork — trainer.py:6 imports a module that no longer exists,
SURVEY §2c.)"""

from __future__ import annotations

from typing import List, Sequence

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI, weighted_average


def assign_groups(num_clients: int, group_num: int, seed: int = 0) -> List[np.ndarray]:
    """Random balanced client→group assignment (ref trainer.py's
    client_indexes-per-group sampling)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(num_clients)
    return [np.sort(g) for g in np.array_split(perm, group_num)]


def resolve_groups(groups, num_clients: int, group_num: int, seed: int) -> List[np.ndarray]:
    """Normalize an explicit group list or fall back to :func:`assign_groups`
    — the ONE definition both the host-loop and mesh hierarchical APIs use,
    so their group semantics can never diverge (their exact equality is a
    test contract, tests/test_hierarchical_sharded.py)."""
    if groups is not None:
        return [np.asarray(g) for g in groups]
    return assign_groups(num_clients, group_num, seed=seed)


class HierarchicalFedAvgAPI(FedAvgAPI):
    _supports_fused = False  # per-round host-side work forbids chunk fusion
    # train_round runs its own group loop and never consumes the
    # _round_placed stash — pipelining would leak prepared batches
    _supports_pipeline = False
    """Two-level FedAvg simulator. Reuses the inherited jitted round function
    for every group sub-round; only the orchestration differs."""

    # The global model is fed to several group sub-rounds; donation would
    # invalidate it after the first group.
    _donate = False
    # Group sub-rounds have ragged cohort sizes and their metric trees are
    # tree_map-summed across groups — per-client loss vectors would make
    # the leaves ragged; power_of_choice keeps the cohort-mean signal here.
    _client_loss_vectors = False

    def __init__(self, config, data, model, groups: Sequence[np.ndarray] = None, **kw):
        super().__init__(config, data, model, **kw)
        self.groups = resolve_groups(
            groups, data.num_clients, config.fed.group_num, config.seed
        )
        # program dedup (fedml_tpu/compile/): weighted_average is a pure
        # module-level fn — one jitted cross-group average per process
        # instead of one per API instance (fedlint uncached-jit catch)
        from fedml_tpu.compile import get_program_cache

        self._avg = get_program_cache().get_or_build(
            "hierarchical_cloud_avg",
            {"kind": "hierarchical_cloud_avg", "fn": weighted_average},
            lambda: jax.jit(weighted_average),
        )

    def _group_round(self, round_idx: int, gi: int, members, sampled_set):
        """One group's ``group_comm_round`` sub-rounds from the current
        global model: ``(w_group | None, weight, metrics | None)``. THE
        group-level math, shared by the in-process loop below and the
        cross-process gRPC bridge (parallel/hierarchical_bridge.py) so an
        edge-server process computes exactly what the simulator computes
        for its group — their equality is a test contract
        (tests/test_multihost_bridge.py)."""
        cfg = self.config
        g_clients = [int(c) for c in members if int(c) in sampled_set]
        if not g_clients:
            return None, 0, None
        w_group = self.global_vars
        metrics_acc = None
        for sub in range(cfg.fed.group_comm_round):
            batch = self._stack(
                g_clients,
                cfg.seed * 1_000_003 + round_idx * 131 + gi * 17 + sub,
            )
            rng = jax.random.fold_in(
                self.rng, (round_idx + 1) * 1009 + gi * 31 + sub
            )
            w_group, m = self.round_fn(
                w_group, *self._place_batch(batch, rng)
            )
            metrics_acc = (
                m
                if metrics_acc is None
                else jax.tree_util.tree_map(
                    lambda a, b: a + b, metrics_acc, m
                )
            )
        weight = sum(len(self.data.client_y[c]) for c in g_clients)
        return w_group, weight, metrics_acc

    def _cloud_average(self, group_vars, group_weights):
        """Cloud step: weighted average of group models; an all-empty
        round (every group missed the cohort — possible with explicit
        partial ``groups``) keeps the current global model. THE cloud
        math, shared with the cross-process bridge
        (parallel/hierarchical_bridge.py) like :meth:`_group_round` —
        bridge == simulator is an equality contract."""
        if not group_vars:
            return self.global_vars
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jax.numpy.stack(
                [jax.numpy.asarray(l) for l in leaves]
            ),
            *group_vars,
        )
        return self._avg(
            stacked,
            jax.numpy.asarray(group_weights, dtype=jax.numpy.float32),
        )

    def train_round(self, round_idx: int):
        cfg = self.config
        # scheduler-backed cohort (FedConfig.selection + fault plan) — the
        # same memoized draw the base API's _round_plan/_log_round see
        sampled = self._sample_clients(round_idx)
        sampled_set = set(int(i) for i in sampled)
        group_vars, group_weights, metrics_acc = [], [], None
        for gi, members in enumerate(self.groups):
            w_group, weight, m = self._group_round(
                round_idx, gi, members, sampled_set
            )
            if w_group is None:
                continue
            group_vars.append(w_group)
            group_weights.append(weight)
            metrics_acc = (
                m
                if metrics_acc is None
                else jax.tree_util.tree_map(lambda a, b: a + b, metrics_acc, m)
            )
        self.global_vars = self._cloud_average(group_vars, group_weights)
        return sampled, metrics_acc
