"""FedOpt family — FedAvg + a server-side optimizer (ref:
fedml_api/distributed/fedopt/ + fedml_api/standalone/fedopt/).

The reference aggregates like FedAvg, then writes the pseudo-gradient
``grad := w_old − w_avg`` into ``param.grad`` and calls a reflected
``torch.optim`` class (FedOptAggregator.py:95-117, OptRepo optrepo.py:7-50).
Here the same move is an optax transform applied to the pseudo-gradient — the
OptRepo reflection becomes a name→optax-constructor registry. Server state
(momentum/adaptivity) persists across rounds as an explicit optax state
pytree — the reference rebuilds the optimizer each round to preserve state
(FedOptAggregator.py:95-102); here it is just carried functionally.

Only the ``params`` collection goes through the server optimizer; non-param
collections (BatchNorm stats) are plain weighted averages, matching the
reference (state-dict averaging covers BN stats, FedAVGAggregator.py:66-71)."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.config import RunConfig, ServerConfig
from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.models import ModelDef
from fedml_tpu.algorithms.fedavg import FedAvgAPI, weighted_average


def make_server_optimizer(sc: ServerConfig) -> optax.GradientTransformation:
    """Name → optax constructor (ref OptRepo name→torch.optim class,
    optrepo.py:7-50; FedAdam/FedYogi per 'Adaptive Federated Optimization',
    the paper the reference's benchmark rows cite)."""
    name = sc.server_optimizer.lower()
    if name == "sgd":
        return optax.sgd(sc.server_lr)
    if name in ("momentum", "sgdm"):
        return optax.sgd(sc.server_lr, momentum=sc.server_momentum or 0.9)
    if name == "adam":
        return optax.adam(sc.server_lr, b1=0.9, b2=0.99, eps=sc.tau)
    if name == "yogi":
        return optax.yogi(sc.server_lr, b1=0.9, b2=0.99, eps=sc.tau)
    if name == "adagrad":
        return optax.adagrad(sc.server_lr, eps=sc.tau)
    raise ValueError(f"unknown server_optimizer {sc.server_optimizer!r}")


def make_server_step(opt: optax.GradientTransformation) -> Callable:
    """``(old_vars, avg_vars, opt_state) -> (new_vars, new_state)`` — the
    FedOpt server move, shared by the vmap/mesh APIs and the transport
    server manager so the pseudo-gradient math lives once."""

    def server_step(old_vars, avg_vars, opt_state):
        # pseudo-grad = w_old − w_avg (FedOptAggregator.py:109-117)
        pseudo_grad = jax.tree_util.tree_map(
            lambda o, a: o - a, old_vars["params"], avg_vars["params"]
        )
        updates, new_state = opt.update(
            pseudo_grad, opt_state, old_vars["params"]
        )
        new_params = optax.apply_updates(old_vars["params"], updates)
        new_vars = dict(avg_vars)  # non-param collections: plain average
        new_vars["params"] = new_params
        return new_vars, new_state

    return server_step


def make_cached_server_step(config: RunConfig):
    """THE jitted FedOpt server-step program, deduped through the
    process-wide ProgramCache — the one registration point shared by the
    vmap/mesh APIs and the transport server manager (both previously
    spelled the digest dict out by hand; a drift between the two copies
    would have split the program they are required to share). The step's
    CODE is fully determined by the server config — the param tree enters
    as a jit shape class, not a program determinant — so one jit object
    serves every model and every API instance in the process. Returns
    ``(cached_program, optimizer)``."""
    from fedml_tpu.compile import get_program_cache

    opt = make_server_optimizer(config.server)
    # step_builder marker MUST be the module-level make_server_step —
    # every call site keys the same program with it, so all sides dedup
    # onto ONE executable
    prog = get_program_cache().get_or_build(
        "server_opt",
        {
            "kind": "fedopt_server_step",
            "server": config.server,
            "step_builder": make_server_step,
        },
        lambda: jax.jit(make_server_step(opt)),
    )
    return prog, opt


class FedOptAPI(FedAvgAPI):
    _supports_fused = False  # per-round host-side work forbids chunk fusion
    """FedOpt simulator: FedAvgAPI with a server-optimizer step appended to
    each round (ref standalone/fedopt/fedopt_api.py:34-109)."""

    _donate = False  # train_round reads old_vars after the round call

    def __init__(self, config: RunConfig, data: FederatedDataset, model: ModelDef, **kw):
        super().__init__(config, data, model, **kw)
        self._server_step, self.server_opt = make_cached_server_step(config)
        self.server_opt_state = self.server_opt.init(self.global_vars["params"])

    def train_round(self, round_idx: int):
        old_vars = self.global_vars
        sampled, metrics = super().train_round(round_idx)
        # super() set global_vars to the plain weighted average; redo the
        # params through the server optimizer.
        self.global_vars, self.server_opt_state = self._server_step(
            old_vars, self.global_vars, self.server_opt_state
        )
        return sampled, metrics
