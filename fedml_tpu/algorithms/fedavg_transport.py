"""Distributed FedAvg over the Message/Observer transport — true cross-silo
federation (ref: fedml_api/distributed/fedavg/{FedAvgServerManager.py,
FedAvgClientManager.py, FedAVGAggregator.py, FedAVGTrainer.py,
message_define.py}).

This is the reference's flagship 6-file pattern collapsed into one module.
The server runs the round FSM (all-received barrier → weighted aggregate →
resample → broadcast, ref FedAvgServerManager.py:34-72); clients run the
jit-compiled local-train scan and upload weights. Unlike the intra-pod
shard_map path (fedml_tpu.parallel), participants here are independent
processes/hosts talking through any BaseCommManager (loopback in tests,
gRPC across machines). Weights travel as binary buffers (core/message.py),
not JSON lists."""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import weighted_average
from fedml_tpu.config import RunConfig
from fedml_tpu.telemetry import ClientHealthRegistry, get_comm_meter, get_tracer
from fedml_tpu.core.comm import BaseCommManager
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.core.managers import ClientManager, ServerManager
from fedml_tpu.core.message import Message, MessageType as MT
from fedml_tpu.data.base import FederatedDataset, stack_clients
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import make_local_train
from fedml_tpu.train.evaluate import evaluate


class FedAvgAggregator:
    """Server-side accumulate + weighted average (ref FedAVGAggregator.py:
    37-78: add_local_trained_result, check_whether_all_receive, aggregate)."""

    def __init__(self, worker_num: int):
        self.worker_num = worker_num
        self.model_dict: Dict[int, dict] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self._flags = [False] * worker_num

    def add_local_trained_result(self, index: int, params: dict, num_samples: float) -> None:
        self.model_dict[index] = params
        self.sample_num_dict[index] = float(num_samples)
        self._flags[index] = True

    def check_whether_all_receive(self) -> bool:
        return all(self._flags)

    def received_count(self) -> int:
        return len(self.model_dict)

    def aggregate(self) -> dict:
        idxs = sorted(self.model_dict)
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves]),
            *[self.model_dict[i] for i in idxs],
        )
        weights = jnp.asarray(
            [self.sample_num_dict[i] for i in idxs], jnp.float32
        )
        avg = weighted_average(stacked, weights)
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self._flags = [False] * self.worker_num
        return jax.device_get(avg)


def _model_wire_cost(tree) -> tuple:
    """(as-shipped, fp32-equivalent) bytes of one model broadcast — the
    downlink mirror of the uplink's arithmetic accounting (no cast copy
    materialized; 4 B x element count for the raw denominator)."""
    leaves = jax.tree_util.tree_leaves(tree)
    shipped = sum(int(np.asarray(a).nbytes) for a in leaves)
    raw = 4 * sum(int(np.size(a)) for a in leaves)
    return shipped, raw


def local_train_key_fields(model: ModelDef, config: RunConfig, task: str):
    """THE digest key of the shared transport local-train program — one
    definition serving both the factory below and the admission
    controller's warm-program probe (fedml_tpu/serve/admission.py
    recomputes a candidate tenant's digest to price its compile cost
    from the content-addressed store; a drifted copy of these fields
    would silently price the wrong program)."""
    from fedml_tpu.compile import model_fingerprint

    return {
        "kind": "local_train",
        "model": model_fingerprint(model),
        "train": config.train,
        "epochs": config.fed.epochs,
        "task": task,
    }


def shared_local_train(model: ModelDef, config: RunConfig, task: str):
    """THE jitted client local-train program for a transport federation,
    deduped through the process-wide ProgramCache (fedml_tpu/compile/):
    every LocalTrainer, every runner, and every test module building the
    same (model, train config, epochs, task) shares one compile."""
    from fedml_tpu.compile import get_program_cache

    return get_program_cache().get_or_build(  # fedlint: disable=baked-constant -- key fields are the dict literal in local_train_key_fields directly above, shared verbatim with the admission controller's pricing probe (serve/admission.py) so the two can never drift; the helper reads only digested leaves (model fingerprint, config.train, epochs, task)
        "local_train",
        local_train_key_fields(model, config, task),
        lambda: jax.jit(
            make_local_train(model, config.train, config.fed.epochs, task=task)
        ),
    )


class LocalTrainer:
    """Client-side trainer wrapper (ref FedAVGTrainer.py:7-54: update_dataset
    by client_index, train(round) -> (weights, local_sample_number))."""

    def __init__(
        self,
        config: RunConfig,
        data: FederatedDataset,
        model: ModelDef,
        task: str,
        local_train_fn=None,
        straggle_s: float = 0.0,
    ):
        self.config = config
        self.data = data
        self.model = model
        # Share one jitted fn across in-process trainers — K distinct
        # closures would defeat the jit cache and compile K times. The
        # program cache (fedml_tpu/compile/) extends that sharing across
        # trainer instances and processes' test modules.
        self.local_train = local_train_fn or shared_local_train(
            model, config, task
        )
        self.client_index = 0
        # Simulated compute heterogeneity: sleep this long after every
        # local training (a slow phone among fast ones). Drives the
        # straggler/async benchmarks; 0 = off.
        self.straggle_s = float(straggle_s)
        # last local mean train loss — attached to the upload message so
        # the server can feed power_of_choice selection (scheduler/)
        self.last_loss: Optional[float] = None

    def update_dataset(self, client_index: int):
        self.client_index = int(client_index)

    def train(self, round_idx: int, variables: dict):
        with get_tracer().span(
            "local_train", client=int(self.client_index), round=int(round_idx)
        ):
            return self._train(round_idx, variables)

    def _train(self, round_idx: int, variables: dict):
        cfg = self.config
        batch = stack_clients(
            self.data,
            [self.client_index],
            cfg.data.batch_size,
            # client_index folded in: otherwise every client in a round
            # would draw the identical shuffle permutation.
            seed=cfg.seed * 1_000_003 + round_idx * 8191 + self.client_index,
            pad_bucket=cfg.data.pad_bucket,
        )
        rng = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed), (round_idx + 1) * 7919 + self.client_index
        )
        new_vars, m = self.local_train(
            variables,
            jnp.asarray(batch.x[0]),
            jnp.asarray(batch.y[0]),
            jnp.asarray(batch.mask[0]),
            rng,
        )
        n = len(self.data.client_y[self.client_index])
        out = jax.device_get(new_vars)
        try:
            count = float(np.asarray(m["count"]))
            # a zero-sample shard has no loss signal: report None (upload
            # omits ARG_TRAIN_LOSS, client stays "cold" for
            # power_of_choice) exactly like the sim's c > 0 skip in
            # _report_client_losses — a fabricated 0.0 would rank the
            # client last in sim/transport-divergent ways
            self.last_loss = (
                float(np.asarray(m["loss_sum"])) / count if count > 0 else None
            )
        except (KeyError, TypeError):  # custom local_train_fn metric shape
            self.last_loss = None
        if self.straggle_s:
            time.sleep(self.straggle_s)
        return out, n


class FedAvgServerManager(ServerManager):
    """Round FSM (ref FedAvgServerManager.py:20-72)."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        model: ModelDef,
        data: Optional[FederatedDataset] = None,
        task: str = "classification",
        worker_num: Optional[int] = None,
        log_fn=None,
        server_opt: bool = False,
        faults=None,
    ):
        super().__init__(comm, rank=0, config=config)
        self.config = config
        self.model = model
        self.data = data
        self.task = task
        self.log_fn = log_fn or (lambda m: None)
        self.worker_num = worker_num or config.fed.client_num_per_round
        self.aggregator = FedAvgAggregator(self.worker_num)
        # secure-agg mode: masked field vectors keyed by party (rank-1).
        # Clients size the mask registry from client_num_per_round (the
        # only value they have), so a worker_num override would give the
        # two wire ends non-cancelling masks — reject it up front.
        if config.comm.secure_agg and self.worker_num != config.fed.client_num_per_round:
            raise ValueError(
                f"secure_agg requires worker_num ({self.worker_num}) == "
                f"client_num_per_round ({config.fed.client_num_per_round}): "
                "clients derive the mask registry from the latter"
            )
        # downlink quantization (CommConfig.downlink_compression): int8
        # only — the top-k family zeroes model coordinates outright, which
        # is a delta codec's trick, not a model broadcast's
        dl = config.comm.downlink_compression
        if dl not in ("none", "int8"):
            raise ValueError(
                f"downlink_compression supports 'none' or 'int8'; got {dl!r}"
            )
        if config.comm.secure_agg and dl != "none":
            # masked uploads are field vectors over the EXACT broadcast
            # reference; requantizing the reference each round would put
            # the two wire ends in different fields
            raise ValueError(
                "secure_agg and downlink_compression are mutually exclusive"
            )
        self._masked_uploads: Dict[int, np.ndarray] = {}
        self._masked_ns: Dict[int, float] = {}
        # client-held-key exchange state (secagg/secure_aggregation.py
        # ClientParty/ServerAggregator): the server holds PUBLIC keys only
        self._round_pks: Dict[int, int] = {}  # party -> pk, this round
        self._recovery_pending = False
        self._recovery_vecs: Dict[int, np.ndarray] = {}  # survivor party -> vec
        self._recovery_requested_for = None  # dropped-set of the last request
        self._registry_sent = False
        # FedOpt over the transport (the reference's fedopt IS a
        # distributed MPI algorithm, FedOptAggregator.py:95-117): apply the
        # server optimizer to the pseudo-gradient after each aggregate.
        self._server_step = None
        self._server_opt_state = None
        if server_opt:
            # one registration point (fedopt.make_cached_server_step) so
            # this manager and the vmap/mesh APIs can never drift apart in
            # how they key the shared server-step program
            from fedml_tpu.algorithms.fedopt import make_cached_server_step

            self._server_step, self._server_optimizer = (
                make_cached_server_step(config)
            )
        self.round_idx = 0
        # Straggler deadline state (FedConfig.deadline_s/min_clients). The
        # timer thread races the comm receive loop; _round_lock serializes
        # round completion.
        self._round_lock = threading.Lock()
        self._deadline_timer: Optional[threading.Timer] = None
        self._deadline_passed = False
        # Stalled-round abandonment: a round can sit below quorum FOREVER
        # when every sampled client crashed/dropped — reachable on purpose
        # under a participation-fault plan, and ONLY then (without one,
        # every sampled client eventually uploads, and the legacy
        # semantics — close on the quorum-th upload whenever it arrives,
        # however late past the deadline — must stay untouched: a cold
        # first-round jit compile can outlast several deadline_s). With
        # the valve armed, each below-quorum deadline re-arms the timer;
        # after 3 consecutive firings with NO new upload the round is
        # abandoned with whatever arrived (possibly nothing — the model
        # then carries over unchanged), loudly, instead of hanging.
        #
        # The plan is read off the ONE FaultInjector the runner plumbs in
        # (run_federation) — re-parsing FedConfig.fault_plan here would
        # re-read the plan file and open a drift window where the valve
        # and the injected faults disagree (a plan file swapped between
        # the two parses). Direct constructions without an injector
        # (grpc rank 0: the clients inject in their own processes) parse
        # once as a fallback.
        self.faults = faults
        if faults is not None:
            _plan = faults.plan
        else:
            from fedml_tpu.scheduler import FaultPlan

            _plan = FaultPlan.from_config(config)
        self._stall_valve = (
            _plan is not None and _plan.has_participation_faults()
        )
        self._stall_last_count = -1
        self._stall_strikes = 0
        # graceful per-tenant drain (fedml_tpu/serve/): when set, the
        # round that is currently open completes normally and the
        # federation then FINISHes instead of broadcasting the next round;
        # _federation_done marks the FINISH having happened, so a late
        # request_stop cannot fabricate an extra zero-upload round
        self._stop_requested = False
        self._federation_done = False
        self.abandoned_rounds = 0
        self.dropped_uploads = 0  # late round-tagged uploads discarded
        self._dead_workers: set = set()  # peers whose broadcasts failed
        self.deadline_error: Optional[BaseException] = None
        self.global_vars = jax.device_get(
            model.init(jax.random.fold_in(jax.random.PRNGKey(config.seed), 0))
        )
        self.history: List[dict] = []
        from fedml_tpu.train.evaluate import make_eval_fn

        self._eval_fn = make_eval_fn(model, task) if data is not None else None
        # Telemetry: the client health registry feeds on the span stream
        # (in-process federations record true local_train wall time) and on
        # this server's broadcast→upload round-trips (the only timing a
        # cross-process gRPC server can see); (client, round) dedupe keeps
        # the two sources from double counting. Round-lifecycle spans begin
        # at broadcast and end at round completion (possibly on another
        # thread), so they use explicit handles, not context managers.
        self._tracer = get_tracer()
        self.health = ClientHealthRegistry.from_config(config).attach(self._tracer)
        self._round_span = None
        self._assigned: Dict[int, tuple] = {}  # worker -> (client_idx, t_bcast)
        # Scheduler: the SAME policy driver the vmap simulator uses
        # (scheduler/policies.py), so both runtimes select byte-identical
        # cohorts from one config — a test contract. The server passes its
        # worker_num as the final k (run_federation already provisions one
        # worker per overprovisioned slot); straggler_aware feeds on this
        # health registry, power_of_choice on the uploads' train losses.
        from fedml_tpu.scheduler import ClientScheduler

        self.scheduler = ClientScheduler.from_config(
            config,
            num_clients=config.fed.client_num_in_total,
            data=data,
            log_fn=self.log_fn,
            health=self.health,
            tracer=self._tracer,
        )
        # Wire telemetry (telemetry/wire.py): client beacons piggybacked
        # on uploads feed the CLI's flight recorder (when one listens on
        # this tracer) and the process fleet aggregator. Dedupe is the
        # worker's last consumed round — a flaky duplicate delivery
        # restates the SAME beacon and must not double-count.
        from fedml_tpu.telemetry.flight import attached_recorder

        self._flight = attached_recorder(self._tracer)
        self._beacon_seen: Dict[int, int] = {}

    def finish(self):
        # stop feeding the health registry from the global span stream —
        # sequential federations in one process (tests, sweeps) must not
        # accumulate listeners; queries on self.health keep working
        self.health.detach()
        super().finish()

    def request_stop(self, drain: bool = True) -> None:
        """Graceful per-tenant stop (fedml_tpu/serve/): ``drain=True``
        lets the currently-open round complete (its cohort's work is not
        thrown away) and FINISHes the fleet instead of broadcasting the
        next round; ``drain=False`` additionally closes the open round
        immediately with whatever uploads have arrived (the zero-upload
        carry-over path applies — the model survives unchanged). Safe
        from any thread EXCEPT this server's own message handlers (it
        takes the round lock); handlers set ``_stop_requested`` directly
        instead."""
        self._stop_requested = True
        if drain:
            return
        with self._round_lock:
            # a federation that already FINISHed (naturally or via an
            # earlier stop) has no open round: completing again would log
            # a spurious zero-upload row and re-broadcast FINISH
            if not self._federation_done:
                self._complete_round()

    def _broadcast(self, msg: Message) -> bool:
        """Send a server->client message, tolerating a dead peer: a client
        process that crashed mid-federation must not take the server FSM
        down with it — the deadline/quorum machinery (FedConfig.deadline_s/
        min_clients) absorbs the missing upload instead (VERDICT r2 Next
        #7, chaos tolerance; the reference's aggregator barrier would hang
        forever, FedAVGAggregator.py:43-49).

        A worker whose send failed is remembered as dead and skipped (each
        skipped round logs once) — without this, every round would re-pay
        the transport's failure timeout inside the round lock. Any message
        later RECEIVED from that worker clears the flag (elastic re-entry,
        commit c8cb247's documented stance)."""
        worker = msg.get_receiver_id()
        if worker in self._dead_workers:
            logging.info("skipping broadcast to dead worker %d", worker)
            return False
        try:
            self.send_message(msg)
            return True
        except Exception as e:  # noqa: BLE001 — transport errors vary by backend
            self._dead_workers.add(worker)
            logging.warning(
                "broadcast to worker %d failed (%s) — continuing on quorum",
                worker,
                e,
            )
            return False

    def send_init_msg(self):
        """Sample the opening round's clients, broadcast the model (ref
        send_init_msg :20-28). The opening round is ``self.round_idx`` —
        0 unless a session resume poured a checkpoint in first
        (fedml_tpu/serve/session.py), in which case the scheduler's
        restored memo re-selects the in-flight cohort byte-identically."""
        self._t0 = time.monotonic()
        # _complete_round (the steady-state sender) runs entirely under
        # _round_lock; the opening round must too, or its writes to
        # _round_span / global_vars / the deadline scaffolding race the
        # first client uploads arriving on the comm thread
        with self._round_lock:
            r = self.round_idx
            sampled = self.scheduler.select(r, k=self.worker_num)
            self._round_span = self._tracer.start_span("round", round=r)
            with self._tracer.span("broadcast", round=r):
                self._broadcast_round(MT.S2C_INIT_CONFIG, r, sampled)
            self._arm_deadline()

    def _broadcast_round(self, msg_type: str, round_idx: int, sampled):
        """Ship the round's model to the sampled cohort, encoding the
        payload ONCE per round instead of once per worker.

        The model tree is host-materialised contiguous up front, so every
        worker's Message references the SAME buffers and the envelope's
        per-param ``ascontiguousarray`` is a no-op — K workers cost one
        model copy, not K (the wire cost is computed once too). With
        ``CommConfig.downlink_compression`` the tree is int8-quantized
        once and the DEQUANTIZED tree becomes the round's reference model
        (``self.global_vars``): clients train from exactly it, compressed
        uplink deltas decode against exactly it, and the next pseudo-
        gradient is measured from exactly it — both wire ends agree
        byte-for-byte on the round's starting point."""
        host = jax.tree_util.tree_map(
            lambda a: np.ascontiguousarray(np.asarray(a)), self.global_vars
        )
        dl = self.config.comm.downlink_compression
        payload = None
        if dl != "none":
            from fedml_tpu.core import compression as CZ

            payload = CZ.encode_delta(host, dl, self.config.comm.topk_frac)
            self.global_vars = CZ.decode_delta(payload, host, dl)
            shipped = CZ.payload_bytes(payload)
            raw = 4 * sum(
                int(np.size(a)) for a in jax.tree_util.tree_leaves(host)
            )
        else:
            self.global_vars = host
            shipped, raw = _model_wire_cost(host)
        for worker, client_idx in enumerate(sampled, start=1):
            msg = Message(msg_type, 0, worker)
            if payload is not None:
                msg.add_params(MT.ARG_MODEL_QUANT, payload)
                msg.add_params(MT.ARG_MODEL_CODEC, dl)
            else:
                msg.add_params(MT.ARG_MODEL_PARAMS, host)
            msg.add_params(MT.ARG_CLIENT_INDEX, int(client_idx))
            msg.add_params(MT.ARG_ROUND_IDX, round_idx)
            self._assigned[worker] = (int(client_idx), time.monotonic())
            if self._broadcast(msg):
                get_comm_meter().on_downlink(shipped, raw)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MT.C2S_SEND_MODEL, self._on_model_from_client
        )
        self.register_message_receive_handler(MT.C2S_PUBKEY, self._on_pubkey)
        self.register_message_receive_handler(
            MT.C2S_RECOVERY, self._on_recovery
        )

    # -- secure-agg key exchange (round structure of Bonawitz et al.:
    #    advertise keys -> masked input -> unmask; the server relays public
    #    keys and never holds a party secret) --
    def _send_registry(self):
        """Broadcast the pk registry of the parties heard so far. Caller
        holds _round_lock. Parties that never advertised a key are simply
        not in the round's mask algebra (Bonawitz proceeds with surviving
        parties), so a client dead before its pubkey cannot deadlock the
        key phase."""
        self._registry_sent = True
        parties = sorted(self._round_pks)
        for p in parties:
            out = Message(MT.S2C_PUBKEYS, 0, p + 1)
            out.add_params(MT.ARG_ROUND_IDX, self.round_idx)
            out.add_params(
                MT.ARG_PUBKEY_REGISTRY,
                {
                    "parties": parties,
                    "pks": [self._round_pks[q] for q in parties],
                },
            )
            self._broadcast(out)

    def _on_pubkey(self, msg: Message):
        self._dead_workers.discard(msg.get_sender_id())
        with self._round_lock:
            if msg.get(MT.ARG_ROUND_IDX, -1) != self.round_idx:
                return
            if self._registry_sent:
                # the round's registry is sealed: a late advertiser was
                # never part of the mask algebra, and recording it would
                # later misclassify it as a dropped party (whose "masks"
                # no survivor ever applied or could recover)
                self.dropped_uploads += 1
                return
            party = msg.get_sender_id() - 1
            self._round_pks[party] = int(msg.get(MT.ARG_PUBKEY))
            if len(self._round_pks) == self.worker_num or (
                self._deadline_passed
                and len(self._round_pks) >= self._quorum()
            ):
                self._send_registry()

    def _on_recovery(self, msg: Message):
        self._dead_workers.discard(msg.get_sender_id())
        with self._round_lock:
            if msg.get(MT.ARG_ROUND_IDX, -1) != self.round_idx:
                return
            answered = set(map(int, msg.get(MT.ARG_DROPPED) or ()))
            if self._recovery_requested_for is None or answered != set(
                self._recovery_requested_for
            ):
                # stale response for an earlier, smaller dropped set —
                # accepting it would bake uncancelled pair masks of the
                # newly-dropped survivors into the aggregate
                return
            party = msg.get_sender_id() - 1
            self._recovery_vecs[party] = np.asarray(
                msg.get(MT.ARG_RECOVERY_VEC), np.int64
            )
            if self._recovery_pending and set(self._recovery_vecs) >= set(
                self._masked_uploads
            ):
                self._complete_round()

    def _on_recovery_deadline(self, armed_round: int):
        """A survivor that never answered its S2C_RECOVER (it died after
        uploading) becomes a dropped party itself: discard its upload and
        restart the recovery exchange with the remaining survivors. The
        survivor set strictly shrinks each iteration, so this terminates."""
        try:
            with self._round_lock:
                if armed_round != self.round_idx or not self._recovery_pending:
                    return
                silent = set(self._masked_uploads) - set(self._recovery_vecs)
                for p in silent:
                    self._masked_uploads.pop(p, None)
                    self._masked_ns.pop(p, None)
                self._recovery_requested_for = None  # force a re-request
                self._complete_round()
        except BaseException as e:  # noqa: BLE001 — see _on_deadline
            self.deadline_error = e
            self.finish()

    # -- straggler deadline (FedConfig.deadline_s) --
    def _arm_deadline(self):
        dl = self.config.fed.deadline_s
        if not dl:
            return
        self._deadline_passed = False
        self._stall_last_count = -1
        self._stall_strikes = 0
        # round generation captured at arm time: cancel() cannot stop a
        # callback already blocked on _round_lock, so a stale timer must
        # recognise that its round has already completed
        self._deadline_timer = threading.Timer(
            dl, self._on_deadline, args=(self.round_idx,)
        )
        self._deadline_timer.daemon = True
        self._deadline_timer.start()

    def _disarm_deadline(self):
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
            self._deadline_timer = None
        self._deadline_passed = False

    def _quorum(self) -> int:
        return max(1, min(self.config.fed.min_clients, self.worker_num))

    def _received_count(self) -> int:
        if self.config.comm.secure_agg:
            return len(self._masked_uploads)
        return self.aggregator.received_count()

    def _on_deadline(self, armed_round: int):
        try:
            with self._round_lock:
                if armed_round != self.round_idx:
                    return  # stale timer: its round already completed
                self._deadline_passed = True
                if (
                    self.config.comm.secure_agg
                    and not self._registry_sent
                    and len(self._round_pks) >= self._quorum()
                ):
                    # key phase stalled on a client that died before its
                    # pubkey: proceed with the parties heard so far (their
                    # uploads can still reach quorum before this same
                    # deadline flag completes the round)
                    self._send_registry()
                    return
                if self._received_count() >= self._quorum():
                    self._complete_round()
                    return
                if not self._stall_valve:
                    # legacy semantics (no participation faults): the
                    # quorum-th upload completes the round on arrival —
                    # _on_model_from_client checks _deadline_passed
                    return
                # Below quorum under a droppy fault plan: keep the flag
                # set (the quorum-th upload still completes the round on
                # arrival) and re-arm so stall detection keeps ticking.
                # Three consecutive deadlines with NO new upload = a round
                # that can never close (the whole cohort crashed/dropped):
                # abandon it with whatever arrived rather than hang —
                # quorum is a liveness floor, not worth a wedged
                # federation (logged loudly).
                n = self._received_count()
                if n == self._stall_last_count:
                    self._stall_strikes += 1
                else:
                    self._stall_last_count = n
                    self._stall_strikes = 0
                if self._stall_strikes >= 2:  # 3rd barren deadline
                    logging.warning(
                        "round %d stalled below quorum (%d/%d uploads "
                        "after 3 deadlines) — abandoning with the "
                        "partial set",
                        self.round_idx, n, self._quorum(),
                    )
                    self.abandoned_rounds += 1
                    self._complete_round()
                    return
                t = threading.Timer(
                    self.config.fed.deadline_s,
                    self._on_deadline,
                    args=(armed_round,),
                )
                t.daemon = True
                t.start()
                self._deadline_timer = t
        except BaseException as e:  # noqa: BLE001
            # the timer thread would otherwise swallow this and leave the
            # server parked on its inbox forever; surface it through finish()
            self.deadline_error = e
            self.finish()

    def _on_model_from_client(self, msg: Message):
        self._dead_workers.discard(msg.get_sender_id())
        with self._round_lock:
            # missing tag (pre-tag client version) fails SAFE: -1 never
            # matches, so an unattributable upload is dropped, not averaged
            # into whatever round happens to be open
            upload_round = msg.get(MT.ARG_ROUND_IDX, -1)
            if upload_round == -1:
                logging.warning(
                    "dropping untagged model upload from sender %s "
                    "(client protocol predates round tags?)",
                    msg.get_sender_id(),
                )
            if upload_round != self.round_idx:
                # straggler reporting for an already-closed round
                self.dropped_uploads += 1
                return
            # health: broadcast→upload round-trip for this worker's client
            # (no-op when the span stream already recorded the round)
            assigned = self._assigned.get(msg.get_sender_id())
            if assigned is not None:
                rtt_s = time.monotonic() - assigned[1]
                # telemetry beacon first: its MEASURED train time is truer
                # than the rtt fallback below, which the (client, round)
                # dedupe then absorbs
                beacon = msg.get(MT.ARG_TELEMETRY)
                if beacon is not None:
                    self._consume_beacon(
                        msg.get_sender_id(), assigned[0], upload_round,
                        beacon, rtt_s,
                    )
                self.health.observe_train(assigned[0], upload_round, rtt_s)
                # power_of_choice bias signal: the client's local mean
                # train loss rides the upload (ARG_TRAIN_LOSS)
                loss = msg.get(MT.ARG_TRAIN_LOSS)
                if loss is not None:
                    self.scheduler.report_loss(assigned[0], float(loss))
            worker = msg.get_sender_id() - 1
            if self.config.comm.secure_agg:
                # store the masked vector; unmasking happens once at round
                # completion (dropout masks recovered there if a quorum
                # round closed without some parties)
                masked = msg.get(MT.ARG_MASKED_UPDATE)
                if masked is None:
                    raise ValueError(
                        f"secure-agg server received an unmasked upload "
                        f"from sender {msg.get_sender_id()} — was that "
                        "client launched without --secure_agg?"
                    )
                if self._recovery_pending:
                    # a "dropped" party's upload racing the recovery
                    # exchange: its masks are being unwound — including it
                    # now would corrupt the sum
                    self.dropped_uploads += 1
                    return
                self._masked_uploads[worker] = masked
                self._masked_ns[worker] = float(msg.get(MT.ARG_NUM_SAMPLES))
                if len(self._masked_uploads) == self.worker_num or (
                    self._deadline_passed
                    and len(self._masked_uploads) >= self._quorum()
                ):
                    self._complete_round()
                return
            params = msg.get(MT.ARG_MODEL_PARAMS)
            if params is None:
                # compressed uplink: reconstruct against this round's
                # broadcast model (the round tag above guarantees the
                # upload belongs to the currently open round). The codec
                # comes from the MESSAGE's protocol tag, so a client whose
                # --compression differs from the server's still decodes
                # correctly instead of wedging the FSM.
                payload = msg.get(MT.ARG_MODEL_DELTA)
                method = msg.get(MT.ARG_COMPRESSION)
                if payload is None or method is None:
                    raise ValueError(
                        f"model upload from sender {msg.get_sender_id()} "
                        "carries neither model_params nor a tagged "
                        "compressed delta"
                    )
                from fedml_tpu.core import compression as CZ

                params = CZ.decode_update(payload, self.global_vars, method)
            self.aggregator.add_local_trained_result(
                worker, params, msg.get(MT.ARG_NUM_SAMPLES)
            )
            if self.aggregator.check_whether_all_receive() or (
                self._deadline_passed
                and self.aggregator.received_count() >= self._quorum()
            ):
                self._complete_round()

    def _consume_beacon(
        self, worker: int, client_idx: int, round_idx: int,
        beacon, rtt_s: float,
    ) -> None:
        """Fold one client telemetry beacon (telemetry/wire.py) into
        health, flight, and fleet. Consumed at most once per (worker,
        round): a flaky/retried upload restates the SAME beacon, and the
        bytes were metered client-side at attach, so duplicates are
        attribution no-ops here. Caller holds _round_lock."""
        if not isinstance(beacon, dict):
            return
        if self._beacon_seen.get(worker) == round_idx:
            return
        self._beacon_seen[worker] = int(round_idx)
        try:
            train_s = max(0.0, float(beacon.get("train_s", 0.0)))
            encode_s = max(0.0, float(beacon.get("encode_s", 0.0)))
        except (TypeError, ValueError):
            return
        tier = beacon.get("tier")
        self.health.observe_train(client_idx, round_idx, train_s, tier=tier)
        from fedml_tpu.telemetry import get_fleet

        get_fleet().observe_beacon(tier, beacon, rtt_s=rtt_s)
        if self._flight is not None:
            # the measured train-vs-wire-vs-queue split: whatever the
            # round trip spent beyond training+encoding sat on the wire
            # or in a queue
            self._flight.observe_beacon(
                round_idx, train_s, encode_s,
                wire_s=max(0.0, rtt_s - train_s - encode_s),
            )

    def _complete_round(self):
        """Aggregate whatever has arrived, eval, resample, broadcast.
        Caller holds _round_lock."""
        self._disarm_deadline()
        zero_uploads = False
        if self.config.comm.secure_agg:
            from fedml_tpu.secagg.secure_aggregation import (
                ServerAggregator,
                tree_dim,
            )

            dropped = sorted(set(self._round_pks) - set(self._masked_uploads))
            if dropped and self._recovery_requested_for != set(dropped):
                # Bonawitz unmask round: registry parties that never
                # uploaded left uncancelled pair masks inside the
                # survivors' uploads — ask each survivor for its recovery
                # contribution; the round completes in _on_recovery. A
                # survivor whose request cannot even be SENT is dead too:
                # drop its upload and re-enter with the larger dropped set
                # (strictly shrinking survivors ⇒ terminates). A recovery
                # timer catches survivors that died without closing their
                # socket (_on_recovery_deadline).
                self._recovery_pending = True
                self._recovery_requested_for = set(dropped)
                self._recovery_vecs = {}
                unreachable = []
                for p in sorted(self._masked_uploads):
                    out = Message(MT.S2C_RECOVER, 0, p + 1)
                    out.add_params(MT.ARG_ROUND_IDX, self.round_idx)
                    out.add_params(MT.ARG_DROPPED, list(map(int, dropped)))
                    if not self._broadcast(out):
                        unreachable.append(p)
                if unreachable:
                    for p in unreachable:
                        self._masked_uploads.pop(p, None)
                        self._masked_ns.pop(p, None)
                    self._recovery_requested_for = None
                    self._complete_round()
                    return
                if self._masked_uploads:
                    t = threading.Timer(
                        max(self.config.fed.deadline_s, 5.0),
                        self._on_recovery_deadline,
                        args=(self.round_idx,),
                    )
                    t.daemon = True
                    t.start()
                    return
            if dropped and set(self._recovery_vecs) < set(self._masked_uploads):
                return  # waiting on recovery vecs (timer bounds the wait)
            srv = ServerAggregator(tree_dim(self.global_vars))
            if self._masked_uploads:
                with self._tracer.span(
                    "aggregate",
                    round=self.round_idx,
                    n_uploads=len(self._masked_uploads),
                    secure_agg=True,
                ):
                    total = srv.masked_sum(self._masked_uploads)
                    if dropped:
                        total = srv.remove_dropout_masks(
                            total, self._recovery_vecs
                        )
                    ns = {p: self._masked_ns[p] for p in self._masked_uploads}
                    avg = srv.decode_average(total, ns, self.global_vars)
            else:
                # every party died mid-protocol: keep the current model
                logging.warning(
                    "secure-agg round %d lost every upload — model unchanged",
                    self.round_idx,
                )
                avg = self.global_vars
                zero_uploads = True
            self._masked_uploads, self._masked_ns = {}, {}
            self._round_pks, self._recovery_vecs = {}, {}
            self._recovery_pending = False
            self._recovery_requested_for = None
            self._registry_sent = False
        elif self.aggregator.received_count() == 0:
            # abandoned round with zero uploads (entire cohort
            # crashed/dropped): the model carries over unchanged
            logging.warning(
                "round %d closed with no uploads — model unchanged",
                self.round_idx,
            )
            avg = self.global_vars
            zero_uploads = True
        else:
            with self._tracer.span(
                "aggregate",
                round=self.round_idx,
                n_uploads=self.aggregator.received_count(),
            ):
                avg = self.aggregator.aggregate()
        if self._server_step is not None and not zero_uploads:
            # a zero-upload round must not step the server optimizer: the
            # pseudo-gradient is exactly zero, but momentum/Adam moments
            # from earlier rounds would still move the model and decay the
            # state on a round in which no client trained
            if self._server_opt_state is None:
                self._server_opt_state = self._server_optimizer.init(
                    self.global_vars["params"]
                )
            self.global_vars, self._server_opt_state = jax.device_get(
                self._server_step(self.global_vars, avg, self._server_opt_state)
            )
        else:
            self.global_vars = avg
        row = {
            "round": self.round_idx,
            # wall clock since w0 went out — the async bench's
            # accuracy-at-matched-wall-clock comparison keys on this
            "t_s": round(time.monotonic() - getattr(self, "_t0", time.monotonic()), 3),
        }
        eval_now = self.data is not None and (
            self.round_idx % self.config.fed.frequency_of_the_test == 0
            or self.round_idx == self.config.fed.comm_round - 1
        )
        if eval_now:
            with self._tracer.span("eval", round=self.round_idx):
                loss, acc = evaluate(
                    self.model,
                    self.global_vars,
                    self.data.test_x,
                    self.data.test_y,
                    task=self.task,
                    eval_fn=self._eval_fn,
                )
            row["Test/Loss"], row["Test/Acc"] = loss, acc
        self.history.append(row)
        self.log_fn(row)
        if self._round_span is not None:
            self._round_span.end()
            self._round_span = None
        self.round_idx += 1
        if self.round_idx >= self.config.fed.comm_round or self._stop_requested:
            self._federation_done = True
            for worker in range(1, self.worker_num + 1):
                self._broadcast(Message(MT.FINISH, 0, worker))
            self.finish()
            return
        sampled = self.scheduler.select(self.round_idx, k=self.worker_num)
        self._round_span = self._tracer.start_span("round", round=self.round_idx)
        with self._tracer.span("broadcast", round=self.round_idx):
            self._broadcast_round(MT.S2C_SYNC_MODEL, self.round_idx, sampled)
        self._arm_deadline()


class FedAvgClientManager(ClientManager):
    """ref FedAvgClientManager.py:17-65."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        rank: int,
        trainer: LocalTrainer,
        ef=None,
        faults=None,
    ):
        super().__init__(comm, rank, config=config)
        self.config = config
        self.trainer = trainer
        # fault injection (scheduler/faults.FaultInjector, usually shared
        # across a federation's client actors): consulted per assignment —
        # dropout skips training+upload, crash makes the CLIENT silent for
        # every round from crash_at_round on (faults follow the client,
        # not this worker slot — the sampler re-assigns clients to workers
        # each round), slowdown sleeps, flaky double-sends the upload
        self._faults = faults
        # TopKErrorFeedback store. The residual must follow the CLIENT, and
        # sampling re-assigns clients to ranks every round — so in-process
        # runtimes SHARE one store across all client actors (run_federation
        # passes it in); a per-process store (grpc) is only sound under
        # rank-stable assignment, which the CLI enforces (full
        # participation).
        if ef is None:
            from fedml_tpu.core.compression import ErrorFeedback

            ef = ErrorFeedback.maybe_from_config(config.comm)
        self._ef = ef
        # secure-agg per-round state: the ClientParty holding THIS client's
        # secret key (never serialized, never sent)
        self._secagg_party = None
        self._secagg_round = -1
        self._secagg_pending = None
        # quantized-downlink decode template (shapes/treedef only; leaf
        # VALUES are never read) — built lazily on the first quantized sync
        self._downlink_template = None

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MT.S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(MT.S2C_SYNC_MODEL, self._on_sync)
        self.register_message_receive_handler(MT.S2C_PUBKEYS, self._on_pubkeys)
        self.register_message_receive_handler(MT.S2C_RECOVER, self._on_recover)
        self.register_message_receive_handler(MT.FINISH, lambda m: self.finish())

    # -- secure-agg client phases (client-held keys): train + advertise a
    #    FRESH locally-generated DH public key, upload the masked update
    #    once the server relays the round's registry, answer a recovery
    #    request if some registry party dropped before uploading --
    def _on_pubkeys(self, msg: Message):
        if self._secagg_party is None or msg.get(MT.ARG_ROUND_IDX) != self._secagg_round:
            return
        reg = msg.get(MT.ARG_PUBKEY_REGISTRY)
        pks = {
            int(p): int(pk) for p, pk in zip(reg["parties"], reg["pks"])
        }
        self._secagg_party.set_registry(pks)
        weights, w_round, n = self._secagg_pending
        out = Message(MT.C2S_SEND_MODEL, self.rank, 0)
        out.add_params(
            MT.ARG_MASKED_UPDATE,
            self._secagg_party.masked_update(weights, w_round, n),
        )
        out.add_params(MT.ARG_NUM_SAMPLES, n)
        out.add_params(MT.ARG_ROUND_IDX, self._secagg_round)
        self.send_message(out)

    def _on_recover(self, msg: Message):
        if self._secagg_party is None or msg.get(MT.ARG_ROUND_IDX) != self._secagg_round:
            return
        dropped = msg.get(MT.ARG_DROPPED)
        vec = self._secagg_party.recovery_mask(dropped)
        out = Message(MT.C2S_RECOVERY, self.rank, 0)
        out.add_params(MT.ARG_ROUND_IDX, self._secagg_round)
        out.add_params(MT.ARG_DROPPED, list(map(int, dropped)))
        out.add_params(MT.ARG_RECOVERY_VEC, vec)
        self.send_message(out)

    def _on_sync(self, msg: Message):
        self.trainer.update_dataset(msg.get(MT.ARG_CLIENT_INDEX))
        round_idx = msg.get(MT.ARG_ROUND_IDX)
        w_round = msg.get(MT.ARG_MODEL_PARAMS)
        if w_round is None:
            # quantized downlink: rebuild the broadcast model from the
            # codec-tagged payload. The decode template only supplies leaf
            # shapes and the treedef, so a fresh model.init works — the
            # decoded tree is byte-identical to the dequantized reference
            # the server kept as this round's global model.
            from fedml_tpu.core import compression as CZ

            payload = msg.get(MT.ARG_MODEL_QUANT)
            codec = msg.get(MT.ARG_MODEL_CODEC)
            if payload is None or codec is None:
                raise ValueError(
                    f"model sync for round {round_idx} carries neither "
                    "model_params nor a codec-tagged quantized payload"
                )
            if self._downlink_template is None:
                self._downlink_template = jax.device_get(
                    self.trainer.model.init(jax.random.PRNGKey(0))
                )
            w_round = CZ.decode_delta(payload, self._downlink_template, codec)
        fd = None
        if self._faults is not None:
            cid = int(self.trainer.client_index)
            fd = self._faults.decide(cid, int(round_idx))
            if fd.crashed:
                # the CLIENT is gone from crash_at_round on: no training,
                # no upload whenever it is sampled — the server's
                # deadline/quorum absorbs each missing upload; this worker
                # slot stays alive for the healthy clients later rounds
                # assign it (the injector records one crash per client)
                self._faults.record(cid, int(round_idx), "crash")
                return
            if fd.drop:
                # dropout: skip the round entirely (never uploads) — the
                # quorum path aggregates the partial cohort
                self._faults.record(cid, int(round_idx), "dropout")
                return
        t_train = time.perf_counter()
        weights, n = self.trainer.train(round_idx, w_round)
        if fd is not None and fd.slowdown_s:
            self._faults.record(
                int(self.trainer.client_index), int(round_idx), "slowdown",
                detail=fd.slowdown_s,
            )
            time.sleep(fd.slowdown_s)
        # beacon train time: compute INCLUDING any injected slowdown (a
        # slow device trains slowly — that is what the tier digests bin)
        train_s = time.perf_counter() - t_train
        comp = self.config.comm.compression
        if self.config.comm.secure_agg:
            # advertise a fresh per-round keypair; the masked upload waits
            # for the registry (_on_pubkeys). The secret key lives only in
            # this process's ClientParty.
            from fedml_tpu.secagg.secure_aggregation import (
                ClientParty,
                tree_dim,
            )

            self._secagg_party = ClientParty(self.rank - 1, tree_dim(weights))
            self._secagg_round = round_idx
            self._secagg_pending = (weights, w_round, n)
            adv = Message(MT.C2S_PUBKEY, self.rank, 0)
            adv.add_params(MT.ARG_ROUND_IDX, round_idx)
            adv.add_params(MT.ARG_PUBKEY, self._secagg_party.pk)
            self.send_message(adv)
            return
        from fedml_tpu.core import compression as CZ
        from fedml_tpu.telemetry import get_comm_meter

        out = Message(MT.C2S_SEND_MODEL, self.rank, 0)
        # fp32-equivalent cost of this update — the denominator of the
        # uplink byte-cut ratio (comm/uplink_* in summary.json); metered
        # for uncompressed uploads too so a baseline run carries the
        # same keys a quantized run is compared against. Counted
        # arithmetically (4 B × element count) — never by materializing
        # a cast copy of the tree on the hot upload path.
        raw_bytes = 4 * sum(
            int(np.size(a)) for a in jax.tree_util.tree_leaves(weights)
        )
        encode_s = 0.0
        if comp != "none":
            # uplink compression (core/compression.py): send the encoded
            # round delta; the server reconstructs against the same w_round
            t_enc = time.perf_counter()
            if self._ef is not None:
                payload = self._ef.encode(
                    self.trainer.client_index, weights, w_round
                )
            else:
                payload = CZ.encode_update(
                    weights, w_round, comp, self.config.comm.topk_frac
                )
            encode_s = time.perf_counter() - t_enc
            get_comm_meter().on_uplink(CZ.payload_bytes(payload), raw_bytes)
            out.add_params(MT.ARG_MODEL_DELTA, payload)
            out.add_params(MT.ARG_COMPRESSION, comp)
        else:
            # as-shipped payload = the leaves' actual buffer bytes (equal
            # to raw_bytes for fp32 weights, smaller for e.g. bf16)
            shipped = sum(
                int(a.nbytes) for a in jax.tree_util.tree_leaves(weights)
            )
            get_comm_meter().on_uplink(shipped, raw_bytes)
            out.add_params(MT.ARG_MODEL_PARAMS, weights)
        out.add_params(MT.ARG_NUM_SAMPLES, n)
        # round tag: lets the server discard a straggler's upload for an
        # already-closed round (FedConfig.deadline_s)
        out.add_params(MT.ARG_ROUND_IDX, round_idx)
        if self.trainer.last_loss is not None:
            out.add_params(MT.ARG_TRAIN_LOSS, float(self.trainer.last_loss))
        if getattr(self.config.comm, "beacons", True):
            # telemetry beacon (telemetry/wire.py): a bounded summary of
            # this round's local measurements, piggybacked on the upload.
            # Attached (and metered) ONCE — the flaky duplicate below
            # restates the same dict, and the server dedupes consumption
            # per (worker, round). Rides the envelope only: aggregation
            # never reads it, so numerics are identical with beacons off.
            from fedml_tpu.telemetry.wire import beacon_nbytes, build_beacon

            snap = get_comm_meter().snapshot()
            tier = None
            if self._faults is not None:
                plan = getattr(self._faults, "plan", None)
                if plan is not None:
                    tier = plan.tier_of(self.trainer.client_index)
            beacon = build_beacon(
                train_s=train_s,
                encode_s=encode_s,
                retries=sum(snap.get("send_retries", {}).values()),
                codec=comp,
                tier=tier,
            )
            out.add_params(MT.ARG_TELEMETRY, beacon)
            get_comm_meter().on_beacon(beacon_nbytes(beacon))
        self.send_message(out)
        if fd is not None and fd.flaky:
            # flaky upload = at-least-once double delivery; the sync
            # server's per-worker slot overwrite absorbs the duplicate
            self._faults.record(
                int(self.trainer.client_index), int(round_idx), "flaky"
            )
            try:
                self.send_message(out)
            except Exception:  # noqa: BLE001 — best-effort duplicate: the
                pass  # real upload above already landed


def run_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    comm_factory,
    task: str = "classification",
    log_fn=None,
    trainer_factory=None,
    server_opt: bool = False,
    warmup: bool = False,
):
    """One-process federation over any transport: 1 server + K client actors
    in threads, each on ``comm_factory(rank)`` (a BaseCommManager) — the
    transport-path analog of the reference's mpirun smoke runs
    (CI-script-framework.sh:16-23), but with a real exit-code/join
    discipline, and pluggable across loopback/gRPC/MQTT exactly like the
    reference's ``--backend`` switch (client_manager.py:20-33). Returns the
    server manager (global_vars, history).

    One worker is spawned per scheduler slot — ``ceil(client_num_per_round
    * overprovision_factor)`` of them — and a FedConfig.fault_plan, if
    set, is parsed ONCE into a single FaultInjector shared by every
    client actor AND the server's stall valve (no repeat file reads, no
    plan-swapped-mid-startup drift); its counters land in summary.json
    and the server's health registry.

    ``warmup=True`` AOT-compiles the shared local-train program for every
    shape class the partition can produce BEFORE any worker thread starts
    — the warmup barrier that lets ``deadline_s`` rounds begin with
    compilation already paid instead of racing a cold compile, in every
    round (not just round 0 — partition_shape_classes in data/base.py is
    the enumeration contract).

    This is now a thin blocking wrapper over
    :class:`fedml_tpu.serve.FedSession` — the long-lived multi-tenant
    service runs N of these sessions concurrently in one process; this
    entry point keeps the classic one-shot semantics (and, having no
    TelemetryScope of its own, the process-global telemetry) intact."""
    from fedml_tpu.serve.session import FedSession

    return FedSession(
        config,
        data,
        model,
        algorithm="fedavg",
        comm_factory=comm_factory,
        task=task,
        log_fn=log_fn,
        trainer_factory=trainer_factory,
        server_opt=server_opt,
        warmup=warmup,
    ).run()


def run_loopback_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    server_opt: bool = False,
    warmup: bool = False,
):
    """Federation over the in-process loopback hub (see run_federation)."""
    hub = LoopbackHub()
    return run_federation(
        config,
        data,
        model,
        lambda rank: LoopbackCommManager(hub, rank),
        task=task,
        log_fn=log_fn,
        server_opt=server_opt,
        warmup=warmup,
    )


def run_shm_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    sock_dir: Optional[str] = None,
    server_opt: bool = False,
    warmup: bool = False,
    namespace: str = "",
):
    """Federation over the shared-memory local transport (TRPC-equivalent,
    ref trpc_comm_manager.py:25-114): bulk tensors ride POSIX shared memory,
    only tiny control records cross the per-rank UNIX sockets.

    ``namespace`` prefixes the socket names — REQUIRED to be unique per
    federation when two concurrent runs share an explicit ``sock_dir``
    (the serve path's sessions generate their own; see ShmCommManager)."""
    import tempfile

    from fedml_tpu.core.shm_comm import ShmCommManager

    with tempfile.TemporaryDirectory(prefix="fedml_shm_") as d:
        return run_federation(
            config,
            data,
            model,
            lambda rank: ShmCommManager(
                rank, sock_dir or d, namespace=namespace
            ),
            task=task,
            log_fn=log_fn,
            server_opt=server_opt,
            warmup=warmup,
        )


def run_mqtt_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    host: str = None,
    port: int = 1883,
    server_opt: bool = False,
    warmup: bool = False,
):
    """Federation over MQTT pub/sub (ref mqtt_comm_manager.py:14-123):
    embedded in-process broker by default, real broker when host given."""
    from fedml_tpu.core.mqtt_comm import EmbeddedBroker, MqttCommManager

    if host is None:
        broker = EmbeddedBroker()
        factory = lambda rank: MqttCommManager(rank, broker=broker)
    else:
        factory = lambda rank: MqttCommManager(rank, host=host, port=port)
    return run_federation(
        config, data, model, factory, task=task, log_fn=log_fn,
        server_opt=server_opt, warmup=warmup,
    )
