"""Distributed FedAvg over the Message/Observer transport — true cross-silo
federation (ref: fedml_api/distributed/fedavg/{FedAvgServerManager.py,
FedAvgClientManager.py, FedAVGAggregator.py, FedAVGTrainer.py,
message_define.py}).

This is the reference's flagship 6-file pattern collapsed into one module.
The server runs the round FSM (all-received barrier → weighted aggregate →
resample → broadcast, ref FedAvgServerManager.py:34-72); clients run the
jit-compiled local-train scan and upload weights. Unlike the intra-pod
shard_map path (fedml_tpu.parallel), participants here are independent
processes/hosts talking through any BaseCommManager (loopback in tests,
gRPC across machines). Weights travel as binary buffers (core/message.py),
not JSON lists."""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import client_sampling, weighted_average
from fedml_tpu.config import RunConfig
from fedml_tpu.core.comm import BaseCommManager
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.core.managers import ClientManager, ServerManager
from fedml_tpu.core.message import Message, MessageType as MT
from fedml_tpu.data.base import FederatedDataset, stack_clients
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import make_local_train
from fedml_tpu.train.evaluate import evaluate


class FedAvgAggregator:
    """Server-side accumulate + weighted average (ref FedAVGAggregator.py:
    37-78: add_local_trained_result, check_whether_all_receive, aggregate)."""

    def __init__(self, worker_num: int):
        self.worker_num = worker_num
        self.model_dict: Dict[int, dict] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self._flags = [False] * worker_num

    def add_local_trained_result(self, index: int, params: dict, num_samples: float) -> None:
        self.model_dict[index] = params
        self.sample_num_dict[index] = float(num_samples)
        self._flags[index] = True

    def check_whether_all_receive(self) -> bool:
        return all(self._flags)

    def received_count(self) -> int:
        return len(self.model_dict)

    def aggregate(self) -> dict:
        idxs = sorted(self.model_dict)
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves]),
            *[self.model_dict[i] for i in idxs],
        )
        weights = jnp.asarray(
            [self.sample_num_dict[i] for i in idxs], jnp.float32
        )
        avg = weighted_average(stacked, weights)
        self.model_dict.clear()
        self.sample_num_dict.clear()
        self._flags = [False] * self.worker_num
        return jax.device_get(avg)


class LocalTrainer:
    """Client-side trainer wrapper (ref FedAVGTrainer.py:7-54: update_dataset
    by client_index, train(round) -> (weights, local_sample_number))."""

    def __init__(
        self,
        config: RunConfig,
        data: FederatedDataset,
        model: ModelDef,
        task: str,
        local_train_fn=None,
    ):
        self.config = config
        self.data = data
        self.model = model
        # Share one jitted fn across in-process trainers — K distinct
        # closures would defeat the jit cache and compile K times.
        self.local_train = local_train_fn or jax.jit(
            make_local_train(model, config.train, config.fed.epochs, task=task)
        )
        self.client_index = 0

    def update_dataset(self, client_index: int):
        self.client_index = int(client_index)

    def train(self, round_idx: int, variables: dict):
        cfg = self.config
        batch = stack_clients(
            self.data,
            [self.client_index],
            cfg.data.batch_size,
            # client_index folded in: otherwise every client in a round
            # would draw the identical shuffle permutation.
            seed=cfg.seed * 1_000_003 + round_idx * 8191 + self.client_index,
            pad_bucket=cfg.data.pad_bucket,
        )
        rng = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed), (round_idx + 1) * 7919 + self.client_index
        )
        new_vars, _ = self.local_train(
            variables,
            jnp.asarray(batch.x[0]),
            jnp.asarray(batch.y[0]),
            jnp.asarray(batch.mask[0]),
            rng,
        )
        n = len(self.data.client_y[self.client_index])
        return jax.device_get(new_vars), n


class FedAvgServerManager(ServerManager):
    """Round FSM (ref FedAvgServerManager.py:20-72)."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        model: ModelDef,
        data: Optional[FederatedDataset] = None,
        task: str = "classification",
        worker_num: Optional[int] = None,
        log_fn=None,
        server_opt: bool = False,
    ):
        super().__init__(comm, rank=0)
        self.config = config
        self.model = model
        self.data = data
        self.task = task
        self.log_fn = log_fn or (lambda m: None)
        self.worker_num = worker_num or config.fed.client_num_per_round
        self.aggregator = FedAvgAggregator(self.worker_num)
        # secure-agg mode: masked field vectors keyed by party (rank-1).
        # Clients size the mask registry from client_num_per_round (the
        # only value they have), so a worker_num override would give the
        # two wire ends non-cancelling masks — reject it up front.
        if config.comm.secure_agg and self.worker_num != config.fed.client_num_per_round:
            raise ValueError(
                f"secure_agg requires worker_num ({self.worker_num}) == "
                f"client_num_per_round ({config.fed.client_num_per_round}): "
                "clients derive the mask registry from the latter"
            )
        self._masked_uploads: Dict[int, np.ndarray] = {}
        self._masked_ns: Dict[int, float] = {}
        # FedOpt over the transport (the reference's fedopt IS a
        # distributed MPI algorithm, FedOptAggregator.py:95-117): apply the
        # server optimizer to the pseudo-gradient after each aggregate.
        self._server_step = None
        self._server_opt_state = None
        if server_opt:
            from fedml_tpu.algorithms.fedopt import (
                make_server_optimizer,
                make_server_step,
            )

            self._server_optimizer = make_server_optimizer(config.server)
            self._server_step = jax.jit(make_server_step(self._server_optimizer))
        self.round_idx = 0
        # Straggler deadline state (FedConfig.deadline_s/min_clients). The
        # timer thread races the comm receive loop; _round_lock serializes
        # round completion.
        self._round_lock = threading.Lock()
        self._deadline_timer: Optional[threading.Timer] = None
        self._deadline_passed = False
        self.dropped_uploads = 0  # late round-tagged uploads discarded
        self.deadline_error: Optional[BaseException] = None
        self.global_vars = jax.device_get(
            model.init(jax.random.fold_in(jax.random.PRNGKey(config.seed), 0))
        )
        self.history: List[dict] = []
        from fedml_tpu.train.evaluate import make_eval_fn

        self._eval_fn = make_eval_fn(model, task) if data is not None else None

    def send_init_msg(self):
        """Sample round-0 clients, broadcast w0 (ref send_init_msg :20-28)."""
        sampled = client_sampling(
            0, self.config.fed.client_num_in_total, self.worker_num
        )
        for worker, client_idx in enumerate(sampled, start=1):
            msg = Message(MT.S2C_INIT_CONFIG, 0, worker)
            msg.add_params(MT.ARG_MODEL_PARAMS, self.global_vars)
            msg.add_params(MT.ARG_CLIENT_INDEX, int(client_idx))
            msg.add_params(MT.ARG_ROUND_IDX, 0)
            self.send_message(msg)
        self._arm_deadline()

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MT.C2S_SEND_MODEL, self._on_model_from_client
        )

    # -- straggler deadline (FedConfig.deadline_s) --
    def _arm_deadline(self):
        dl = self.config.fed.deadline_s
        if not dl:
            return
        self._deadline_passed = False
        # round generation captured at arm time: cancel() cannot stop a
        # callback already blocked on _round_lock, so a stale timer must
        # recognise that its round has already completed
        self._deadline_timer = threading.Timer(
            dl, self._on_deadline, args=(self.round_idx,)
        )
        self._deadline_timer.daemon = True
        self._deadline_timer.start()

    def _disarm_deadline(self):
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
            self._deadline_timer = None
        self._deadline_passed = False

    def _quorum(self) -> int:
        return max(1, min(self.config.fed.min_clients, self.worker_num))

    def _received_count(self) -> int:
        if self.config.comm.secure_agg:
            return len(self._masked_uploads)
        return self.aggregator.received_count()

    def _on_deadline(self, armed_round: int):
        try:
            with self._round_lock:
                if armed_round != self.round_idx:
                    return  # stale timer: its round already completed
                self._deadline_passed = True
                if self._received_count() >= self._quorum():
                    self._complete_round()
        except BaseException as e:  # noqa: BLE001
            # the timer thread would otherwise swallow this and leave the
            # server parked on its inbox forever; surface it through finish()
            self.deadline_error = e
            self.finish()
            # else: below quorum — complete as soon as the quorum-th
            # upload arrives (_on_model_from_client checks the flag)

    def _on_model_from_client(self, msg: Message):
        with self._round_lock:
            # missing tag (pre-tag client version) fails SAFE: -1 never
            # matches, so an unattributable upload is dropped, not averaged
            # into whatever round happens to be open
            upload_round = msg.get(MT.ARG_ROUND_IDX, -1)
            if upload_round == -1:
                logging.warning(
                    "dropping untagged model upload from sender %s "
                    "(client protocol predates round tags?)",
                    msg.get_sender_id(),
                )
            if upload_round != self.round_idx:
                # straggler reporting for an already-closed round
                self.dropped_uploads += 1
                return
            worker = msg.get_sender_id() - 1
            if self.config.comm.secure_agg:
                # store the masked vector; unmasking happens once at round
                # completion (dropout masks recovered there if a quorum
                # round closed without some parties)
                masked = msg.get(MT.ARG_MASKED_UPDATE)
                if masked is None:
                    raise ValueError(
                        f"secure-agg server received an unmasked upload "
                        f"from sender {msg.get_sender_id()} — was that "
                        "client launched without --secure_agg?"
                    )
                self._masked_uploads[worker] = masked
                self._masked_ns[worker] = float(msg.get(MT.ARG_NUM_SAMPLES))
                if len(self._masked_uploads) == self.worker_num or (
                    self._deadline_passed
                    and len(self._masked_uploads) >= self._quorum()
                ):
                    self._complete_round()
                return
            params = msg.get(MT.ARG_MODEL_PARAMS)
            if params is None:
                # compressed uplink: reconstruct against this round's
                # broadcast model (the round tag above guarantees the
                # upload belongs to the currently open round). The codec
                # comes from the MESSAGE's protocol tag, so a client whose
                # --compression differs from the server's still decodes
                # correctly instead of wedging the FSM.
                payload = msg.get(MT.ARG_MODEL_DELTA)
                method = msg.get(MT.ARG_COMPRESSION)
                if payload is None or method is None:
                    raise ValueError(
                        f"model upload from sender {msg.get_sender_id()} "
                        "carries neither model_params nor a tagged "
                        "compressed delta"
                    )
                from fedml_tpu.core import compression as CZ

                params = CZ.decode_update(payload, self.global_vars, method)
            self.aggregator.add_local_trained_result(
                worker, params, msg.get(MT.ARG_NUM_SAMPLES)
            )
            if self.aggregator.check_whether_all_receive() or (
                self._deadline_passed
                and self.aggregator.received_count() >= self._quorum()
            ):
                self._complete_round()

    def _complete_round(self):
        """Aggregate whatever has arrived, eval, resample, broadcast.
        Caller holds _round_lock."""
        self._disarm_deadline()
        if self.config.comm.secure_agg:
            from fedml_tpu.secagg.secure_aggregation import (
                round_aggregator,
                tree_dim,
                unmask_round_average,
            )

            agg = round_aggregator(
                self.worker_num,
                tree_dim(self.global_vars),
                self.config.seed,
                self.round_idx,
            )
            avg = unmask_round_average(
                agg, self._masked_uploads, self._masked_ns, self.global_vars
            )
            self._masked_uploads, self._masked_ns = {}, {}
        else:
            avg = self.aggregator.aggregate()
        if self._server_step is not None:
            if self._server_opt_state is None:
                self._server_opt_state = self._server_optimizer.init(
                    self.global_vars["params"]
                )
            self.global_vars, self._server_opt_state = jax.device_get(
                self._server_step(self.global_vars, avg, self._server_opt_state)
            )
        else:
            self.global_vars = avg
        row = {"round": self.round_idx}
        eval_now = self.data is not None and (
            self.round_idx % self.config.fed.frequency_of_the_test == 0
            or self.round_idx == self.config.fed.comm_round - 1
        )
        if eval_now:
            loss, acc = evaluate(
                self.model,
                self.global_vars,
                self.data.test_x,
                self.data.test_y,
                task=self.task,
                eval_fn=self._eval_fn,
            )
            row["Test/Loss"], row["Test/Acc"] = loss, acc
        self.history.append(row)
        self.log_fn(row)
        self.round_idx += 1
        if self.round_idx >= self.config.fed.comm_round:
            for worker in range(1, self.worker_num + 1):
                self.send_message(Message(MT.FINISH, 0, worker))
            self.finish()
            return
        sampled = client_sampling(
            self.round_idx, self.config.fed.client_num_in_total, self.worker_num
        )
        for worker, client_idx in enumerate(sampled, start=1):
            msg = Message(MT.S2C_SYNC_MODEL, 0, worker)
            msg.add_params(MT.ARG_MODEL_PARAMS, self.global_vars)
            msg.add_params(MT.ARG_CLIENT_INDEX, int(client_idx))
            msg.add_params(MT.ARG_ROUND_IDX, self.round_idx)
            self.send_message(msg)
        self._arm_deadline()


class FedAvgClientManager(ClientManager):
    """ref FedAvgClientManager.py:17-65."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        rank: int,
        trainer: LocalTrainer,
        ef=None,
    ):
        super().__init__(comm, rank)
        self.config = config
        self.trainer = trainer
        # TopKErrorFeedback store. The residual must follow the CLIENT, and
        # sampling re-assigns clients to ranks every round — so in-process
        # runtimes SHARE one store across all client actors (run_federation
        # passes it in); a per-process store (grpc) is only sound under
        # rank-stable assignment, which the CLI enforces (full
        # participation).
        if ef is None:
            from fedml_tpu.core.compression import TopKErrorFeedback

            ef = TopKErrorFeedback.maybe_from_config(config.comm)
        self._ef = ef

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MT.S2C_INIT_CONFIG, self._on_sync)
        self.register_message_receive_handler(MT.S2C_SYNC_MODEL, self._on_sync)
        self.register_message_receive_handler(MT.FINISH, lambda m: self.finish())

    def _on_sync(self, msg: Message):
        self.trainer.update_dataset(msg.get(MT.ARG_CLIENT_INDEX))
        round_idx = msg.get(MT.ARG_ROUND_IDX)
        w_round = msg.get(MT.ARG_MODEL_PARAMS)
        weights, n = self.trainer.train(round_idx, w_round)
        out = Message(MT.C2S_SEND_MODEL, self.rank, 0)
        comp = self.config.comm.compression
        if self.config.comm.secure_agg:
            # masked upload (ref distributed turboaggregate): the server
            # only ever sees the pairwise-masked field vector
            from fedml_tpu.secagg.secure_aggregation import (
                mask_round_update,
                round_aggregator,
                tree_dim,
            )

            agg = round_aggregator(
                self.config.fed.client_num_per_round,
                tree_dim(weights),
                self.config.seed,
                round_idx,
            )
            out.add_params(
                MT.ARG_MASKED_UPDATE,
                mask_round_update(agg, self.rank - 1, weights, w_round, n),
            )
        elif comp != "none":
            # uplink compression (core/compression.py): send the encoded
            # round delta; the server reconstructs against the same w_round
            from fedml_tpu.core import compression as CZ

            if self._ef is not None:
                payload = self._ef.encode(
                    self.trainer.client_index, weights, w_round
                )
            else:
                payload = CZ.encode_update(
                    weights, w_round, comp, self.config.comm.topk_frac
                )
            out.add_params(MT.ARG_MODEL_DELTA, payload)
            out.add_params(MT.ARG_COMPRESSION, comp)
        else:
            out.add_params(MT.ARG_MODEL_PARAMS, weights)
        out.add_params(MT.ARG_NUM_SAMPLES, n)
        # round tag: lets the server discard a straggler's upload for an
        # already-closed round (FedConfig.deadline_s)
        out.add_params(MT.ARG_ROUND_IDX, round_idx)
        self.send_message(out)


def run_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    comm_factory,
    task: str = "classification",
    log_fn=None,
    trainer_factory=None,
    server_opt: bool = False,
):
    """One-process federation over any transport: 1 server + K client actors
    in threads, each on ``comm_factory(rank)`` (a BaseCommManager) — the
    transport-path analog of the reference's mpirun smoke runs
    (CI-script-framework.sh:16-23), but with a real exit-code/join
    discipline, and pluggable across loopback/gRPC/MQTT exactly like the
    reference's ``--backend`` switch (client_manager.py:20-33). Returns the
    server manager (global_vars, history)."""
    K = config.fed.client_num_per_round
    server = FedAvgServerManager(
        config,
        comm_factory(0),
        model,
        data=data,
        task=task,
        worker_num=K,
        log_fn=log_fn,
        server_opt=server_opt,
    )
    shared_train = jax.jit(
        make_local_train(model, config.train, config.fed.epochs, task=task)
    )
    make_trainer = trainer_factory or (
        lambda rank: LocalTrainer(
            config, data, model, task, local_train_fn=shared_train
        )
    )
    # one shared error-feedback store: residuals are keyed by client id and
    # the sampler re-assigns clients to ranks each round
    from fedml_tpu.core.compression import TopKErrorFeedback

    shared_ef = TopKErrorFeedback.maybe_from_config(config.comm)
    if shared_ef is not None and config.fed.deadline_s:
        # depth guard (not just a CLI nicety): a quorum round can discard a
        # late upload AFTER the client cleared its residual — that mass
        # would be permanently lost
        raise ValueError(
            "error_feedback cannot be combined with deadline_s quorum "
            "rounds: a dropped late upload loses residual-cleared mass"
        )
    clients = [
        FedAvgClientManager(
            config, comm_factory(rank), rank, make_trainer(rank), ef=shared_ef
        )
        for rank in range(1, K + 1)
    ]
    errors: List[BaseException] = []

    def guarded_run(c):
        # A dead client would stall the server's all-received barrier
        # forever; surface the failure by stopping the server loop.
        try:
            c.run()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
            server.finish()

    threads = [
        threading.Thread(target=guarded_run, args=(c,), daemon=True)
        for c in clients
    ]
    for t in threads:
        t.start()
    server.send_init_msg()
    server.run()  # blocks until FINISH or a client failure stops the loop
    if getattr(server, "deadline_error", None) is not None:
        for c in clients:
            c.finish()
        raise RuntimeError("server deadline path failed") from server.deadline_error
    if errors:
        # release the surviving client threads before raising — they would
        # otherwise park on inbox.get() for the process lifetime.
        for c in clients:
            c.finish()
        raise RuntimeError("client actor failed") from errors[0]
    for t in threads:
        t.join(timeout=60)
        if t.is_alive():
            raise RuntimeError("client thread failed to finish")
    return server


def run_loopback_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    server_opt: bool = False,
):
    """Federation over the in-process loopback hub (see run_federation)."""
    hub = LoopbackHub()
    return run_federation(
        config,
        data,
        model,
        lambda rank: LoopbackCommManager(hub, rank),
        task=task,
        log_fn=log_fn,
        server_opt=server_opt,
    )


def run_shm_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    sock_dir: Optional[str] = None,
    server_opt: bool = False,
):
    """Federation over the shared-memory local transport (TRPC-equivalent,
    ref trpc_comm_manager.py:25-114): bulk tensors ride POSIX shared memory,
    only tiny control records cross the per-rank UNIX sockets."""
    import tempfile

    from fedml_tpu.core.shm_comm import ShmCommManager

    with tempfile.TemporaryDirectory(prefix="fedml_shm_") as d:
        return run_federation(
            config,
            data,
            model,
            lambda rank: ShmCommManager(rank, sock_dir or d),
            task=task,
            log_fn=log_fn,
            server_opt=server_opt,
        )


def run_mqtt_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    host: str = None,
    port: int = 1883,
    server_opt: bool = False,
):
    """Federation over MQTT pub/sub (ref mqtt_comm_manager.py:14-123):
    embedded in-process broker by default, real broker when host given."""
    from fedml_tpu.core.mqtt_comm import EmbeddedBroker, MqttCommManager

    if host is None:
        broker = EmbeddedBroker()
        factory = lambda rank: MqttCommManager(rank, broker=broker)
    else:
        factory = lambda rank: MqttCommManager(rank, host=host, port=port)
    return run_federation(
        config, data, model, factory, task=task, log_fn=log_fn,
        server_opt=server_opt,
    )
