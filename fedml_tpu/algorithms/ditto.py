"""Ditto — personalized federated learning (Li et al., MLSys 2021).

BEYOND the reference's inventory (SURVEY §2b lists no personalization
algorithm): every client keeps a PERSONAL model v_k alongside the shared
global model w. The global model trains exactly as FedAvg; after each
local training, the sampled clients also advance their personal model by
SGD on the personalized objective

    min_v  F_k(v) + lam/2 * ||v - w||^2

i.e. the task loss plus a proximal pull toward the CURRENT global model
(w at round start — the model the server broadcast). lam interpolates
between purely-local models (lam=0: v_k never sees the federation) and
the global model (lam→inf: v_k pinned to w). Personalized accuracy is
evaluated per client: v_k on client k's own shard.

TPU-first shape (same pattern as SCAFFOLD's control store,
algorithms/scaffold.py): the N personal models live as ONE stacked
[N, ...] device pytree; a round gathers the sampled rows, runs the lifted
personal trains under the same vmap/scan client schedules as FedAvg, and
scatters the rows back — all inside one jitted round function.

Oracle discipline (tests/test_ditto.py): the personal-train loop mirrors
train/client.make_local_train's rng/permutation structure EXACTLY, so at
lam=0 a personal step sequence is bit-identical to plain local training —
the degenerate-config equality the CI oracle pattern demands
(ref CI-script-fedavg.sh:42-48).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu import _jax_compat

_jax_compat.install()  # jax.shard_map / jax.lax.pcast on older jaxlib

from fedml_tpu.algorithms.fedavg import (
    FedAvgAPI,
    client_axis_map,
    make_fedavg_round_body,
    resolve_client_parallelism,
)
from fedml_tpu.config import RunConfig, TrainConfig
from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import make_local_train


def make_ditto_personal_train(
    model: ModelDef, tc: TrainConfig, epochs: int, lam: float,
    task: str = "classification",
):
    """Personal-model training step:
    ``(w_ref_params, v_vars, x, y, mask, rng) -> (v_vars', metrics)``.

    This IS train/client.make_local_train with ``external_prox=True`` and
    prox_mu=lam: the one difference from plain local training is that the
    proximal term pulls toward the EXTERNAL ``w_ref_params`` (the
    broadcast global model) instead of the entry params — Ditto's
    personalized objective. Sharing the loop keeps the lam=0 case
    bit-identical to plain local training by construction."""
    return make_local_train(
        model,
        dataclasses.replace(tc, prox_mu=lam),
        epochs,
        task=task,
        external_prox=True,
    )


def make_ditto_round(
    model: ModelDef,
    config: RunConfig,
    lam: float,
    task: str = "classification",
    client_mode: Optional[str] = None,
    donate: bool = True,
):
    """Jitted Ditto round: plain-FedAvg global update + personal-row
    updates, one program.

    ``(global_vars, v_stack, idx, x, y, mask, num_samples, rngs) ->
      (global_vars', v_stack', metrics)``

    The personal step's proximal reference is the round-START global model
    (the broadcast w^t, per the paper's v-update), not the round's new
    average."""
    body = _make_ditto_cohort_body(model, config, lam, task, client_mode)

    def round_fn(global_vars, v_stack, idx, x, y, mask, num_samples, rngs):
        v_rows = jax.tree_util.tree_map(lambda s: s[idx], v_stack)
        new_global, new_rows, g_metrics = body(
            global_vars, v_rows, x, y, mask, num_samples, rngs
        )
        new_stack = jax.tree_util.tree_map(
            lambda s, r: s.at[idx].set(r), v_stack, new_rows
        )
        return new_global, new_stack, g_metrics

    # program dedup (fedml_tpu/compile/): fedlint uncached-jit caught this
    # factory returning a bare jit object — --warmup aside, every DittoAPI
    # construction over the same (model, config, lam) recompiled its own
    # round. lam is baked into the traced personal objective (prox_mu) as
    # a program CONSTANT, so it must split the digest.
    from fedml_tpu.compile import get_program_cache, model_fingerprint

    return get_program_cache().get_or_build(
        "ditto_round",
        {
            "kind": "ditto_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "lam": float(lam),
            "mode": client_mode,
            "parallelism": config.fed.client_parallelism,
            "donate": donate,
        },
        lambda: jax.jit(round_fn, donate_argnums=(1,) if donate else ()),
    )


def _make_ditto_cohort_body(model, config, lam, task, client_mode):
    """THE cohort-level Ditto round math — one definition shared by the
    full-stack round (which wraps it with the in-program idx
    gather/scatter) and the spilled cohort round (which jits it bare), so
    the two can never drift and spilled == in-HBM holds by construction
    (tests/test_state_spill.py)."""
    mode = client_mode or resolve_client_parallelism(
        config.fed.client_parallelism, model
    )
    fedavg_body = make_fedavg_round_body(
        model, config, task=task, client_mode=mode
    )
    personal = make_ditto_personal_train(
        model, config.train, config.fed.epochs, lam, task=task
    )
    lifted_personal = client_axis_map(personal, mode, n_broadcast=1)

    def body(global_vars, v_rows, x, y, mask, num_samples, rngs):
        new_global, (_, g_metrics) = fedavg_body(
            global_vars, x, y, mask, num_samples, rngs
        )
        # independent personal rng stream: same per-round keys, folded so
        # the global and personal shuffles/dropout draws are uncorrelated
        p_rngs = jax.vmap(lambda k: jax.random.fold_in(k, 0x0D17_70))(rngs)
        # personal metrics are dropped (nothing downstream reads them —
        # FedAvgAPI._pack_metrics consumes the global keys only; XLA DCEs
        # the unused computation), so the round's metrics are exactly the
        # FedAvg global-training metrics.
        new_rows, _ = lifted_personal(
            global_vars["params"], v_rows, x, y, mask, p_rngs
        )
        new_rows = jax.tree_util.tree_map(
            lambda r, old: r.astype(old.dtype), new_rows, v_rows
        )
        return new_global, new_rows, jax.tree_util.tree_map(jnp.sum, g_metrics)

    return body


def make_ditto_cohort_round(
    model: ModelDef,
    config: RunConfig,
    lam: float,
    task: str = "classification",
    client_mode: Optional[str] = None,
):
    """Cohort-form Ditto round for the SPILLED personal-model store:
    ``(global_vars, v_rows, x, y, mask, num_samples, rngs) ->
      (global_vars', v_rows', metrics)``
    — :func:`make_ditto_round` with the [N, ...] stack gather/scatter
    moved out to the host store (state_store.MmapClientState); only the
    cohort's [C, ...] personal rows enter HBM. Identical in-program math
    ⇒ spilled runs bit-match in-HBM runs (tests/test_state_spill.py)."""
    from fedml_tpu.compile import get_program_cache, model_fingerprint

    # donate the cohort rows (argnum 1): the host store keeps the durable copy
    return get_program_cache().get_or_build(
        "ditto_cohort_round",
        {
            "kind": "ditto_cohort_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "lam": float(lam),
            "mode": client_mode,
            "parallelism": config.fed.client_parallelism,
        },
        lambda: jax.jit(
            _make_ditto_cohort_body(model, config, lam, task, client_mode),
            donate_argnums=(1,),
        ),
    )


def make_sharded_ditto_cohort_round(
    model: ModelDef,
    config: RunConfig,
    mesh,
    lam: float,
    task: str = "classification",
):
    """Cohort-form Ditto round over a client-sharded mesh (the spill-tier
    x multi-chip composition, VERDICT r4 Weak #4 — same shape as
    scaffold.make_sharded_scaffold_cohort_round): personal rows arrive
    SHARDED over the client axis straight from the host store's cohort
    gather and leave sharded for the scatter; the global FedAvg update is
    the weighted psum. Padded dummy rows (num_samples == 0, all-zero
    masks) contribute zero weight and unchanged rows."""
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    mode = resolve_client_parallelism(config.fed.client_parallelism, model)
    local_train = make_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    lifted_local = client_axis_map(local_train, mode)
    personal = make_ditto_personal_train(
        model, config.train, config.fed.epochs, lam, task=task
    )
    lifted_personal = client_axis_map(personal, mode, n_broadcast=1)

    def shard_body(global_vars, v_rows, x, y, mask, num_samples, rngs):
        varying = lambda t: jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"), t
        )
        gv = varying(global_vars)
        client_vars, metrics = lifted_local(gv, x, y, mask, rngs)
        wsum = jax.lax.psum(jnp.sum(num_samples), axis)
        w = num_samples / jnp.maximum(wsum, 1e-9)
        new_global = jax.tree_util.tree_map(
            lambda s: jax.lax.psum(
                jnp.tensordot(w, s.astype(jnp.float32), axes=1), axis
            ),
            client_vars,
        )
        p_rngs = jax.vmap(lambda k: jax.random.fold_in(k, 0x0D17_70))(rngs)
        new_rows, _ = lifted_personal(gv["params"], v_rows, x, y, mask, p_rngs)
        new_rows = jax.tree_util.tree_map(
            lambda r, old: r.astype(old.dtype), new_rows, v_rows
        )
        agg = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(jnp.sum(m), axis), metrics
        )
        return new_global, new_rows, agg

    data_spec = P(axis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(),) + (data_spec,) * 6,
        out_specs=(P(), data_spec, P()),
        check_vma=False,  # same stance as make_sharded_ditto_round
    )
    from fedml_tpu.compile import (
        get_program_cache,
        mesh_fingerprint,
        model_fingerprint,
    )

    return get_program_cache().get_or_build(
        "sharded_ditto_cohort_round",
        {
            "kind": "sharded_ditto_cohort_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "lam": float(lam),
            "parallelism": config.fed.client_parallelism,
            "mesh": mesh_fingerprint(mesh),
        },
        lambda: jax.jit(sharded, donate_argnums=(1,)),
    )


def make_sharded_ditto_round(
    model: ModelDef,
    config: RunConfig,
    mesh,
    lam: float,
    task: str = "classification",
    donate: bool = True,
):
    """Ditto round over a client-sharded mesh (shard_map form of
    make_ditto_round, same signature; no reference counterpart — the ref
    has no personalization at all).

    Sharding layout mirrors SCAFFOLD's (scaffold.make_sharded_scaffold_round):
    the personal store ``v_stack`` stays REPLICATED; the cohort's data and
    index vector shard over the client axis. Each shard gathers its own
    clients' personal rows, trains them against the replicated broadcast
    model, and the row updates travel as all_gathered COHORT deltas
    (O(|S|·params) over ICI) applied with ``.at[idx].add`` — dummy padding
    clients train on all-zero masks, end exactly where they started, and
    contribute exact-zero deltas, so idx collisions with padding rows are
    harmless."""
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    mode = resolve_client_parallelism(config.fed.client_parallelism, model)
    local_train = make_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    lifted_local = client_axis_map(local_train, mode)
    personal = make_ditto_personal_train(
        model, config.train, config.fed.epochs, lam, task=task
    )
    lifted_personal = client_axis_map(personal, mode, n_broadcast=1)

    def shard_body(global_vars, v_stack, idx, x, y, mask, num_samples, rngs):
        varying = lambda t: jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"), t
        )
        gv = varying(global_vars)
        stack = varying(v_stack)
        client_vars, metrics = lifted_local(gv, x, y, mask, rngs)
        wsum = jax.lax.psum(jnp.sum(num_samples), axis)
        w = num_samples / jnp.maximum(wsum, 1e-9)
        new_global = jax.tree_util.tree_map(
            lambda s: jax.lax.psum(
                jnp.tensordot(w, s.astype(jnp.float32), axes=1), axis
            ),
            client_vars,
        )
        v_rows = jax.tree_util.tree_map(lambda s: s[idx], stack)
        p_rngs = jax.vmap(lambda k: jax.random.fold_in(k, 0x0D17_70))(rngs)
        new_rows, _ = lifted_personal(gv["params"], v_rows, x, y, mask, p_rngs)
        delta = jax.tree_util.tree_map(
            lambda new, old: new.astype(old.dtype) - old, new_rows, v_rows
        )
        idx_all = jax.lax.all_gather(idx, axis, tiled=True)
        delta_all = jax.tree_util.tree_map(
            lambda d: jax.lax.all_gather(d, axis, tiled=True), delta
        )
        new_stack = jax.tree_util.tree_map(
            lambda stack_l, d: stack_l.at[idx_all].add(d), stack, delta_all
        )
        agg = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(jnp.sum(m), axis), metrics
        )
        return new_global, new_stack, agg

    data_spec = P(axis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P()) + (data_spec,) * 6,
        out_specs=(P(), P(), P()),
        # every output is psum/all_gather-combined, replicated by
        # construction; custom-VJP norm ops inside local_train defeat
        # static VMA inference (same stance as scaffold's sharded round)
        check_vma=False,
    )
    from fedml_tpu.compile import (
        get_program_cache,
        mesh_fingerprint,
        model_fingerprint,
    )

    return get_program_cache().get_or_build(
        "sharded_ditto_round",
        {
            "kind": "sharded_ditto_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "lam": float(lam),
            "parallelism": config.fed.client_parallelism,
            "mesh": mesh_fingerprint(mesh),
            "donate": donate,
        },
        lambda: jax.jit(sharded, donate_argnums=(1,) if donate else ()),
    )


class DittoAPI(FedAvgAPI):
    """Ditto simulator on the FedAvg skeleton — adds the per-client
    personal-model store and per-client personalized evaluation. The store
    is a stacked on-device [N, ...] pytree while it fits
    FedConfig.state_budget_bytes and SPILLS to the disk tier beyond it
    (state_store.MmapClientState; round 3 refused instead, VERDICT r3
    Weak #3) — Ditto is cross-device by nature, so the spill path is the
    one that scales it to the data layer's 100k-client regime."""

    _supports_fused = False  # per-round personal-state exchange

    def __init__(
        self, config: RunConfig, data: FederatedDataset, model: ModelDef,
        lam: float = 0.1, **kw,
    ):
        super().__init__(config, data, model, **kw)
        from fedml_tpu.algorithms.state_store import (
            make_spill_store,
            resolve_state_store,
        )

        self.lam = float(lam)
        n = config.fed.client_num_in_total
        vbytes = sum(
            int(np.prod(v.shape)) * v.dtype.itemsize
            for v in jax.tree_util.tree_leaves(self.global_vars)
        )
        self._state_mode = resolve_state_store(
            config.fed, vbytes * n, n_clients=n,
            population=getattr(config, "population", None),
        )
        if self._state_mode == "device":
            # paper init: v_k = w_0 (every personal model starts at the
            # global init)
            self.v_stack = jax.tree_util.tree_map(
                lambda g: jnp.broadcast_to(g, (n,) + g.shape), self.global_vars
            )
            self._ditto_round = self._build_ditto_round()
        else:
            from fedml_tpu.algorithms.state_store import CohortPrefetcher

            self.v_stack = None
            # lazy v_k = w_0 init: untouched rows gather as w_0 without a
            # 100k-row write at construction
            self._v_store = make_spill_store(
                self._state_mode,
                jax.device_get(self.global_vars),
                n,
                config.fed.state_dir or None,
                population=getattr(config, "population", None),
            )
            self._v_prefetch = CohortPrefetcher(self._v_store)
            self._ditto_round = self._build_ditto_cohort_round()

    def _build_ditto_cohort_round(self):
        """Jitted cohort-form round for the SPILLED store. The mesh
        subclass swaps in the shard_map form — spill and multi-chip
        compose (round 4 refused here, VERDICT r4 Weak #4)."""
        return make_ditto_cohort_round(
            self.model, self.config, self.lam, task=self.task,
            client_mode=self._client_mode,
        )

    def _build_ditto_round(self):
        return make_ditto_round(
            self.model, self.config, self.lam, task=self.task,
            client_mode=self._client_mode, donate=self._donate,
        )

    def _build_round_fn(self, local_train_fn):
        return None  # unused — train_round is fully overridden

    def round_flops(self, round_idx: int = 0):
        return None  # bespoke round fn; XLA cost analysis not wired

    def checkpoint_state(self):
        """Personal models are round state — a resume that dropped them
        would silently reset every client's personalization. Spilled-
        store checkpoints embed the touched rows themselves
        (self-contained npz); either representation restores into either
        store mode."""
        if self._state_mode == "device":
            return {"v_stack": self.v_stack}
        # self-contained: the touched rows ARE the store's whole
        # information content (untouched rows gather as w_0), so the
        # checkpoint survives tmp-cleaners and never references the live
        # (still-mutating) directory
        self._v_store.flush()  # checkpoint == durability point for the spill tier
        idx = self._v_store.initialized_ids()
        rows = self._v_store.gather(idx)
        out = {"v_rows_idx": idx}
        for i, leaf in enumerate(jax.tree_util.tree_leaves(rows)):
            out[f"v_rows_{i}"] = leaf
        return out

    def restore_state(self, tree):
        from fedml_tpu.utils.checkpoint import restore_like

        if self._state_mode != "device":
            # a pending prefetch holds PRE-restore rows; drop it before
            # reset_to rewrites the store
            self._v_prefetch.cancel()
        if "v_stack" in tree:
            if self._state_mode == "device":
                self.v_stack = restore_like(self.v_stack, tree["v_stack"])
            else:
                # a device-mode checkpoint restores into a spilled run by
                # scattering the whole stack
                stack = restore_like(
                    jax.tree_util.tree_map(
                        lambda g: jnp.broadcast_to(
                            g, (self._v_store.n,) + g.shape
                        ),
                        self.global_vars,
                    ),
                    tree["v_stack"],
                )
                self._v_store.reset_to(
                    np.arange(self._v_store.n), jax.device_get(stack)
                )
        else:
            idx = np.asarray(tree["v_rows_idx"])
            template = jax.device_get(self.global_vars)
            leaves, treedef = jax.tree_util.tree_flatten(template)
            rows = jax.tree_util.tree_unflatten(
                treedef,
                [np.asarray(tree[f"v_rows_{i}"]) for i in range(len(leaves))],
            )
            if self._state_mode == "device":
                # a spilled checkpoint restores into a device-mode run
                self.v_stack = jax.tree_util.tree_map(
                    lambda s, r: jnp.asarray(s).at[
                        jnp.asarray(idx)
                    ].set(jnp.asarray(r)),
                    jax.tree_util.tree_map(
                        lambda g: jnp.broadcast_to(
                            g,
                            (self.config.fed.client_num_in_total,) + g.shape,
                        ),
                        self.global_vars,
                    ),
                    rows,
                )
            else:
                self._v_store.reset_to(idx, rows)

    def _personal_row(self, i: int):
        """Client i's personal model as a single-row pytree — the one
        accessor personalized eval uses, store-agnostic."""
        if self._state_mode == "device":
            return jax.tree_util.tree_map(lambda s: s[i], self.v_stack)
        return jax.tree_util.tree_map(
            lambda r: r[0], self._v_store.gather([i])
        )

    def _place_client_indices(self, sampled):
        """The sampled client ids as the round fn's gather/scatter index
        vector — the sharded subclass pads to the mesh and shards it."""
        return jnp.asarray(np.asarray(sampled, np.int32))

    def train_round(self, round_idx: int):
        sampled, _steps, _bs = self._round_plan(round_idx)
        # batch via the shared warmup/pipeline stash contract (see
        # fedavg._round_placed — byte-identical to building it here)
        placed = self._round_placed(round_idx, sampled)
        if self._state_mode == "device":
            self.global_vars, self.v_stack, metrics = self._ditto_round(
                self.global_vars,
                self.v_stack,
                self._place_client_indices(sampled),
                *placed,
            )
            return sampled, metrics
        # NOTE: this take/launch/device_get/scatter choreography is the
        # same contract as ScaffoldAPI.train_round's spilled path (exclude
        # this round's ids from the background read; scatter only
        # rows[:n_real]) — tests/test_state_spill.py pins both against
        # their in-HBM twins, so a divergence fails loudly
        ids, n_real = self._spill_pad_ids(sampled)
        v_rows = self._place_cohort_rows(self._v_prefetch.take(round_idx, ids))
        self.global_vars, new_rows, metrics = self._ditto_round(
            self.global_vars,
            v_rows,
            *placed,
        )
        # overlap the next cohort's disk gather with this round's device
        # compute; rows scattered below are excluded (no torn reads)
        if round_idx + 1 < self.config.fed.comm_round:
            nxt_ids, _ = self._spill_pad_ids(self._round_plan(round_idx + 1)[0])
            self._v_prefetch.launch(
                round_idx + 1, nxt_ids,
                exclude=set(int(i) for i in np.asarray(sampled)),
            )
        host_rows = jax.device_get(new_rows)
        self._v_store.scatter(
            np.asarray(sampled),
            jax.tree_util.tree_map(lambda r: r[:n_real], host_rows),
        )
        return sampled, metrics

    def train(self):
        final = super().train()
        final = dict(final or {})
        personalized = self.personalized_test_on_clients()
        final.update(personalized)
        self.log_fn(personalized)
        return final

    def personalized_test_on_clients(
        self, batch_size: int = 256, max_clients: int = 256,
    ):
        """Per-client eval of each personal model on that client's OWN
        shard (test shard when present, else train shard) — Ditto's
        headline metric, vs the single global model on the same shards.
        Above ``max_clients`` clients a seeded subset is evaluated (two
        evals per client; unbounded N would dwarf the training loop)."""
        from fedml_tpu.train.evaluate import evaluate

        has_test = self.data.client_test_x is not None
        ids = range(self.data.num_clients)
        if self.data.num_clients > max_clients:
            ids = np.random.default_rng(self.config.seed).choice(
                self.data.num_clients, size=max_clients, replace=False
            )
        per_rows, g_rows = [], []
        for i in ids:
            x = (self.data.client_test_x if has_test else self.data.client_x)[i]
            y = (self.data.client_test_y if has_test else self.data.client_y)[i]
            if len(y) == 0:
                continue
            v_i = self._personal_row(i)
            _, acc_p = evaluate(
                self.model, v_i, x, y, batch_size=batch_size, task=self.task,
                eval_fn=self.eval_fn,
            )
            _, acc_g = evaluate(
                self.model, self.global_vars, x, y, batch_size=batch_size,
                task=self.task, eval_fn=self.eval_fn,
            )
            per_rows.append(float(acc_p))
            g_rows.append(float(acc_g))
        return {
            "Personalized/Acc": float(np.mean(per_rows)),
            "Global/Acc": float(np.mean(g_rows)),
            "num_clients_evaluated": len(per_rows),
        }
