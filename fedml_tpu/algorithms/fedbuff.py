"""Asynchronous buffered federated aggregation (FedBuff) over the
Message/Observer transport — beyond the reference, which has NO async path:
its aggregator barrier waits for every worker forever
(ref FedAVGAggregator.py:43-49; SURVEY §5 "no straggler mitigation, no
client-dropout tolerance"), so one slow device rate-limits the fleet. The
sync transport here already softens that with deadline/quorum rounds
(fedavg_transport.py); this module removes the barrier entirely.

Protocol (the buffered-async scheme of Nguyen et al., AISTATS 2022 —
public algorithm, implemented fresh):

- the server keeps a model VERSION counter ``t`` and a buffer of client
  deltas. There are no rounds and no barrier.
- every client upload is answered IMMEDIATELY with the current model and
  a fresh client assignment — workers never idle waiting for each other,
  so a slow worker costs only its own throughput (its eventual update is
  staleness-discounted, not waited for).
- a client trains from the version-``b`` model and uploads
  ``delta = w_local - w_b`` tagged with ``b``; staleness is
  ``tau = t - b``.
- when the buffer holds ``k = FedConfig.async_buffer_k`` deltas the
  server applies one step (``apply_buffered_update``):

      w  <-  w + eta_g * sum_i s(tau_i) d_i / sum_i s(tau_i),
      s(tau) = (1 + tau) ** -async_staleness_exp

  and advances ``t``. ``FedConfig.comm_round`` counts these server steps.

TPU stance (SURVEY §7 "async/cross-silo boundary"): the jitted programs
stay pure — the client runs the same compiled local-train scan as the
sync path, the server step is one jitted stacked-tree contraction — and
ALL asynchrony lives in the host-side actor loop, which is exactly the
transport layer the Observer pattern already gives us.

Degenerate-config oracle (tests/test_fedbuff.py): with every delta at
staleness 0, eta_g=1 and k uploads from equal-sized shards, one buffered
step equals the synchronous FedAvg average of the k local models.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.config import RunConfig
from fedml_tpu.core.comm import BaseCommManager
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.core.managers import ClientManager, ServerManager
from fedml_tpu.core.message import Message, MessageType as MT
from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.models import ModelDef
from fedml_tpu.algorithms.fedavg_transport import LocalTrainer, _model_wire_cost
from fedml_tpu.telemetry import ClientHealthRegistry, get_comm_meter, get_tracer
from fedml_tpu.train.evaluate import evaluate, make_eval_fn


def staleness_weight(tau, exp: float = 0.5):
    """Polynomial staleness discount s(tau) = (1+tau)^-exp; s(0) = 1."""
    return (1.0 + jnp.asarray(tau, jnp.float32)) ** (-exp)


def apply_buffered_update(global_vars, deltas: list, taus, eta_g: float, exp: float):
    """One buffered server step: staleness-weighted mean of client deltas
    applied to the global model. Pure — jit/oracle-testable independent of
    the transport machinery."""
    stacked = jax.tree_util.tree_map(
        lambda *ls: jnp.stack([jnp.asarray(l) for l in ls]), *deltas
    )
    w = staleness_weight(jnp.asarray(taus, jnp.float32), exp)
    w = w / jnp.sum(w)

    def upd(g, d):
        g = jnp.asarray(g)
        mean = jnp.tensordot(w, d.astype(jnp.float32), axes=1)
        return (g + eta_g * mean).astype(g.dtype)

    return jax.tree_util.tree_map(upd, global_vars, stacked)


class FedBuffServerManager(ServerManager):
    """Barrier-free server: buffer deltas, flush every k, always answer an
    upload with the current model + a new client assignment."""

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        model: ModelDef,
        data: Optional[FederatedDataset] = None,
        task: str = "classification",
        worker_num: Optional[int] = None,
        log_fn=None,
        max_workers: Optional[int] = None,
    ):
        super().__init__(comm, rank=0, config=config)
        if config.fed.async_buffer_k <= 0:
            raise ValueError("FedBuff requires FedConfig.async_buffer_k > 0")
        self.config = config
        self.model = model
        self.data = data
        self.task = task
        self.log_fn = log_fn or (lambda m: None)
        # None-check, not truthiness: worker_num=0 is a real fleet mode —
        # the server starts with an EMPTY fleet and every client enters
        # through the C2S_JOIN admission door (the fleet launcher's churn
        # path); `or` would silently coerce it to client_num_per_round
        self.worker_num = (
            worker_num if worker_num is not None
            else config.fed.client_num_per_round
        )
        # Elastic-fleet cap (fedml_tpu/serve/): C2S_JOIN from a rank
        # beyond the current fleet is accepted while the live worker
        # count is below this, refused with FINISH past it (backpressure
        # — the join is the admission point, so an over-subscribed tenant
        # sheds load at the door instead of queueing unbounded
        # assignments). None = the initial fleet is also the cap.
        self.max_workers = (
            int(max_workers) if max_workers is not None else self.worker_num
        )
        self.joins_accepted = 0
        self.joins_refused = 0
        self.leaves = 0
        # graceful stop (serve drain semantics, docs/SERVING.md): when
        # set, the next upload path shuts the federation down — after a
        # final flush of the partial buffer when _drain_on_stop (buffered
        # client work becomes one last server step), discarding it
        # otherwise. Handlers may set the flags directly; request_stop()
        # additionally applies them inline when called from outside.
        self._stop_requested = False
        self._drain_on_stop = True
        self.version = 0  # server model version t
        self.server_steps = 0  # buffer flushes so far
        self._dispatch_counter = 0
        self._buffer: List[dict] = []
        self._buffer_taus: List[int] = []
        self._finished = False
        self._dead_workers: set = set()
        # ranks that have actually been part of the fleet: the preset
        # in-process workers plus every admitted C2S_JOIN. The FINISH
        # broadcast iterates THIS set — under an admission-door fleet,
        # `range(1, worker_num+1)` contains phantom ranks that never
        # joined (spawned but reaped, refused, still dialing), and a
        # FINISH to a never-seen peer blocks in wait_for_ready for the
        # full send timeout, per phantom, while holding _lock
        self._joined: set = set(range(1, self.worker_num + 1))
        # fault-starvation valve: consecutive DECLINED assignments with no
        # intervening real upload. A plan that crashes/drops every client
        # would otherwise spin the decline/re-dispatch loop forever with
        # the buffer never reaching async_buffer_k — past the threshold
        # the server shuts down loudly instead (runner raises).
        self._decline_streak = 0
        self.fault_starved = False
        # at-least-once delivery dedupe: a client retries an upload whose
        # RPC erred client-side AFTER server-side delivery (e.g. a unary
        # deadline hit while the server was busy flushing); the dispatch
        # tag is unique per assignment and one assignment is outstanding
        # per worker, so last-tag-per-sender drops the duplicate
        self._last_upload_tag: Dict[int, int] = {}
        # worker -> (client_index, tag) of its one outstanding assignment;
        # duplicate uploads are answered by re-sending THIS, never by
        # minting a second assignment (see _dispatch reuse)
        self._outstanding: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self.staleness_seen: List[int] = []  # one entry per buffered delta
        self.global_vars = jax.device_get(
            model.init(jax.random.fold_in(jax.random.PRNGKey(config.seed), 0))
        )
        self.history: List[dict] = []
        self._eval_fn = make_eval_fn(model, task) if data is not None else None
        # Telemetry: per-client health from the span stream (in-process
        # workers) or the dispatch→upload round-trip (cross-process). The
        # straggler flag is the hook staleness-aware scheduling needs: a
        # flagged client's next delta can be discounted before it is even
        # buffered. Rounds here are model VERSIONS (there is no barrier).
        self._tracer = get_tracer()
        self.health = ClientHealthRegistry.from_config(config).attach(self._tracer)
        self._dispatch_times: Dict[int, tuple] = {}  # worker -> (cid, tag, t)
        # Non-uniform dispatch (FedConfig.selection): route each
        # assignment through the scheduler registry keyed by the dispatch
        # counter — straggler_aware skips telemetry-flagged clients,
        # power_of_choice biases to high-loss ones (staleness-aware
        # participation in the FedBuff sense: a straggler is avoided up
        # front instead of discounted after the fact). The default
        # "uniform" keeps the legacy seeded stream bit-for-bit.
        self._scheduler = None
        if getattr(config.fed, "selection", "uniform") != "uniform":
            from fedml_tpu.scheduler import ClientScheduler

            self._scheduler = ClientScheduler.from_config(
                config,
                num_clients=config.fed.client_num_in_total,
                data=data,
                health=self.health,
                tracer=self._tracer,
                memoize=False,  # keyed by dispatch counter, unbounded
            )

    # -- dispatch --
    def _next_client_index(self) -> int:
        """Seeded assignment stream (the async analog of the sync path's
        round-seeded client_sampling, ref FedAVGAggregator.py:80-88);
        policy-routed when FedConfig.selection is non-uniform."""
        if self._scheduler is not None:
            idx = int(self._scheduler.select(self._dispatch_counter, k=1)[0])
            self._dispatch_counter += 1
            return idx
        rng = np.random.default_rng(
            self.config.seed * 1_000_003 + self._dispatch_counter
        )
        self._dispatch_counter += 1
        return int(rng.integers(0, self.config.fed.client_num_in_total))

    def _dispatch(
        self, worker: int, msg_type: str = MT.S2C_SYNC_MODEL, reuse: bool = False
    ):
        if worker in self._dead_workers:
            return
        if reuse and worker in self._outstanding:
            # duplicate-upload reply: re-send the SAME outstanding
            # assignment (same tag/client) rather than minting a new one —
            # a duplicate must never increase the number of outstanding
            # assignments per worker (the dedupe invariant), only restate
            # the one that may have been lost. The model/base are CURRENT:
            # strictly fresher is fine, the tag is what dedupes.
            client_index, tag = self._outstanding[worker]
        else:
            client_index = self._next_client_index()
            tag = self._dispatch_counter
            self._outstanding[worker] = (client_index, tag)
        msg = Message(msg_type, 0, worker)
        msg.add_params(MT.ARG_MODEL_PARAMS, self.global_vars)
        msg.add_params(MT.ARG_CLIENT_INDEX, client_index)
        msg.add_params(MT.ARG_BASE_VERSION, self.version)
        # ARG_ROUND_IDX doubles as the batch-shuffle seed on the client
        msg.add_params(MT.ARG_ROUND_IDX, tag)
        # health: the tag is the dedupe key the client's local_train span
        # also carries (its "round"), so span- and server-side observations
        # of one assignment collapse to one record
        self._dispatch_times[worker] = (client_index, tag, time.monotonic())
        try:
            self.send_message(msg)
            # downlink accounting at dispatch encode time — the async
            # mirror of the sync server's broadcast metering
            shipped, raw = _model_wire_cost(self.global_vars)
            get_comm_meter().on_downlink(shipped, raw)
        except Exception as e:  # noqa: BLE001 — transport errors vary by backend
            self._dead_workers.add(worker)
            logging.warning("async dispatch to worker %d failed (%s)", worker, e)

    def send_init_msg(self):
        self._t0 = time.monotonic()
        # every steady-state dispatch runs inside a handler holding _lock;
        # the opening dispatches must too — an early JOIN/upload arriving
        # on the comm thread would otherwise race the assignment stream
        with self._lock:
            for worker in range(1, self.worker_num + 1):
                self._dispatch(worker, MT.S2C_INIT_CONFIG)

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(
            MT.C2S_SEND_MODEL, self._on_delta_from_client
        )
        self.register_message_receive_handler(MT.C2S_JOIN, self._on_join)
        self.register_message_receive_handler(MT.C2S_LEAVE, self._on_leave)

    def finish(self):
        self.health.detach()  # see FedAvgServerManager.finish
        super().finish()

    # -- elastic fleet membership (fedml_tpu/serve/) --
    def _live_worker_count(self) -> int:
        """Caller holds _lock. Membership is the _joined SET, not the
        1..worker_num range: an external fleet joins in arbitrary rank
        order, and counting the range would let one high-rank joiner
        inflate the live count by hundreds of phantoms (refusing every
        later join while the fleet is near-empty)."""
        return sum(1 for w in self._joined if w not in self._dead_workers)

    def _on_join(self, msg: Message):
        with self._lock:
            sender = msg.get_sender_id()
            if self._finished:
                # late joiner against a drained tenant: answer FINISH so
                # the worker exits instead of parking on its inbox
                try:
                    self.send_message(Message(MT.FINISH, 0, sender))
                except Exception:  # noqa: BLE001 — dead peer
                    pass
                return
            # set membership, not rank comparison: under sparse/shuffled
            # external joins, `sender <= worker_num` would treat every
            # never-joined rank below the current max as a live member
            # and wave it past the admission cap
            alive = (
                sender in self._joined and sender not in self._dead_workers
            )
            if not alive and self._live_worker_count() >= self.max_workers:
                # backpressure: the fleet is at capacity — refuse at the
                # door (FINISH) rather than admit a worker whose uploads
                # would only deepen the staleness tail. The refused rank
                # is recorded dead FIRST (same lock, ordered before the
                # counter an unlocked observer may poll): if a later
                # admission grows worker_num past it, _live_worker_count
                # must not count this never-admitted phantom as live.
                self._dead_workers.add(sender)
                self.joins_refused += 1
                logging.info(
                    "join from rank %d refused: fleet at max_workers=%d",
                    sender, self.max_workers,
                )
                try:
                    self.send_message(Message(MT.FINISH, 0, sender))
                except Exception:  # noqa: BLE001 — dead peer
                    pass
                return
            self._dead_workers.discard(sender)
            self.worker_num = max(self.worker_num, sender)
            self._joined.add(sender)
            self.joins_accepted += 1
            self._dispatch(sender)

    def _on_leave(self, msg: Message):
        with self._lock:
            sender = msg.get_sender_id()
            if sender in self._dead_workers:
                # duplicate LEAVE (at-least-once delivery) — already
                # counted; re-adding would double the leaves tally
                return
            # no more dispatches to this rank: mark it dead (a later JOIN
            # from the same rank revives it) and forget its outstanding
            # assignment — async has no barrier, the assignment simply
            # evaporates and the next upload from anyone refills the buffer
            self._dead_workers.add(sender)
            self._outstanding.pop(sender, None)
            self._dispatch_times.pop(sender, None)
            self.leaves += 1

    # -- graceful stop / rolling-checkpoint surface (fedml_tpu/serve/) --
    def _shutdown(self):
        """FINISH the fleet and stop this server's loop. Caller holds
        _lock (or is the constructor-less starvation path, same thread)."""
        self._finished = True
        for worker in sorted(self._joined):
            if worker in self._dead_workers:
                continue
            try:
                # single attempt on purpose: a dead rank at shutdown must
                # cost one bounded timeout, not the whole retry schedule —
                # multiplied by the dead fraction of a 1000-rank fleet
                self.comm.send_message_nowait(Message(MT.FINISH, 0, worker))
            except Exception:  # noqa: BLE001 — dead peer at shutdown
                pass
        self.finish()

    def request_stop(self, drain: bool = True, defer: bool = False) -> None:
        """Stop this tenant: ``drain=True`` flushes whatever deltas are
        buffered as one final (partial) server step before FINISHing the
        fleet — buffered client work is never thrown away; ``drain=False``
        discards the buffer. ``defer=True`` only sets the flags (safe
        from inside this server's own handlers — e.g. a rolling-
        checkpoint log_fn stopping the session at a chosen step); the
        next upload applies them. In-flight local trainings are answered
        by the FINISH already in each worker's inbox."""
        self._drain_on_stop = bool(drain)
        self._stop_requested = True
        if defer:
            return
        with self._lock:
            if self._finished:
                return
            if self._drain_on_stop and self._buffer:
                self._flush()
            if not self._finished:
                self._shutdown()

    def checkpoint_state(self) -> dict:
        """The server's algorithm-private state for the checkpoint
        ``algo`` slot (utils/checkpoint.py): model version, step count,
        and the dispatch counter. The dispatch stream is pure in
        (seed, counter), so a resumed session re-mints the in-flight
        assignments byte-identically — the async analog of the sync
        scheduler's selection memo. Rolling checkpoints are taken at
        flush boundaries (the buffer is empty when log_fn runs), so no
        buffered deltas need persisting."""
        return {
            "version": np.asarray(self.version, np.int64),
            "server_steps": np.asarray(self.server_steps, np.int64),
            "dispatch_counter": np.asarray(self._dispatch_counter, np.int64),
        }

    def restore_state(self, state: dict) -> None:
        # restore runs before the serve loop starts, but take the lock
        # anyway: it is free at that point and the counters it writes are
        # lock-protected everywhere else
        with self._lock:
            self.version = int(np.asarray(state["version"]))
            self.server_steps = int(np.asarray(state["server_steps"]))
            self._dispatch_counter = int(np.asarray(state["dispatch_counter"]))

    # -- aggregation --
    def _on_delta_from_client(self, msg: Message):
        with self._lock:
            if self._finished:
                return
            self._dead_workers.discard(msg.get_sender_id())
            if msg.get(MT.ARG_DECLINED):
                # fault-injected decline: no update for this assignment —
                # answer with a FRESH assignment (same dedupe discipline
                # as an upload) so the worker keeps feeding the buffer
                sender = msg.get_sender_id()
                tag = msg.get(MT.ARG_ROUND_IDX, -1)
                if tag >= 0 and self._last_upload_tag.get(sender) == tag:
                    # duplicate decline (at-least-once delivery): restate
                    # the outstanding assignment, same as the duplicate-
                    # UPLOAD path — the duplicate means the worker may
                    # never have seen our reply, and dropping it silently
                    # would strand the worker until its orphan deadline
                    if not self._finished:
                        self._dispatch(sender, reuse=True)
                    return
                self._last_upload_tag[sender] = tag
                self._decline_streak += 1
                if self._decline_streak > max(100, 20 * self.worker_num):
                    logging.error(
                        "fault plan starved the buffer: %d consecutive "
                        "declined assignments with no upload — every "
                        "client appears crashed/dropped; shutting down",
                        self._decline_streak,
                    )
                    self.fault_starved = True
                    self._shutdown()
                    return
                if self._stop_requested:
                    if self._drain_on_stop and self._buffer:
                        self._flush()
                    if not self._finished:
                        self._shutdown()
                    return
                self._dispatch(sender)
                return
            delta = msg.get(MT.ARG_ASYNC_DELTA)
            base = msg.get(MT.ARG_BASE_VERSION, -1)
            if delta is None or base < 0:
                logging.warning(
                    "async server dropping malformed upload from sender %s "
                    "(missing delta or base version — sync-protocol client?)",
                    msg.get_sender_id(),
                )
                return
            sender = msg.get_sender_id()
            tag = msg.get(MT.ARG_ROUND_IDX, -1)
            if tag >= 0 and self._last_upload_tag.get(sender) == tag:
                logging.info(
                    "async server dropping duplicate upload from rank %d "
                    "(dispatch tag %d already buffered — client retry "
                    "after a delivered-but-errored RPC)", sender, tag,
                )
                # still answer with a dispatch: the duplicate means the
                # client never saw OUR reply (it may have been the send
                # that failed) — dropping silently would leave the worker
                # assignment-less until its deadman fired. reuse=True
                # re-sends the outstanding assignment: if the original
                # reply WAS delivered after all, the worker redoes one
                # assignment and its re-upload dedupes here — outstanding
                # work can never grow.
                if not self._finished:
                    self._dispatch(sender, reuse=True)
                return
            self._last_upload_tag[sender] = tag
            self._decline_streak = 0  # a real upload: the fleet is alive
            disp = self._dispatch_times.get(sender)
            if disp is not None and disp[1] == tag:
                self.health.observe_train(
                    disp[0], tag, time.monotonic() - disp[2]
                )
                if self._scheduler is not None:
                    loss = msg.get(MT.ARG_TRAIN_LOSS)
                    if loss is not None:
                        self._scheduler.report_loss(disp[0], float(loss))
            tau = self.version - int(base)
            self._buffer.append(delta)
            self._buffer_taus.append(tau)
            self.staleness_seen.append(tau)
            if len(self._buffer) >= self.config.fed.async_buffer_k:
                self._flush()
            if self._stop_requested and not self._finished:
                # deferred stop (request_stop(defer=True), e.g. a rolling-
                # checkpoint log_fn killing the session at a chosen step):
                # drain flushes the partial buffer as one last step,
                # hard-stop discards it; either way the fleet FINISHes now
                if self._drain_on_stop and self._buffer:
                    self._flush()
                if not self._finished:
                    self._shutdown()
                return
            if not self._finished:
                self._dispatch(msg.get_sender_id())

    def _flush(self):
        """Apply one buffered server step; caller holds _lock."""
        fed = self.config.fed
        taus = list(self._buffer_taus)
        with self._tracer.span(
            "server_step",
            version=self.version,
            n_deltas=len(self._buffer),
            staleness_max=int(max(taus)),
        ):
            self.global_vars = jax.device_get(
                apply_buffered_update(
                    self.global_vars,
                    self._buffer,
                    taus,
                    fed.async_server_lr,
                    fed.async_staleness_exp,
                )
            )
        self._buffer, self._buffer_taus = [], []
        self.version += 1
        self.server_steps += 1
        row = {
            "server_step": self.server_steps,
            "version": self.version,
            "staleness_mean": float(np.mean(taus)),
            "staleness_max": int(np.max(taus)),
            # wall clock since w0 went out (matched-wall accuracy races)
            "t_s": round(time.monotonic() - getattr(self, "_t0", time.monotonic()), 3),
        }
        if self.data is not None and (
            self.server_steps % self.config.fed.frequency_of_the_test == 0
            or self.server_steps == fed.comm_round
        ):
            # keyed to the server_step span just recorded (it carried the
            # PRE-increment version), so the flight recorder merges this
            # eval into that step's folded record
            with self._tracer.span("eval", round=self.version - 1):
                loss, acc = evaluate(
                    self.model,
                    self.global_vars,
                    self.data.test_x,
                    self.data.test_y,
                    task=self.task,
                    eval_fn=self._eval_fn,
                )
            row["Test/Loss"], row["Test/Acc"] = loss, acc
        self.history.append(row)
        self.log_fn(row)
        if self.server_steps >= fed.comm_round:
            hist = {}
            for t in self.staleness_seen:
                hist[int(t)] = hist.get(int(t), 0) + 1
            self.log_fn(
                {
                    "async_final": True,
                    "server_steps": self.server_steps,
                    "wall_s": row["t_s"],
                    "staleness_hist": {str(k): v for k, v in sorted(hist.items())},
                }
            )
            self._shutdown()


class FedBuffClientManager(ClientManager):
    """Train-on-arrival worker: every received model is trained from and
    answered with a delta; FINISH ends the loop. Runs the SAME jitted
    local-train scan as the sync transport client."""

    #: seconds a worker waits, AFTER an upload, for the server's reply
    #: (redispatch or FINISH) before declaring itself orphaned. This is a
    #: HANG guard, not a latency SLA: without it a dead server leaves a
    #: silently-hung process parked on its inbox forever. The default is
    #: deliberately generous because the reply to the k-th uploader waits
    #: on the server's buffer flush, whose first occurrence (and first
    #: eval round) pays a jit compile — minutes on a slow CI host.
    #: Startup is fully exempt: a worker waiting for its FIRST dispatch
    #: waits indefinitely (clients legitimately start before the server).
    #: Override per-instance via the constructor.
    ORPHAN_DEADLINE_S = 600.0

    def __init__(
        self,
        config: RunConfig,
        comm: BaseCommManager,
        rank: int,
        trainer: LocalTrainer,
        orphan_deadline_s: Optional[float] = None,
        faults=None,
    ):
        super().__init__(comm, rank, config=config)
        self.config = config
        self.trainer = trainer
        # fault injection (scheduler/faults.py), keyed by the dispatch tag
        # (the async "round"): a dropout/crashed assignment is DECLINED —
        # the worker sends an empty ARG_DECLINED reply so the server
        # re-dispatches it a fresh assignment instead of the fleet
        # shrinking by one worker per injected fault (faults follow the
        # CLIENT; the worker slot is simulation infrastructure). flaky
        # double-sends the delta, exercising the server's at-least-once
        # dedupe; slowdown drives real staleness.
        self._faults = faults
        if orphan_deadline_s is not None:
            self.ORPHAN_DEADLINE_S = float(orphan_deadline_s)
        self._got_finish = False
        # graceful leave (fedml_tpu/serve/ elastic fleets): when set, the
        # NEXT dispatch is answered with C2S_LEAVE instead of training —
        # the server stops dispatching to this rank and this worker's
        # receive loop ends. Leaving on a dispatch boundary (not mid-
        # train) keeps the protocol simple: the worker never abandons an
        # upload the server is accounting for.
        self._leave_requested = False
        self.left = False
        # assignment dedupe: the server restates a worker's OUTSTANDING
        # assignment when it sees a duplicate upload (at-least-once
        # recovery). If this worker already handled that tag, the restated
        # copy must be ignored — handling it again would upload a second
        # duplicate, which the server would again answer with a restated
        # assignment: a self-sustaining echo that doubles the worker's
        # work for the rest of the run.
        self._last_handled_tag: Optional[int] = None
        self._liveness_timer: Optional[threading.Timer] = None
        # arm/disarm/fire are serialized by this lock + generation counter:
        # Timer.cancel() cannot stop a callback already executing at the
        # deadline boundary, but a stale generation makes it a no-op
        self._live_lock = threading.Lock()
        self._live_gen = 0
        self.orphaned = False  # set by the deadman timer; checked by runners

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MT.S2C_INIT_CONFIG, self._on_model)
        self.register_message_receive_handler(MT.S2C_SYNC_MODEL, self._on_model)
        self.register_message_receive_handler(MT.FINISH, self._on_finish)

    # fedlint: disable=retry-no-dedupe -- FINISH is terminal and idempotent: the only accumulation on this path is _disarm_liveness's generation bump, which exists precisely so a late/duplicate timer or FINISH is a no-op
    def _on_finish(self, msg: Message):
        self._got_finish = True
        self.finish()

    def finish(self):
        # disarm on EVERY termination path (FINISH, runner-driven, deadman)
        # — a timer left armed across an external finish() would later fire
        # and spuriously mark an already-exited worker orphaned
        self._disarm_liveness()
        super().finish()

    def _arm_liveness(self):
        with self._live_lock:
            self._live_gen += 1
            if self._liveness_timer is not None:
                self._liveness_timer.cancel()
            t = threading.Timer(
                self.ORPHAN_DEADLINE_S, self._deadman, args=(self._live_gen,)
            )
            t.daemon = True
            t.start()
            self._liveness_timer = t

    def _disarm_liveness(self):
        with self._live_lock:
            self._live_gen += 1
            if self._liveness_timer is not None:
                self._liveness_timer.cancel()
                self._liveness_timer = None

    def _deadman(self, gen: int):
        with self._live_lock:
            if gen != self._live_gen or self._got_finish:
                return  # a reply/finish won the race — stale timer
            self.orphaned = True
        logging.error(
            "async worker rank %d: no server reply within %.0fs of the "
            "last upload — server lost; exiting as ORPHANED",
            self.rank, self.ORPHAN_DEADLINE_S,
        )
        self.finish()

    def request_leave(self) -> None:
        """Ask this worker to leave the fleet at its next dispatch (see
        ``_leave_requested``). Safe from any thread."""
        self._leave_requested = True

    def _on_model(self, msg: Message):
        self._disarm_liveness()
        if self._leave_requested:
            out = Message(MT.C2S_LEAVE, self.rank, 0)
            out.add_params(MT.ARG_ROUND_IDX, int(msg.get(MT.ARG_ROUND_IDX)))
            try:
                self.send_message(out)
            except Exception:  # noqa: BLE001 — a dead server can't
                pass  # object to us leaving
            self.left = True
            self.finish()
            return
        tag = int(msg.get(MT.ARG_ROUND_IDX))
        if tag == self._last_handled_tag:
            # restated assignment we already completed (see above) — but
            # keep the orphan deadman armed: we are still waiting on the
            # server's NEXT dispatch, and returning disarmed would let a
            # dead server strand this worker silently forever
            self._arm_liveness()
            return
        self._last_handled_tag = tag
        self.trainer.update_dataset(msg.get(MT.ARG_CLIENT_INDEX))
        w_base = msg.get(MT.ARG_MODEL_PARAMS)
        fd = None
        if self._faults is not None:
            cid = int(self.trainer.client_index)
            # probabilistic draws keyed by the unique dispatch tag;
            # crash_at_round compared against the server MODEL VERSION
            # (the async round analog — tags grow unboundedly and would
            # cross any crash threshold within a few dozen dispatches)
            fd = self._faults.decide(
                cid, tag, crash_round=int(msg.get(MT.ARG_BASE_VERSION, 0))
            )
            if fd.crashed or fd.drop:
                # decline the assignment: this CLIENT produces no update
                # (crash = never again, the injector records it once;
                # dropout = this assignment only), but the worker must
                # stay in the dispatch loop — going silent would shrink
                # the fleet by one worker per injected fault and starve
                # the buffer below async_buffer_k (a hang, not a test)
                self._faults.record(
                    cid, tag, "crash" if fd.crashed else "dropout"
                )
                out = Message(MT.C2S_SEND_MODEL, self.rank, 0)
                out.add_params(MT.ARG_DECLINED, True)
                out.add_params(MT.ARG_ROUND_IDX, tag)
                try:
                    self.send_message(out)
                finally:
                    self._arm_liveness()
                return
        new_vars, n = self.trainer.train(msg.get(MT.ARG_ROUND_IDX), w_base)
        if fd is not None and fd.slowdown_s:
            self._faults.record(
                int(self.trainer.client_index), tag, "slowdown",
                detail=fd.slowdown_s,
            )
            time.sleep(fd.slowdown_s)
        delta = jax.tree_util.tree_map(
            lambda a, b: np.asarray(a) - np.asarray(b), new_vars, w_base
        )
        out = Message(MT.C2S_SEND_MODEL, self.rank, 0)
        out.add_params(MT.ARG_ASYNC_DELTA, delta)
        out.add_params(MT.ARG_NUM_SAMPLES, n)
        out.add_params(MT.ARG_BASE_VERSION, msg.get(MT.ARG_BASE_VERSION))
        # dispatch tag: unique per assignment — the server's duplicate
        # filter keys on it (the retry below is at-least-once delivery)
        out.add_params(MT.ARG_ROUND_IDX, msg.get(MT.ARG_ROUND_IDX))
        if self.trainer.last_loss is not None:
            out.add_params(MT.ARG_TRAIN_LOSS, float(self.trainer.last_loss))
        if fd is not None and fd.flaky:
            # at-least-once double delivery: this extra copy lands first,
            # the loop below sends the "real" one, and the server's
            # dispatch-tag dedupe must absorb exactly one of them
            self._faults.record(int(self.trainer.client_index), tag, "flaky")
            try:
                self.send_message(out)
            except Exception:  # noqa: BLE001 — best-effort duplicate
                pass
        import time as _time

        try:
            for attempt in (1, 2):
                try:
                    self.send_message(out)
                    return
                except Exception as e:  # noqa: BLE001 — transport errors vary
                    if attempt == 1:
                        # one retry distinguishes a transient blip from the
                        # two terminal cases below
                        _time.sleep(0.5)
                        continue
                    # Either the normal end-of-run race — the server
                    # reached its last buffer flush and shut down while we
                    # were still training (its FINISH is already in our
                    # inbox and ends the loop as soon as this handler
                    # returns) — or a genuinely lost server. The liveness
                    # deadman armed below separates the two: FINISH within
                    # ORPHAN_DEADLINE_S is the clean race, silence marks
                    # this worker ORPHANED (visible, nonzero-exit via the
                    # runners) instead of a silent forever-block.
                    logging.warning(
                        "async upload from rank %d undeliverable after "
                        "retry (%s) — waiting %.0fs for FINISH",
                        self.rank, e, self.ORPHAN_DEADLINE_S,
                    )
        finally:
            # armed on BOTH outcomes: after a successful upload the server
            # replies immediately (redispatch or FINISH) in steady state,
            # so a silent gap past the deadline means the server died
            # between our upload and its reply
            self._arm_liveness()


def run_fedbuff_federation(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    comm_factory,
    task: str = "classification",
    log_fn=None,
):
    """One-process async federation: 1 server + worker_num client actors in
    threads over any BaseCommManager (structure mirrors
    fedavg_transport.run_federation).

    Thin blocking wrapper over :class:`fedml_tpu.serve.FedSession` — the
    long-lived service (fedml_tpu/serve/) runs N FedBuff sessions
    concurrently with elastic join/leave and rolling checkpoints; this
    entry point keeps the classic run-to-completion semantics (and the
    process-global telemetry) intact."""
    from fedml_tpu.serve.session import FedSession

    return FedSession(
        config,
        data,
        model,
        algorithm="fedbuff",
        comm_factory=comm_factory,
        task=task,
        log_fn=log_fn,
    ).run()


def run_fedbuff_loopback(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
):
    hub = LoopbackHub()
    return run_fedbuff_federation(
        config, data, model, lambda rank: LoopbackCommManager(hub, rank),
        task=task, log_fn=log_fn,
    )


def run_fedbuff_shm(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    sock_dir: Optional[str] = None,
    namespace: str = "",
):
    """Async federation over the shared-memory local transport (the
    TRPC-slot backend, core/shm_comm.py) — the protocol is comm-agnostic,
    so the runner only swaps the factory. ``namespace`` disambiguates
    socket names when concurrent federations share an explicit
    ``sock_dir`` (see ShmCommManager)."""
    import tempfile

    from fedml_tpu.core.shm_comm import ShmCommManager

    def run(d):
        return run_fedbuff_federation(
            config, data, model,
            lambda rank: ShmCommManager(rank, d, namespace=namespace),
            task=task, log_fn=log_fn,
        )

    if sock_dir is not None:
        return run(sock_dir)
    with tempfile.TemporaryDirectory(prefix="fedml_shm_async_") as d:
        return run(d)


def run_fedbuff_mqtt(
    config: RunConfig,
    data: FederatedDataset,
    model: ModelDef,
    task: str = "classification",
    log_fn=None,
    host: Optional[str] = None,
    port: int = 1883,
):
    """Async federation over MQTT pub/sub (embedded in-process broker by
    default, real TCP broker when ``host`` is given)."""
    from fedml_tpu.core.mqtt_comm import EmbeddedBroker, MqttCommManager

    if host is None:
        broker = EmbeddedBroker()
        factory = lambda rank: MqttCommManager(rank, broker=broker)
    else:
        factory = lambda rank: MqttCommManager(rank, host=host, port=port)
    return run_fedbuff_federation(
        config, data, model, factory, task=task, log_fn=log_fn,
    )
