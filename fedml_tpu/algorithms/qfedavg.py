"""q-FedAvg — fair federated aggregation (q-FFL, Li et al., ICLR 2020).

BEYOND the reference's inventory (SURVEY §2b has no fairness-aware
aggregation): plain FedAvg minimizes the average loss, which lets the
server trade a few clients' terrible models for many clients' good ones.
q-FFL reweights toward high-loss clients — minimizing
(1/q+1)·Σ F_k^{q+1} — so accuracy is distributed more uniformly across
the federation. q interpolates from plain FedAvg (q=0) toward minimax
fairness (q→∞).

The q-FedAvg update (paper's Algorithm 2, public; implemented fresh):

    g_k   = (w_t - w_k) / lr                 (the client's effective grad)
    Delta_k = F_k^q * g_k
    h_k   = q * F_k^{q-1} * ||g_k||^2 + F_k^q / lr
    w_{t+1} = w_t - (sum_k Delta_k) / (sum_k h_k)

where F_k is client k's TRAINING loss at the broadcast model w_t —
computed EXACTLY here: one forward pass over the client's shard at w_t
inside the jitted round, before local training (an earlier draft used
the mean loss over the whole local trajectory, which systematically
down-weights fast-learning clients; the paper's weights are defined at
w_t). At q=0 this reduces EXACTLY to the uniform mean of the
client models: Delta_k = g_k, h_k = 1/lr, so
w - lr/K * sum (w - w_k)/lr... = mean_k w_k — the degenerate-config
oracle tests/test_qfedavg.py pins.

TPU shape: the whole update is one jitted round — the F_k forward pass
rides the same lifted client schedule as the local trains (fused by XLA
into the round program); no host round-trip.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import (
    FedAvgAPI,
    client_axis_map,
    make_fedavg_round_body,
    resolve_client_parallelism,
)
from fedml_tpu.config import RunConfig
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import make_task_loss


def qfedavg_update(global_vars, client_vars, losses, lr: float, q: float):
    """One q-FedAvg server step from the stacked client results.

    ``client_vars``: [K, ...] stacked trees; ``losses``: [K] mean training
    loss per client. Pure — oracle-testable."""
    eps = 1e-10
    L = jnp.maximum(jnp.asarray(losses, jnp.float32), eps)
    deltas = jax.tree_util.tree_map(
        lambda g, cv: (
            g.astype(jnp.float32)[None] - cv.astype(jnp.float32)
        ) / lr,
        global_vars, client_vars,
    )
    # ||g_k||^2 over the full tree
    gsq = sum(
        jnp.sum(jnp.square(d), axis=tuple(range(1, d.ndim)))
        for d in jax.tree_util.tree_leaves(deltas)
    )  # [K]
    Lq = L**q
    h = q * (L ** (q - 1)) * gsq + Lq / lr  # [K]
    hsum = jnp.sum(h)

    def upd(g, d):
        num = jnp.tensordot(Lq, d, axes=1)  # sum_k F_k^q g_k
        return (g.astype(jnp.float32) - num / hsum).astype(g.dtype)

    return jax.tree_util.tree_map(upd, global_vars, deltas)


def make_qfedavg_round(
    model: ModelDef,
    config: RunConfig,
    q: float,
    task: str = "classification",
    client_mode: Optional[str] = None,
    donate: bool = True,
    local_train_fn=None,
):
    """Jitted q-FedAvg round: the plain round's lifted local trains, with
    the weighted average replaced by the q-FFL update driven by each
    client's mean training loss. Same signature as the FedAvg round fn."""
    body = make_fedavg_round_body(
        model, config, task=task, client_mode=client_mode,
        local_train_fn=local_train_fn,
    )
    lr = config.train.lr
    mode = client_mode or resolve_client_parallelism(
        config.fed.client_parallelism, model
    )
    task_loss = make_task_loss(task)

    def broadcast_loss(gv, xc, yc, mc):
        """Mean training loss of ONE client's shard at the broadcast model
        — the F_k(w_t) that q-FFL's weights are defined on."""

        def step(carry, inp):
            xb, yb, mb = inp
            logits, _ = model.apply(gv, xb, train=False)
            loss, _, total = task_loss(logits, yb, mb)
            return carry + jnp.stack([loss * total, total]), None

        sums, _ = jax.lax.scan(step, jnp.zeros(2), (xc, yc, mc))
        return sums[0] / jnp.maximum(sums[1], 1.0)

    lifted_loss = client_axis_map(broadcast_loss, mode)

    def round_fn(global_vars, x, y, mask, num_samples, client_rngs):
        # F_k at w_t BEFORE local training (XLA may still schedule both
        # passes together — no data dependence forces an ordering)
        losses = lifted_loss(global_vars, x, y, mask)
        _, (client_vars, metrics) = body(
            global_vars, x, y, mask, num_samples, client_rngs
        )
        new_global = qfedavg_update(global_vars, client_vars, losses, lr, q)
        return new_global, jax.tree_util.tree_map(jnp.sum, metrics)

    # program dedup (fedml_tpu/compile/): q and lr are baked into the
    # traced update as program CONSTANTS, so both must determine the
    # digest (q explicitly; lr rides in config.train) — the scaffold
    # server-constant lesson
    from fedml_tpu.compile import get_program_cache, model_fingerprint

    cache = get_program_cache()
    builder = lambda: jax.jit(round_fn, donate_argnums=(0,) if donate else ())
    if local_train_fn is not None:
        return cache.wrap_uncached("qfedavg_round", builder())
    return cache.get_or_build(
        "qfedavg_round",
        {
            "kind": "qfedavg_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "mode": mode,
            "q": float(q),
            "donate": donate,
        },
        builder,
    )


class QFedAvgAPI(FedAvgAPI):
    """q-FedAvg simulator on the FedAvg skeleton."""

    _supports_fused = False  # bespoke aggregation, no chunked round fn

    def __init__(self, config, data, model, q: float = 1.0, **kw):
        if config.train.client_optimizer != "sgd" or config.train.momentum:
            raise ValueError(
                "q-FedAvg's h_k normalizer is defined on plain-SGD local "
                "steps (the paper's L-estimate 1/lr) — got "
                f"{config.train.client_optimizer!r}, "
                f"momentum={config.train.momentum}"
            )
        self.q = float(q)
        super().__init__(config, data, model, **kw)

    def _build_round_fn(self, local_train_fn):
        return make_qfedavg_round(
            self.model, self.config, self.q, task=self.task,
            client_mode=self._client_mode, donate=self._donate,
            local_train_fn=local_train_fn,
        )
