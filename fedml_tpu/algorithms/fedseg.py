"""FedSeg — federated semantic segmentation (ref: fedml_api/distributed/
fedseg/{FedSegAggregator.py:10-41 per-client mIoU tracking,
MyModelTrainer.py:95-128 eval, utils.py:161-197 Saver, :239+ Evaluator}).

FedAvg over an encoder-decoder with the per-pixel ignore-index CE task
("segmentation" in train/client.py) plus confusion-matrix mIoU/FWIoU
evaluation and best-mIoU checkpoint promotion (the Saver's contract)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.utils.checkpoint import save_checkpoint
from fedml_tpu.utils.seg_metrics import Evaluator


class FedSegAPI(FedAvgAPI):
    _supports_fused = False  # custom round bodies forbid chunk fusion
    def __init__(self, config, data, model, checkpoint_path: Optional[str] = None, **kw):
        kw.setdefault("task", "segmentation")
        super().__init__(config, data, model, **kw)
        self.checkpoint_path = checkpoint_path
        self.best_miou = -1.0
        self._predict = jax.jit(  # fedlint: disable=uncached-jit -- per-API-instance argmax-predict closure over self.model; eval-only long-tail path
            lambda v, x: jnp.argmax(self.model.apply(v, x, train=False)[0], -1)
        )

    def evaluate_seg(self, batch_size: int = 16) -> dict:
        """mIoU/FWIoU/pixel-acc on the global test set (ref Evaluator usage,
        MyModelTrainer.py:95-128)."""
        ev = Evaluator(self.data.num_classes)
        x, y = self.data.test_x, self.data.test_y
        for s in range(0, len(y), batch_size):
            pred = self._predict(self.global_vars, jnp.asarray(x[s : s + batch_size]))
            ev.add_batch(np.asarray(y[s : s + batch_size]), np.asarray(pred))
        return {
            "Test/mIoU": ev.Mean_Intersection_over_Union(),
            "Test/FWIoU": ev.Frequency_Weighted_Intersection_over_Union(),
            "Test/Acc": ev.Pixel_Accuracy(),
            "Test/Acc_class": ev.Pixel_Accuracy_Class(),
        }

    def train(self):
        cfg = self.config
        final = {}
        for round_idx in range(cfg.fed.comm_round):
            _, metrics = self.train_round(round_idx)
            count = float(metrics["count"])
            row = {
                "round": round_idx,
                "Train/Loss": float(metrics["loss_sum"]) / max(count, 1e-9),
                "Train/Acc": float(metrics["correct"]) / max(count, 1e-9),
            }
            if (
                round_idx % cfg.fed.frequency_of_the_test == 0
                or round_idx == cfg.fed.comm_round - 1
            ):
                row.update(self.evaluate_seg())
                # best-mIoU promotion (ref Saver.save_checkpoint,
                # fedseg/utils.py:161-197)
                if self.checkpoint_path and row["Test/mIoU"] > self.best_miou:
                    self.best_miou = row["Test/mIoU"]
                    save_checkpoint(
                        self.checkpoint_path,
                        self.global_vars,
                        round_idx=round_idx,
                        extra_meta={"best_miou": self.best_miou},
                    )
            self.history.append(row)
            self.log_fn(row)
            final = row
        return final
