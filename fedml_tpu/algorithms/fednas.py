"""FedNAS — federated neural architecture search over the DARTS space (ref:
fedml_api/distributed/fednas/{FedNASAggregator.py:56-114 separate weight/α
averaging + per-round genotype record :173+, FedNASTrainer.py:34-128
search/local_search}; second-order architect at
model/cv/darts/architect.py:32-44).

Each client alternates (a) architecture steps — ∇α L_val — and (b) weight
steps — ∇w L_train — on its local split; the server sample-weight-averages w
and α separately and records the derived genotype per round.

``arch_grad`` selects the architect:

- ``"first"`` — first-order DARTS (the reference's default path): ∇α of the
  validation loss at the current weights.
- ``"second"`` — the unrolled architect (ref architect.py:32-44
  `_compute_unrolled_model`): ∇α L_val(w − ξ·∇w L_train(w, α), α). JAX
  differentiates *through* the inner step (grad-of-grad) — no
  finite-difference Hessian-vector product (the reference's
  `_hessian_vector_product`). The unrolled virtual step here is plain
  SGD (no momentum/wd), a standard simplification: the α-gradient is
  exact for THAT virtual step, while the reference unrolls its
  momentum+wd update and then approximates the HVP by finite
  differences — two different approximations of the same second-order
  objective."""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.algorithms.fedavg import weighted_average
from fedml_tpu.scheduler import select_clients
from fedml_tpu.models.darts import DARTSNetwork, derive_genotype


def _split_arch(params):
    arch = {k: v for k, v in params.items() if k.startswith("alpha_")}
    weights = {k: v for k, v in params.items() if not k.startswith("alpha_")}
    return arch, weights


class FedNASAPI:
    def __init__(
        self,
        data,
        num_classes: int,
        input_shape,
        ch: int = 8,
        cells: int = 2,
        steps: int = 2,
        w_lr: float = 0.025,
        arch_lr: float = 3e-3,
        batch_size: int = 16,
        seed: int = 0,
        arch_grad: str = "first",
        xi: float = None,
    ):
        if arch_grad not in ("first", "second"):
            raise ValueError(f"arch_grad must be 'first' or 'second', got {arch_grad!r}")
        self.arch_grad = arch_grad
        self.xi = w_lr if xi is None else xi  # unrolled inner-step lr (ref architect.py:34)
        self.data = data
        self.net = DARTSNetwork(
            num_classes=num_classes, ch=ch, cells=cells, steps=steps
        )
        self.steps = steps
        rng = jax.random.PRNGKey(seed)
        dummy = jnp.zeros((1,) + tuple(input_shape))
        self.variables = self.net.init({"params": rng}, dummy, train=False)
        self.w_opt = optax.sgd(w_lr, momentum=0.9)
        self.arch_opt = optax.adam(arch_lr, b1=0.5, b2=0.999)
        self.batch_size = batch_size
        self.genotype_history: List = []
        self._train_step = jax.jit(self._make_step(update_arch=False))  # fedlint: disable=uncached-jit -- per-API-instance DARTS step over opaque self state; long-tail driver outside the warmup/dedup path
        self._arch_step = jax.jit(  # fedlint: disable=uncached-jit -- per-API-instance DARTS arch step over opaque self state; long-tail driver outside the warmup/dedup path
            self._make_second_order_arch_step()
            if arch_grad == "second"
            else self._make_step(update_arch=True)
        )

    def _make_step(self, update_arch: bool):
        net = self.net
        opt = self.arch_opt if update_arch else self.w_opt

        def loss_fn(target_params, other_params, bs, x, y):
            if update_arch:
                params = {**other_params, **target_params}
            else:
                params = {**target_params, **other_params}
            variables = {"params": params}
            if bs:
                variables["batch_stats"] = bs
            logits, mut = net.apply(
                variables, x, train=True, mutable=["batch_stats"]
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
            return loss, mut.get("batch_stats", {})

        def step(variables, opt_state, x, y):
            arch, weights = _split_arch(variables["params"])
            target, other = (arch, weights) if update_arch else (weights, arch)
            (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                target, other, variables.get("batch_stats", {}), x, y
            )
            updates, opt_state = opt.update(grads, opt_state, target)
            target = optax.apply_updates(target, updates)
            params = {**other, **target}
            out = {"params": params}
            if new_bs:
                out["batch_stats"] = new_bs
            return out, opt_state, loss

        return step

    def _make_second_order_arch_step(self):
        """Unrolled architect (ref architect.py:32-44): α-gradient of the
        validation loss at w' = w − ξ·∇w L_train(w, α), differentiated
        through the inner step by autodiff (no finite-difference HVP). The
        virtual step is plain SGD — see the module docstring for how this
        approximation relates to the reference's. BN stats are read, not
        mutated, inside the unrolled evaluation (weight steps own the
        running stats)."""
        net, opt, xi = self.net, self.arch_opt, self.xi

        def raw_loss(arch, weights, bs, x, y):
            variables = {"params": {**weights, **arch}}
            if bs:
                variables["batch_stats"] = bs
            logits, _ = net.apply(variables, x, train=True, mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        def step(variables, opt_state, xv, yv, xt, yt):
            arch, weights = _split_arch(variables["params"])
            bs = variables.get("batch_stats", {})

            def unrolled_val_loss(arch_p):
                g_w = jax.grad(raw_loss, argnums=1)(arch_p, weights, bs, xt, yt)
                w2 = jax.tree_util.tree_map(
                    lambda w, g: w - xi * g, weights, g_w
                )
                return raw_loss(arch_p, w2, bs, xv, yv)

            loss, grads = jax.value_and_grad(unrolled_val_loss)(arch)
            updates, opt_state = opt.update(grads, opt_state, arch)
            arch = optax.apply_updates(arch, updates)
            out = {"params": {**weights, **arch}}
            if bs:
                out["batch_stats"] = bs
            return out, opt_state, loss

        return step

    def _local_search(self, variables, x, y, epochs: int):
        """ref FedNASTrainer.search: per epoch, arch step on val half +
        weight steps on train half."""
        n = len(y)
        half = n // 2
        xt, yt = x[:half], y[:half]
        xv, yv = x[half:], y[half:]
        arch, weights = _split_arch(variables["params"])
        w_os = self.w_opt.init(weights)
        a_os = self.arch_opt.init(arch)
        B = self.batch_size
        loss = jnp.zeros(())
        for _ in range(epochs):
            for s in range(0, max(len(yv) - B + 1, 1), B):
                if self.arch_grad == "second":
                    t = s % max(len(yt) - B + 1, 1)
                    variables, a_os, _ = self._arch_step(
                        variables,
                        a_os,
                        jnp.asarray(xv[s : s + B]),
                        jnp.asarray(yv[s : s + B]),
                        jnp.asarray(xt[t : t + B]),
                        jnp.asarray(yt[t : t + B]),
                    )
                else:
                    variables, a_os, _ = self._arch_step(
                        variables, a_os, jnp.asarray(xv[s : s + B]), jnp.asarray(yv[s : s + B])
                    )
            for s in range(0, max(len(yt) - B + 1, 1), B):
                variables, w_os, loss = self._train_step(
                    variables, w_os, jnp.asarray(xt[s : s + B]), jnp.asarray(yt[s : s + B])
                )
        return variables, float(loss)

    def train_round(self, round_idx: int, client_num_per_round: int, epochs: int = 1):
        sampled = select_clients(
            round_idx, self.data.num_clients, client_num_per_round
        )
        locals_, weights_n = [], []
        for ci in sampled:
            v, _ = self._local_search(
                self.variables, self.data.client_x[ci], self.data.client_y[ci], epochs
            )
            locals_.append(v)
            weights_n.append(len(self.data.client_y[ci]))
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *locals_
        )
        # weight + α averaged with the same sample weights, but kept as the
        # two logical groups of the reference's aggregator (they are separate
        # subtrees of params here, so one weighted_average covers both).
        self.variables = weighted_average(
            stacked, jnp.asarray(weights_n, jnp.float32)
        )
        geno = derive_genotype(
            self.variables["params"]["alpha_normal"], steps=self.steps
        )
        self.genotype_history.append((round_idx, geno))
        return geno

    def evaluate(self, x, y, batch_size: int = 64) -> float:
        correct = 0
        for s in range(0, len(y), batch_size):
            logits = self.net.apply(
                self.variables, jnp.asarray(x[s : s + batch_size]), train=False
            )
            correct += int(jnp.sum(jnp.argmax(logits, -1) == jnp.asarray(y[s : s + batch_size])))
        return correct / len(y)
