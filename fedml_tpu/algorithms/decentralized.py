"""Decentralized online learning — DSGD + Push-Sum gossip over a topology
(ref: fedml_api/standalone/decentralized/{decentralized_fl_api.py:20-99,
client_dsgd.py:6-102, client_pushsum.py:7-129}; regret metric at
decentralized_fl_api.py:11-17).

The reference steps every worker through a Python loop per iteration —
train one streaming sample, exchange weights with topology neighbors via
dicts. Here all N workers are a stacked leading axis and the whole run is
ONE `lax.scan`: per iteration a vmapped SGD step then the mixing step
``params ← W @ params`` (the row-stochastic confusion matrix of
partition/topology.py applied with einsum — gossip as a matmul on the MXU).
Push-Sum additionally carries the ω weights (client_pushsum.py:38-45):
x ← Wᵀx, ω ← Wᵀω, estimate z = x/ω. Push-Sum's debiasing requires a
*column-stochastic* mixing matrix (mass is pushed out along out-edges and
must be conserved); topology managers produce row-stochastic W, so the
pushsum variant mixes with Wᵀ — correct averaging on the asymmetric
(directed) topologies where plain DSGD mixing is biased."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.models import ModelDef


def _binary_loss(model: ModelDef):
    def loss_fn(params, x, y):
        logits, _ = model.apply({"params": params}, x, train=True)
        logit = logits.reshape(-1)[:1]
        return optax.sigmoid_binary_cross_entropy(logit, y.reshape(-1)[:1]).mean()

    return loss_fn


def make_decentralized_run(
    model: ModelDef,
    mixing_matrix: np.ndarray,
    lr: float,
    wd: float = 0.0,
    variant: str = "dsgd",
    loss_fn: Optional[Callable] = None,
):
    """Build ``run(stacked_params, x, y) -> (final_params, per_iter_loss)``.

    x: [N, T, *feat] streaming samples (worker-major), y: [N, T] (binary
    targets, ref BCELoss on logistic regression). variant: "dsgd" | "pushsum".
    """
    if variant not in ("dsgd", "pushsum"):
        raise ValueError(f"variant must be 'dsgd' or 'pushsum', got {variant!r}")
    W = jnp.asarray(mixing_matrix, jnp.float32)
    if variant == "pushsum":
        # Row-stochastic W does not conserve Σx under mixing; Push-Sum's
        # x/ω debias is only unbiased with column-stochastic mixing, so
        # push along the transpose (each worker splits its mass over
        # out-neighbors).
        W = W.T
    N = W.shape[0]
    loss_fn = loss_fn or _binary_loss(model)
    grad_fn = jax.value_and_grad(loss_fn)

    def mix(tree):
        return jax.tree_util.tree_map(
            lambda p: jnp.einsum("ij,j...->i...", W, p), tree
        )

    def run(stacked_params, x, y):
        T = x.shape[1]

        def step(carry, t):
            params, omega = carry
            losses, grads = jax.vmap(grad_fn)(
                params, x[:, t][:, None], y[:, t][:, None]
            )
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * (g + wd * p), params, grads
            )
            params = mix(params)
            if variant == "pushsum":
                omega = W @ omega
            return (params, omega), jnp.mean(losses)

        omega0 = jnp.ones((N,), jnp.float32)
        (params, omega), losses = jax.lax.scan(
            step, (stacked_params, omega0), jnp.arange(T)
        )
        if variant == "pushsum":
            params = jax.tree_util.tree_map(
                lambda p: p / omega.reshape((N,) + (1,) * (p.ndim - 1)), params
            )
        return params, losses

    # the concrete mixing matrix W (and the loss_fn hook) are closed over
    # — an opaque program identity, so bypass the digest registry but keep
    # the ProgramCache accounting/warmup surface (fedlint uncached-jit)
    from fedml_tpu.compile import get_program_cache

    return get_program_cache().wrap_uncached("decentralized_run", jax.jit(run))


class DecentralizedAPI:
    """Driver (ref FedML_decentralized_fl, decentralized_fl_api.py:20-99):
    builds stacked worker params, runs the scan, reports regret = running
    mean of per-iteration losses."""

    def __init__(
        self,
        model: ModelDef,
        topology,
        lr: float = 0.1,
        wd: float = 0.0,
        variant: str = "dsgd",
        seed: int = 0,
    ):
        self.model = model
        self.topology = topology
        self.variant = variant
        N = topology.topology.shape[0]
        keys = jax.random.split(jax.random.PRNGKey(seed), N)
        self.params = jax.vmap(lambda k: model.init(k)["params"])(keys)
        self.run_fn = make_decentralized_run(
            model, topology.topology, lr, wd, variant
        )

    def run(self, x: np.ndarray, y: np.ndarray):
        self.params, losses = self.run_fn(
            self.params, jnp.asarray(x), jnp.asarray(y, jnp.float32)
        )
        losses = np.asarray(losses)
        regret = np.cumsum(losses) / (np.arange(len(losses)) + 1)
        return {"losses": losses, "regret": regret}
