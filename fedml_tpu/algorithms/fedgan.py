"""FedGAN — FedAvg over a generator+discriminator pair (ref:
fedml_api/distributed/fedgan/{FedGanAPI.py, FedGANAggregator.py:15-112} with
the MNISTGan model, model/cv/mnistgan.py).

The aggregator is plain sample-weighted FedAvg over the COMBINED G+D state
(the reference averages the whole MNISTGan state dict); only the local
training differs — per batch: a discriminator step (BCE real=1/fake=0) then
a generator step (BCE fake=1), the standard alternating GAN update. The
local loop is a lax.scan like every other local trainer, so the GAN variant
vmaps over clients and shard_maps over the mesh unchanged."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.models import ModelDef
from fedml_tpu.models.gan import Discriminator, Generator


def make_gan_model_def(z_dim: int = 100) -> ModelDef:
    """ModelDef-shaped container for init only; apply() is unused (GAN local
    training needs the two-step update below, not a single forward)."""
    import dataclasses

    class _GanDef(ModelDef):
        def init(self, rng):
            g = Generator()
            d = Discriminator()
            k1, k2 = jax.random.split(rng)
            gv = g.init({"params": k1}, jnp.zeros((1, z_dim)), train=False)
            dv = d.init({"params": k2}, jnp.zeros((1, 28, 28, 1)), train=False)
            variables = {
                "params": {"netg": gv["params"], "netd": dv["params"]},
            }
            bs = {}
            if "batch_stats" in gv:
                bs["netg"] = gv["batch_stats"]
            if "batch_stats" in dv:
                bs["netd"] = dv["batch_stats"]
            if bs:
                variables["batch_stats"] = bs
            return variables

    return _GanDef(
        module=None,
        input_shape=(28, 28, 1),
        num_classes=1,
        has_batch_stats=True,
        name="mnistgan",
    )


def make_gan_local_train(train_config, epochs: int, z_dim: int = 100):
    """Local GAN trainer with the (variables, x, y, mask, rng) signature the
    FedAvg round skeleton expects; y is ignored (unsupervised)."""
    g = Generator()
    d = Discriminator()
    g_opt = optax.adam(train_config.lr, b1=0.5)
    d_opt = optax.adam(train_config.lr, b1=0.5)

    def apply_g(params, bs, z, train):
        variables = {"params": params}
        if bs is not None:
            variables["batch_stats"] = bs
        if train:
            out, mut = g.apply(variables, z, train=True, mutable=["batch_stats"])
            return out, mut["batch_stats"]
        return g.apply(variables, z, train=False), bs

    def d_logits(params, x):
        return d.apply({"params": params}, x, train=False)

    def local_train(variables, x, y, mask, rng):
        del y
        params0 = variables["params"]
        g_bs0 = variables.get("batch_stats", {}).get("netg")
        S, B = mask.shape

        def step(carry, inp):
            (gp, dp, g_bs, g_os, d_os) = carry
            xb, mb, sidx = inp
            step_rng = jax.random.fold_in(rng, sidx)
            z = jax.random.normal(step_rng, (B, z_dim))
            m = mb[:, None]

            # --- D step: real→1, fake(detached)→0
            def d_loss_fn(dparams):
                fake, _ = apply_g(gp, g_bs, z, True)
                lr_real = optax.sigmoid_binary_cross_entropy(
                    d_logits(dparams, xb), jnp.ones((B, 1))
                )
                lr_fake = optax.sigmoid_binary_cross_entropy(
                    d_logits(dparams, jax.lax.stop_gradient(fake)), jnp.zeros((B, 1))
                )
                return jnp.sum((lr_real + lr_fake) * m) / jnp.maximum(jnp.sum(m), 1e-9)

            d_l, d_grads = jax.value_and_grad(d_loss_fn)(dp)
            d_updates, d_os_new = d_opt.update(d_grads, d_os, dp)
            dp_new = optax.apply_updates(dp, d_updates)

            # --- G step: fake→1
            def g_loss_fn(gparams):
                fake, new_bs = apply_g(gparams, g_bs, z, True)
                lg = optax.sigmoid_binary_cross_entropy(
                    d_logits(dp_new, fake), jnp.ones((B, 1))
                )
                return jnp.sum(lg * m) / jnp.maximum(jnp.sum(m), 1e-9), new_bs

            (g_l, new_g_bs), g_grads = jax.value_and_grad(g_loss_fn, has_aux=True)(gp)
            g_updates, g_os_new = g_opt.update(g_grads, g_os, gp)
            gp_new = optax.apply_updates(gp, g_updates)

            has_data = jnp.sum(mb) > 0
            keep = lambda n, o: jax.tree_util.tree_map(
                lambda a, b: jnp.where(has_data, a, b), n, o
            )
            carry = (
                keep(gp_new, gp),
                keep(dp_new, dp),
                keep(new_g_bs, g_bs) if g_bs is not None else g_bs,
                keep(g_os_new, g_os),
                keep(d_os_new, d_os),
            )
            mets = jnp.stack([g_l * jnp.sum(mb), d_l * jnp.sum(mb), jnp.sum(mb)])
            return carry, mets

        def epoch(carry, _e):
            carry, mets = jax.lax.scan(step, carry, (x, mask, jnp.arange(S)))
            return carry, mets.sum(axis=0)

        g_os = g_opt.init(params0["netg"])
        d_os = d_opt.init(params0["netd"])
        carry = (params0["netg"], params0["netd"], g_bs0, g_os, d_os)
        carry, mets = jax.lax.scan(epoch, carry, jnp.arange(epochs))
        mets = mets.sum(axis=0)
        gp, dp, g_bs, _, _ = carry
        out = {"params": {"netg": gp, "netd": dp}}
        if g_bs is not None:
            out["batch_stats"] = {"netg": g_bs}
        metrics = {
            "loss_sum": mets[0],  # generator loss (weighted)
            "correct": mets[1],  # discriminator loss (weighted) — see train()
            "count": mets[2],
            "steps": jnp.zeros(()),
        }
        return out, metrics

    return local_train


class FedGANAPI(FedAvgAPI):
    _supports_fused = False  # custom round bodies forbid chunk fusion
    """FedAvg round skeleton with the GAN local trainer (ref FedGanAPI.py)."""

    def __init__(self, config, data, model=None, z_dim: int = 100, **kw):
        model = model or make_gan_model_def(z_dim)
        kw["local_train_fn"] = make_gan_local_train(
            config.train, config.fed.epochs, z_dim
        )
        super().__init__(config, data, model, **kw)
        self.z_dim = z_dim

    def train(self):
        final = {}
        for round_idx in range(self.config.fed.comm_round):
            _, metrics = self.train_round(round_idx)
            count = float(metrics["count"])
            row = {
                "round": round_idx,
                "Train/G_Loss": float(metrics["loss_sum"]) / max(count, 1e-9),
                "Train/D_Loss": float(metrics["correct"]) / max(count, 1e-9),
            }
            self.history.append(row)
            self.log_fn(row)
            final = row
        return final

    def generate(self, n: int, seed: int = 0):
        g = Generator()
        variables = {"params": self.global_vars["params"]["netg"]}
        if "batch_stats" in self.global_vars and "netg" in self.global_vars["batch_stats"]:
            variables["batch_stats"] = self.global_vars["batch_stats"]["netg"]
        z = jax.random.normal(jax.random.PRNGKey(seed), (n, self.z_dim))
        return g.apply(variables, z, train=False)
