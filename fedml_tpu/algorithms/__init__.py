from fedml_tpu.algorithms.fedavg import (
    FedAvgAPI,
    client_sampling,
    make_fedavg_round,
    weighted_average,
)
from fedml_tpu.algorithms.fedopt import FedOptAPI, make_server_optimizer
from fedml_tpu.algorithms.fednova import FedNovaAPI, make_fednova_round
from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgAPI, assign_groups

__all__ = [
    "FedAvgAPI",
    "FedOptAPI",
    "FedNovaAPI",
    "HierarchicalFedAvgAPI",
    "assign_groups",
    "client_sampling",
    "make_fedavg_round",
    "make_fednova_round",
    "make_server_optimizer",
    "weighted_average",
]
