from fedml_tpu.algorithms.fedavg import (
    FedAvgAPI,
    client_sampling,
    make_fedavg_round,
    weighted_average,
)
from fedml_tpu.algorithms.fedopt import FedOptAPI, make_server_optimizer
from fedml_tpu.algorithms.fednova import FedNovaAPI, make_fednova_round
from fedml_tpu.algorithms.scaffold import ScaffoldAPI, make_scaffold_round
from fedml_tpu.algorithms.hierarchical import HierarchicalFedAvgAPI, assign_groups

# Heavier algorithm modules import lazily from their own namespaces:
#   fedml_tpu.algorithms.fedavg_robust    RobustFedAvgAPI
#   fedml_tpu.algorithms.fedavg_transport run_loopback_federation, managers
#   fedml_tpu.algorithms.decentralized    DecentralizedAPI (DSGD/PushSum)
#   fedml_tpu.algorithms.split_nn         SplitNNAPI
#   fedml_tpu.algorithms.vertical_fl      VFLAPI
#   fedml_tpu.algorithms.fedgkt           FedGKTAPI
#   fedml_tpu.algorithms.fedgan           FedGANAPI
#   fedml_tpu.algorithms.fedseg           FedSegAPI
#   fedml_tpu.algorithms.fednas           FedNASAPI
#   fedml_tpu.algorithms.base_framework   templates

__all__ = [
    "FedAvgAPI",
    "FedOptAPI",
    "FedNovaAPI",
    "ScaffoldAPI",
    "make_scaffold_round",
    "HierarchicalFedAvgAPI",
    "assign_groups",
    "client_sampling",
    "make_fedavg_round",
    "make_fednova_round",
    "make_server_optimizer",
    "weighted_average",
]
