from fedml_tpu.algorithms.fedavg import (
    FedAvgAPI,
    client_sampling,
    make_fedavg_round,
    weighted_average,
)

__all__ = ["FedAvgAPI", "client_sampling", "make_fedavg_round", "weighted_average"]
