"""Classical vertical (feature-partitioned) FL — guest holds the labels,
hosts hold disjoint feature columns (ref: fedml_api/distributed/
classical_vertical_fl/{vfl_api.py:16-44, guest_trainer.py:73-126,
host_trainer.py:43-78} and the standalone party sim, standalone/
classical_vertical_fl/{vfl.py, party_models.py}).

Protocol per batch (ref guest_trainer.train): each host computes
h_k = dense(extractor_k(x_k)) and uploads the logit contribution; the guest
sums contributions with its own, computes the loss, and returns ∂L/∂h_k to
every host, which backprops through its local stack. The reference hand-rolls
this split backward with torch autograd fragments and embedded numpy shims;
here the ENTIRE multi-party step is one jit'd function — jax.grad through
the sum of party contributions IS the split backward, and the host/guest
message boundary is recovered for the transport path by cutting the vjp at
the logit-sum (the math is identical, verified by test)."""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.models.vfl import VFLClassifier, VFLFeatureExtractor


class VFLParty:
    """One party's feature slice + local models (ref party_models.py:
    VFLGuestModel/VFLHostModel)."""

    def __init__(self, feature_dim: int, hidden_dim: int, out_dim: int, rng, has_labels=False):
        self.extractor = VFLFeatureExtractor(output_dim=hidden_dim)
        self.dense = VFLClassifier(output_dim=out_dim, use_bias=has_labels)
        k1, k2 = jax.random.split(rng)
        dummy = jnp.zeros((1, feature_dim))
        self.params = {
            "extractor": self.extractor.init(k1, dummy),
            "dense": self.dense.init(
                k2, jnp.zeros((1, hidden_dim))
            ),
        }
        self.has_labels = has_labels

    def contribution(self, params, x):
        feats = self.extractor.apply(params["extractor"], x)
        return self.dense.apply(params["dense"], feats)


class VFLAPI:
    """Federation of one guest (labels) + K hosts (ref VflFixture /
    FedML_VFL_distributed). All parties' params live in one list so the
    jitted train step updates everyone at once."""

    def __init__(
        self,
        feature_splits: Sequence[int],
        hidden_dim: int = 16,
        out_dim: int = 1,
        lr: float = 0.05,
        seed: int = 0,
    ):
        from fedml_tpu.splitfed.programs import make_vfl_fused_step

        rngs = jax.random.split(jax.random.PRNGKey(seed), len(feature_splits))
        self.parties: List[VFLParty] = [
            VFLParty(d, hidden_dim, out_dim, rngs[i], has_labels=(i == 0))
            for i, d in enumerate(feature_splits)
        ]
        self.feature_splits = tuple(int(d) for d in feature_splits)
        self.hidden_dim = int(hidden_dim)
        self.out_dim = int(out_dim)
        self.opt = optax.sgd(lr, momentum=0.9)
        self.params = [p.params for p in self.parties]
        self.opt_state = self.opt.init(self.params)
        # the fused multi-party step is a digested ProgramCache factory keyed
        # on the feature split + module dims + optimizer config
        # (fedml_tpu/splitfed/programs.py), shared with the guest/host
        # transport runtime
        self._step = make_vfl_fused_step(
            self.feature_splits, hidden_dim=hidden_dim, out_dim=out_dim, lr=lr
        )

    def train_epoch(self, xs_parties: Sequence[np.ndarray], y: np.ndarray, batch_size: int = 32):
        n = len(y)
        losses, corrects = [], 0
        for s in range(0, n - batch_size + 1, batch_size):
            xs = [jnp.asarray(x[s : s + batch_size]) for x in xs_parties]
            yb = jnp.asarray(y[s : s + batch_size], jnp.float32)
            self.params, self.opt_state, loss, correct = self._step(
                self.params, self.opt_state, xs, yb
            )
            losses.append(float(loss))
            corrects += int(correct)
        seen = (n // batch_size) * batch_size
        return {"loss": float(np.mean(losses)), "acc": corrects / max(seen, 1)}

    def guest_host_split_step(self, xs_parties, y):
        """The explicit message-boundary version (what travels on the wire in
        distributed VFL): hosts send logit contributions forward; guest
        returns ∂L/∂h_k (ref guest_trainer.py:96-126 send gradients to
        hosts). Returns per-host gradients — used to test the fused path."""
        xs = [jnp.asarray(x) for x in xs_parties]
        y = jnp.asarray(y, jnp.float32)
        contribs, vjps = [], []
        for p, pp, x in zip(self.parties, self.params, xs):
            c, vjp = jax.vjp(lambda q: p.contribution(q, x), pp)
            contribs.append(c)
            vjps.append(vjp)

        def guest_loss(all_c):
            logit = sum(all_c).reshape(-1)
            return optax.sigmoid_binary_cross_entropy(logit, y).mean()

        g_contrib = jax.grad(guest_loss)(contribs)
        return [vjp(g)[0] for vjp, g in zip(vjps, g_contrib)]
