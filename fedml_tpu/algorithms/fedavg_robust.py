"""FedAvg-robust — FedAvg with backdoor defenses applied per-client before
averaging (ref: fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py:
173-201; defense math in fedml_core/robustness/robust_aggregation.py).

The defense (norm-diff clipping, then optional weak-DP noise after the
average) runs inside the jitted round: clipping vmaps over the stacked client
axis instead of the reference's per-client Python loop. The poisoned-task
evaluation harness (backdoor accuracy, FedAvgRobustAggregator.py:14-60) pairs
with data/edge_cases.py's poisoned datasets."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.robustness import (
    RobustConfig,
    add_gaussian_noise,
    make_byzantine_aggregate,
    norm_diff_clip_tree,
)


# fold_in tag deriving the weak-DP noise key from the round rng — ONE
# definition, shared by the vmap and mesh APIs (their exact equality is a
# test contract, tests/test_robust_sharded.py)
NOISE_FOLD = 0x5EED


def make_defense_hooks(robust: RobustConfig):
    """defense config → (post_train, post_aggregate, aggregate_fn) — the
    hook triple both round skeletons (vmap make_fedavg_round, mesh
    make_sharded_fedavg_round) accept, so the defense math lives once."""

    def post_train(client_vars, global_vars, noise_rng):
        if robust.defense_type in ("norm_diff_clipping", "weak_dp"):
            return jax.vmap(
                lambda cv: norm_diff_clip_tree(cv, global_vars, robust.norm_bound)
            )(client_vars)
        return client_vars

    def post_aggregate(new_global, noise_rng):
        if robust.defense_type == "weak_dp":
            return add_gaussian_noise(new_global, noise_rng, robust.stddev)
        return new_global

    return post_train, post_aggregate, make_byzantine_aggregate(robust)


def make_robust_fedavg_round(
    model,
    config,
    robust: RobustConfig,
    task: str = "classification",
    local_train_fn=None,
    donate: bool = True,
):
    """The FedAvg round skeleton with the defense inserted via the
    DESCRIBABLE ``robust=`` path (the skeleton itself lives once, in
    make_fedavg_round): the round — including the Byzantine aggregators
    — dedupes through the ProgramCache with the RobustConfig in its
    digest, AOT-warms, and persists through the executable store like
    every other first-class program (it used to bypass via
    ``wrap_uncached`` because the hook closures were opaque)."""
    from fedml_tpu.algorithms.fedavg import make_fedavg_round

    return make_fedavg_round(
        model,
        config,
        task=task,
        local_train_fn=local_train_fn,
        donate=donate,
        robust=robust,
    )


class RobustFedAvgAPI(FedAvgAPI):
    _supports_fused = False  # per-round host-side work forbids chunk fusion
    """FedAvg simulator with robust aggregation."""

    def __init__(self, config, data, model, robust: RobustConfig = RobustConfig(), **kw):
        self.robust = robust
        super().__init__(config, data, model, **kw)

    def _build_round_fn(self, local_train_fn):
        inner = make_robust_fedavg_round(
            self.model,
            self.config,
            self.robust,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
        )
        return inner

    def _place_batch(self, batch, round_rng):
        base = super()._place_batch(batch, round_rng)
        noise_rng = jax.random.fold_in(round_rng, NOISE_FOLD)
        return base + (noise_rng,)
