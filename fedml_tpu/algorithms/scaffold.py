"""SCAFFOLD — stochastic controlled averaging (Karimireddy et al. 2020).

BEYOND the reference's inventory (it ships FedAvg/FedProx/FedOpt/FedNova;
SURVEY §2b) — included because it is the canonical answer to the client
-drift problem the hard-accuracy benchmark demonstrates (bench.py
``hard_accuracy``: FedAvg misses the synthetic(1,1) target that
FedProx/FedOpt reach), and because it exercises the one capability the
other algorithms don't: PERSISTENT per-client state (SURVEY §7 names the
client-state store as a hard part).

Algorithm (Option II of the paper):
  server state: x (params), c (control variate, same tree)
  client i state: c_i (persists across rounds; zero-init)
  local step:   y ← y − lr·(∇f_i(y) + c − c_i)
  after K steps: c_i⁺ = c_i − c + (x − y)/(K·lr)
  server:       x ← x + η_g·mean(Δy_i),  c ← c + (|S|/N)·mean(Δc_i)

TPU-first shape: the per-client control variates live as ONE stacked
pytree of [N, ...] device arrays; a round gathers the sampled rows,
runs the lifted local trains (same vmap/scan client schedules as FedAvg),
and scatters the updated rows back — all inside one jitted round
function, no host round-trips. Memory cost is N × |params|, inherent to
SCAFFOLD (it is why the paper targets cross-silo N); past
FedConfig.state_budget_bytes the stack SPILLS to the disk tier
(state_store.MmapClientState, cohort rows only in HBM — bit-identical
math, tests/test_state_spill.py) instead of refusing.

Restriction: plain-SGD local steps only (the control-variate correction
is defined on the SGD update; momentum/Adam change the fixed point) —
mirrors FedNova's guard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu import _jax_compat

_jax_compat.install()  # jax.shard_map / jax.lax.pcast on older jaxlib

from fedml_tpu.algorithms.fedavg import (
    FedAvgAPI,
    client_axis_map,
    resolve_client_parallelism,
)
from fedml_tpu.config import RunConfig
from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.models import ModelDef
from fedml_tpu.train.client import (
    make_mixed_forward,
    make_task_loss,
    masked_epoch_perm,
)


def make_scaffold_local_train(model: ModelDef, tc, epochs: int, task: str = "classification"):
    """Per-client SCAFFOLD local train:
    ``(variables, c_server, c_i, x, y, mask, rng) ->
      (y_vars, c_i_new, metrics)``
    with x [S, B, *feat]. The correction (c − c_i) is added to every
    gradient step; K (the c_i⁺ normalizer) counts the steps that carried
    data (all-padding steps are where-gated no-ops, as in FedAvg)."""
    if tc.client_optimizer != "sgd" or tc.momentum:
        raise ValueError(
            "SCAFFOLD requires plain-SGD local steps "
            f"(got {tc.client_optimizer!r}, momentum={tc.momentum})"
        )
    if tc.prox_mu:
        raise ValueError("SCAFFOLD with prox_mu is not supported")
    if tc.wd:
        # refusing beats silently training without the flag's effect: the
        # control-variate update is defined on the bare-SGD step
        raise ValueError("SCAFFOLD with weight decay (wd) is not supported")
    fwd = make_mixed_forward(model, tc)
    task_loss = make_task_loss(task)
    lr = tc.lr

    def local_train(variables, c_server, c_i, x, y, mask, rng):
        params0 = variables["params"]
        extra0 = {k: v for k, v in variables.items() if k != "params"}
        S, B = mask.shape[0], mask.shape[1]
        n_flat = S * B
        x_flat = x.reshape((n_flat,) + x.shape[2:])
        y_flat = y.reshape((n_flat,) + y.shape[2:])
        m_flat = mask.reshape((n_flat,))
        correction = jax.tree_util.tree_map(
            lambda cs, ci: cs - ci, c_server, c_i
        )

        def loss_fn(params, extra, xb, yb, mb, step_rng):
            logits, new_extra = fwd(params, extra, xb, step_rng)
            l, correct, total = task_loss(logits, yb, mb)
            return l, (new_extra, l, correct, total)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def epoch_body(carry, epoch_idx):
            params, extra, k_steps = carry
            ep_rng = jax.random.fold_in(rng, epoch_idx)
            perm = masked_epoch_perm(ep_rng, m_flat)
            xe = x_flat[perm].reshape(x.shape)
            ye = y_flat[perm].reshape(y.shape)
            me = m_flat[perm].reshape(mask.shape)

            def step_body(carry, inp):
                params, extra, k_steps = carry
                xb, yb, mb, sidx = inp
                has_data = jnp.sum(mb) > 0
                step_rng = jax.random.fold_in(ep_rng, sidx)
                (_, (new_extra, l, correct, total)), grads = grad_fn(
                    params, extra, xb, yb, mb, step_rng
                )
                new_params = jax.tree_util.tree_map(
                    lambda p, g, corr: p - lr * (g + corr),
                    params, grads, correction,
                )
                keep = lambda new, old: jax.tree_util.tree_map(
                    lambda n, o: jnp.where(has_data, n, o), new, old
                )
                h = has_data.astype(jnp.float32)
                mets = jnp.stack([l * total, correct, total, jnp.float32(1)]) * h
                return (
                    keep(new_params, params),
                    keep(new_extra, extra),
                    k_steps + h,
                ), mets

            (params, extra, k_steps), mets = jax.lax.scan(
                step_body, (params, extra, k_steps),
                (xe, ye, me, jnp.arange(S)),
            )
            return (params, extra, k_steps), mets.sum(axis=0)

        (params, extra, k_steps), mets = jax.lax.scan(
            epoch_body, (params0, extra0, jnp.float32(0)), jnp.arange(epochs)
        )
        mets = mets.sum(axis=0)
        # Option II: c_i⁺ = c_i − c + (x − y)/(K·lr); K = data-carrying steps
        k_safe = jnp.maximum(k_steps, 1.0)
        c_i_new = jax.tree_util.tree_map(
            lambda ci, cs, x0, yk: ci
            - cs
            + (x0.astype(jnp.float32) - yk.astype(jnp.float32))
            / (k_safe * lr),
            c_i, c_server, params0, params,
        )
        # a client with NO data leaves its control variate untouched
        had_data = k_steps > 0
        c_i_new = jax.tree_util.tree_map(
            lambda new, old: jnp.where(had_data, new, old), c_i_new, c_i
        )
        metrics = {
            "loss_sum": mets[0],
            "correct": mets[1],
            "count": mets[2],
            "steps": mets[3],
        }
        return {"params": params, **extra}, c_i_new, metrics

    return local_train


def make_scaffold_round(
    model: ModelDef,
    config: RunConfig,
    task: str = "classification",
    donate: bool = False,
    client_mode: str | None = None,
):
    """Jitted SCAFFOLD round:
    ``(global_vars, c_server, c_stack, idx, x, y, mask, ns, rngs) ->
      (global_vars', c_server', c_stack', agg_metrics)``
    where c_stack is the FULL [N, ...] per-client control-variate store
    (rows gathered/scattered inside the program — only the small index
    vector crosses the host boundary) and ns weights the Δy average as in
    FedAvg."""
    body = _make_scaffold_cohort_body(model, config, task, client_mode)

    def round_fn(global_vars, c_server, c_stack, idx, x, y, mask, num_samples, rngs):
        c_gather = jax.tree_util.tree_map(lambda a: a[idx], c_stack)
        new_global, c_server_new, c_new, agg = body(
            global_vars, c_server, c_gather, x, y, mask, num_samples, rngs
        )
        c_stack_new = jax.tree_util.tree_map(
            lambda stack, new: stack.at[idx].set(new), c_stack, c_new
        )
        return new_global, c_server_new, c_stack_new, agg

    # program dedup (fedml_tpu/compile/): one jitted SCAFFOLD round per
    # (model, train config, epochs, task, schedule) per process
    from fedml_tpu.compile import get_program_cache, model_fingerprint

    return get_program_cache().get_or_build(
        "scaffold_round",
        {
            "kind": "scaffold_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            # client_mode=None resolves inside the body from this config
            # field — both enter the key so "vmap" and "scan" programs
            # can never merge
            "mode": client_mode,
            "parallelism": config.fed.client_parallelism,
            # the cohort body BAKES IN the server lr (η_g) and the /N of
            # the c-server update — they are program constants, not shape
            # classes, and merging across them is wrong numerics
            "server": config.server,
            "n_total": config.fed.client_num_in_total,
            "donate": donate,
        },
        lambda: jax.jit(round_fn, donate_argnums=(2,) if donate else ()),
    )


def _make_scaffold_cohort_body(model, config, task, client_mode):
    """THE cohort-level SCAFFOLD server math — one definition shared by
    the full-stack round (which wraps it with the in-program idx
    gather/scatter) and the spilled cohort round (which jits it bare), so
    the two can never drift and spilled == in-HBM holds by construction
    (tests/test_state_spill.py)."""
    local_train = make_scaffold_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    eta_g = config.server.server_lr  # paper's η_g; ServerConfig default 1.0
    n_total = config.fed.client_num_in_total
    # same client schedules as FedAvg (vmap for small models, sequential
    # scan for conv models whose per-client weights would under-tile the
    # MXU as grouped convs); global_vars and c_server broadcast
    mode = client_mode or resolve_client_parallelism(
        config.fed.client_parallelism, model
    )
    lifted = client_axis_map(local_train, mode, n_broadcast=2)

    def body(global_vars, c_server, c_rows, x, y, mask, num_samples, rngs):
        y_vars, c_new, metrics = lifted(
            global_vars, c_server, c_rows, x, y, mask, rngs
        )

        w = num_samples / jnp.maximum(jnp.sum(num_samples), 1e-9)
        # x ← x + η_g · Σ w_i Δy_i   (params through the control update;
        # non-param collections are plain weighted averages, as in FedAvg)
        def avg_delta(stacked, g):
            return jnp.tensordot(
                w, stacked.astype(jnp.float32) - g.astype(jnp.float32)[None],
                axes=1,
            )

        new_params = jax.tree_util.tree_map(
            lambda g, s: (g.astype(jnp.float32) + eta_g * avg_delta(s, g)).astype(g.dtype),
            global_vars["params"], y_vars["params"],
        )
        new_global = {
            k: (
                new_params
                if k == "params"
                else jax.tree_util.tree_map(
                    lambda s: jnp.tensordot(w, s.astype(jnp.float32), axes=1),
                    v,
                )
            )
            for k, v in y_vars.items()
        }
        # c ← c + (|S|/N) · mean Δc_i  (uniform mean, per the paper).
        # |S| and the mean are derived from the inclusion mask, not the
        # array axis: (|S|/N)·mean over REAL rows ≡ Σ_incl Δc_i / N, so a
        # padded cohort (num_samples == 0 dummy rows, pad_clients_to's
        # contract) cannot inflate |S| or deflate the update — advisor r4.
        incl = (num_samples > 0).astype(jnp.float32)
        c_server_new = jax.tree_util.tree_map(
            lambda cs, new, old: cs
            + jnp.tensordot(incl, new - old, axes=1) / n_total,
            c_server, c_new, c_rows,
        )
        agg = jax.tree_util.tree_map(jnp.sum, metrics)
        return new_global, c_server_new, c_new, agg

    return body


def make_scaffold_cohort_round(
    model: ModelDef,
    config: RunConfig,
    task: str = "classification",
    client_mode: str | None = None,
):
    """Cohort-form SCAFFOLD round for the SPILLED state store:
    ``(global_vars, c_server, c_rows, x, y, mask, ns, rngs) ->
      (global_vars', c_server', c_rows', agg_metrics)``
    — :func:`make_scaffold_round` with the [N, ...] stack gather/scatter
    moved out to the host store (state_store.MmapClientState); only the
    cohort's [C, ...] control rows enter HBM. The in-program math after
    the gather is the same code, so a spilled run bit-matches the in-HBM
    run (pinned in tests/test_state_spill.py)."""
    from fedml_tpu.compile import get_program_cache, model_fingerprint

    # donate the cohort rows (argnum 2): the host store keeps the durable
    # copy; the device rows are consumed by the round. Same digest shape
    # as make_scaffold_round: eta_g and 1/N are baked program constants.
    return get_program_cache().get_or_build(
        "scaffold_cohort_round",
        {
            "kind": "scaffold_cohort_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "mode": client_mode,
            "parallelism": config.fed.client_parallelism,
            "server": config.server,
            "n_total": config.fed.client_num_in_total,
        },
        lambda: jax.jit(
            _make_scaffold_cohort_body(model, config, task, client_mode),
            donate_argnums=(2,),
        ),
    )


def make_sharded_scaffold_cohort_round(
    model: ModelDef, config: RunConfig, mesh, task: str = "classification"
):
    """Cohort-form SCAFFOLD round over a client-sharded mesh — the
    composition VERDICT r4 Weak #4 asked for: the 100k-client spilled
    state tier and the multi-chip runtime in one round.

    ``(global_vars, c_server, c_rows, x, y, mask, ns, rngs) ->
      (global_vars', c_server', c_rows', agg_metrics)``
    where ``c_rows`` arrives SHARDED over the client axis (the host store
    gathered only the cohort — O(|S|·params) of disk IO and HBM, never
    the [N, ...] stack) and the updated rows leave sharded the same way
    for the host scatter. The server math matches
    :func:`_make_scaffold_cohort_body` exactly, with psums where the
    single-chip body reduces locally: Δy via the weighted psum, c-server
    via psum over the inclusion-masked row deltas / N (padded dummy rows
    carry num_samples == 0 AND exact-zero deltas). A spilled mesh run
    therefore matches the spilled single-chip run to float tolerance —
    pinned in tests/test_state_spill.py."""
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    mode = resolve_client_parallelism(config.fed.client_parallelism, model)
    local_train = make_scaffold_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    lifted = client_axis_map(local_train, mode, n_broadcast=2)
    eta_g = config.server.server_lr
    n_total = config.fed.client_num_in_total

    def shard_body(global_vars, c_server, c_rows, x, y, mask, num_samples, rngs):
        varying = lambda t: jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"), t
        )
        gv = varying(global_vars)
        cs = varying(c_server)
        y_vars, c_new, metrics = lifted(gv, cs, c_rows, x, y, mask, rngs)

        wsum = jax.lax.psum(jnp.sum(num_samples), axis)
        w = num_samples / jnp.maximum(wsum, 1e-9)

        def psum_avg_delta(stacked, g):
            return jax.lax.psum(
                jnp.tensordot(
                    w,
                    stacked.astype(jnp.float32) - g.astype(jnp.float32)[None],
                    axes=1,
                ),
                axis,
            )

        new_params = jax.tree_util.tree_map(
            lambda g, s: (
                g.astype(jnp.float32) + eta_g * psum_avg_delta(s, g)
            ).astype(g.dtype),
            gv["params"], y_vars["params"],
        )
        new_global = {
            k: (
                new_params
                if k == "params"
                else jax.tree_util.tree_map(
                    lambda s: jax.lax.psum(
                        jnp.tensordot(w, s.astype(jnp.float32), axes=1), axis
                    ),
                    v,
                )
            )
            for k, v in y_vars.items()
        }
        # c ← c + Σ_incl Δc_i / N — the single-chip cohort body's masked
        # sum, psum'd across shards
        incl = (num_samples > 0).astype(jnp.float32)
        c_server_new = jax.tree_util.tree_map(
            lambda c, new, old: c + jax.lax.psum(
                jnp.tensordot(incl, new - old, axes=1), axis
            ) / n_total,
            cs, c_new, c_rows,
        )
        agg = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(jnp.sum(m), axis), metrics
        )
        return new_global, c_server_new, c_new, agg

    data_spec = P(axis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        # (gv, c_server) replicated; (c_rows, x, y, mask, ns, rngs) sharded
        in_specs=(P(), P()) + (data_spec,) * 6,
        # rows leave sharded — the host scatter reads the real prefix
        out_specs=(P(), P(), data_spec, P()),
        check_vma=False,  # same stance as make_sharded_scaffold_round
    )
    from fedml_tpu.compile import (
        get_program_cache,
        mesh_fingerprint,
        model_fingerprint,
    )

    return get_program_cache().get_or_build(
        "sharded_scaffold_cohort_round",
        {
            "kind": "sharded_scaffold_cohort_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "parallelism": config.fed.client_parallelism,
            "server": config.server,
            "n_total": config.fed.client_num_in_total,
            "mesh": mesh_fingerprint(mesh),
        },
        lambda: jax.jit(sharded, donate_argnums=(2,)),
    )


def make_sharded_scaffold_round(model: ModelDef, config: RunConfig, mesh, task: str = "classification", donate: bool = True):
    """SCAFFOLD round over a client-sharded mesh (the reference has no
    distributed SCAFFOLD at all — this is the shard_map form of the vmap
    round above, same signature).

    Sharding layout: the per-client control store ``c_stack`` stays
    REPLICATED (cross-silo N × |params| fits every chip — SCAFFOLD's own
    regime) while the sampled cohort's data and index vector shard over
    the client axis. Each shard gathers its own clients' rows locally,
    trains, and contributes:
    - Δy via the same weighted psum as sharded FedAvg;
    - the cohort's (idx, Δc) rows via ``all_gather`` — O(|S|·params)
      over ICI, NOT an O(N·params) zeros-scattered stack psum — followed
      by one in-place ``.at[idx_all].add`` on the replicated store.
      Dummy padding clients train on all-zero masks, end with
      c_i⁺ == c_i, and therefore contribute exact zeros.
    c ← c + Σ Δc / N  (≡ the paper's (|S|/N)·mean over the real cohort,
    with padded rows vanishing)."""
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    mode = resolve_client_parallelism(config.fed.client_parallelism, model)
    local_train = make_scaffold_local_train(
        model, config.train, config.fed.epochs, task=task
    )
    lifted = client_axis_map(local_train, mode, n_broadcast=2)
    eta_g = config.server.server_lr
    n_total = config.fed.client_num_in_total

    def shard_body(global_vars, c_server, c_stack, idx, x, y, mask, num_samples, rngs):
        varying = lambda t: jax.tree_util.tree_map(
            lambda a: jax.lax.pcast(a, (axis,), to="varying"), t
        )
        gv = varying(global_vars)
        cs = varying(c_server)
        stack = varying(c_stack)
        c_gather = jax.tree_util.tree_map(lambda a: a[idx], stack)
        y_vars, c_new, metrics = lifted(gv, cs, c_gather, x, y, mask, rngs)

        wsum = jax.lax.psum(jnp.sum(num_samples), axis)
        w = num_samples / jnp.maximum(wsum, 1e-9)

        def psum_avg_delta(stacked, g):
            return jax.lax.psum(
                jnp.tensordot(
                    w,
                    stacked.astype(jnp.float32) - g.astype(jnp.float32)[None],
                    axes=1,
                ),
                axis,
            )

        new_params = jax.tree_util.tree_map(
            lambda g, s: (
                g.astype(jnp.float32) + eta_g * psum_avg_delta(s, g)
            ).astype(g.dtype),
            gv["params"], y_vars["params"],
        )
        new_global = {
            k: (
                new_params
                if k == "params"
                else jax.tree_util.tree_map(
                    lambda s: jax.lax.psum(
                        jnp.tensordot(w, s.astype(jnp.float32), axes=1), axis
                    ),
                    v,
                )
            )
            for k, v in y_vars.items()
        }
        # Row updates travel as the gathered COHORT deltas (O(|S|·params)
        # over ICI), not a zeros-scattered full stack (O(N·params) psum +
        # a second full-stack temporary per shard — pathological when the
        # population is much larger than the cohort).
        delta = jax.tree_util.tree_map(
            lambda new, old: new - old, c_new, c_gather
        )
        idx_all = jax.lax.all_gather(idx, axis, tiled=True)
        delta_all = jax.tree_util.tree_map(
            lambda d: jax.lax.all_gather(d, axis, tiled=True), delta
        )
        # c ← c + Σ Δc / N (dummy padding rows are exact zeros)
        c_server_new = jax.tree_util.tree_map(
            lambda c, d: c + jnp.sum(d, axis=0) / n_total, cs, delta_all
        )
        c_stack_new = jax.tree_util.tree_map(
            lambda stack_l, d: stack_l.at[idx_all].add(d), stack, delta_all
        )
        agg = jax.tree_util.tree_map(
            lambda m: jax.lax.psum(jnp.sum(m), axis), metrics
        )
        return new_global, c_server_new, c_stack_new, agg

    data_spec = P(axis)
    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(), P()) + (data_spec,) * 6,
        out_specs=(P(), P(), P(), P()),
        # every output is a psum-combined value, replicated by construction;
        # the custom-VJP norm ops inside local_train defeat static VMA
        # inference (same situation as parallel/long_context.py) — the
        # mesh-invariance test pins sharded == single-chip bitwise-close
        check_vma=False,
    )
    from fedml_tpu.compile import (
        get_program_cache,
        mesh_fingerprint,
        model_fingerprint,
    )

    return get_program_cache().get_or_build(
        "sharded_scaffold_round",
        {
            "kind": "sharded_scaffold_round",
            "model": model_fingerprint(model),
            "train": config.train,
            "epochs": config.fed.epochs,
            "task": task,
            "parallelism": config.fed.client_parallelism,
            "server": config.server,
            "n_total": config.fed.client_num_in_total,
            "mesh": mesh_fingerprint(mesh),
            "donate": donate,
        },
        lambda: jax.jit(sharded, donate_argnums=(2,) if donate else ()),
    )


class ScaffoldAPI(FedAvgAPI):
    """SCAFFOLD simulator on the FedAvg skeleton — adds the server control
    variate and the per-client control store. The store lives in HBM as a
    stacked [N, ...] pytree while it fits FedConfig.state_budget_bytes and
    SPILLS to the disk tier beyond it (state_store.MmapClientState —
    cohort rows only ride to device; round 3 refused instead,
    VERDICT r3 Weak #3)."""

    _supports_fused = False  # per-round control-variate state exchange

    def __init__(self, config: RunConfig, data: FederatedDataset, model: ModelDef, **kw):
        super().__init__(config, data, model, **kw)
        from fedml_tpu.algorithms.state_store import (
            make_spill_store,
            resolve_state_store,
        )

        params = self.global_vars["params"]
        n = config.fed.client_num_in_total
        psize = sum(
            int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
        )
        zeros32 = lambda p: jnp.zeros(p.shape, jnp.float32)
        self.c_server = jax.tree_util.tree_map(zeros32, params)
        self._state_mode = resolve_state_store(
            config.fed, 4 * psize * n, n_clients=n,
            population=getattr(config, "population", None),
        )
        if self._state_mode == "device":
            self.c_stack = jax.tree_util.tree_map(
                lambda p: jnp.zeros((n,) + p.shape, jnp.float32), params
            )
            self._scaffold_round = self._build_scaffold_round()
        else:
            from fedml_tpu.algorithms.state_store import CohortPrefetcher

            self.c_stack = None
            self._c_store = make_spill_store(
                self._state_mode,
                jax.tree_util.tree_map(
                    lambda p: np.zeros(p.shape, np.float32), params
                ),
                n,
                config.fed.state_dir or None,
                population=getattr(config, "population", None),
            )
            # overlap the NEXT cohort's disk gather with the current
            # round's device compute (the measured spill tax was 3.1x —
            # VERDICT r4 Weak #4; the gather is the front half of it)
            self._c_prefetch = CohortPrefetcher(self._c_store)
            self._scaffold_round = self._build_scaffold_cohort_round()

    def _build_scaffold_cohort_round(self):
        """Jitted cohort-form round for the SPILLED store. The mesh
        subclass swaps in the shard_map form — spill and multi-chip
        compose (round 4 refused here, VERDICT r4 Weak #4)."""
        return make_scaffold_cohort_round(
            self.model, self.config, task=self.task,
            client_mode=self._client_mode,
        )

    def _build_scaffold_round(self):
        # donate the c_stack (argnum 2): train_round keeps no alias to the
        # pre-round stack, and without donation every round would hold TWO
        # full N×|params| copies while .at[idx].set builds the new one —
        # exactly the thrashing the state budget exists to prevent
        return make_scaffold_round(
            self.model, self.config, task=self.task, donate=True,
            client_mode=self._client_mode,
        )

    def _place_client_indices(self, sampled):
        """The sampled client ids as the round fn's gather/scatter index
        vector — the sharded subclass pads to the mesh and shards it."""
        return jnp.asarray(np.asarray(sampled, np.int32))

    def _build_round_fn(self, local_train_fn):
        return None  # unused — train_round is fully overridden

    def round_flops(self, round_idx: int = 0):
        return None  # bespoke round fn; XLA cost analysis not wired

    def checkpoint_state(self):
        """Control-variate state for checkpoint/resume — without this a
        resumed run would silently restart c/c_i at zero and degenerate
        to FedAvg until the variates re-learn. Spilled-store checkpoints
        embed the TOUCHED ROWS themselves (self-contained npz — a mere
        path to the live directory would roll forward as training
        continues and dangle after a tmp-cleaner pass); either
        representation restores into either store mode."""
        if self._state_mode == "device":
            return {"c_server": self.c_server, "c_stack": self.c_stack}
        # self-contained: the touched rows ARE the store's whole
        # information content (untouched rows gather as zeros), so the
        # checkpoint survives tmp-cleaners and never references the live
        # (still-mutating) directory
        self._c_store.flush()  # checkpoint == durability point for the spill tier
        idx = self._c_store.initialized_ids()
        rows = self._c_store.gather(idx)
        out = {"c_server": self.c_server, "c_rows_idx": idx}
        for i, leaf in enumerate(jax.tree_util.tree_leaves(rows)):
            out[f"c_rows_{i}"] = leaf
        return out

    def restore_state(self, tree):
        from fedml_tpu.utils.checkpoint import restore_like

        if self._state_mode != "device":
            # a pending prefetch holds PRE-restore rows; drop it (and let
            # any in-flight read finish before reset_to rewrites the store)
            self._c_prefetch.cancel()
        self.c_server = restore_like(self.c_server, tree["c_server"])
        n = self.config.fed.client_num_in_total
        zeros_stack = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros((n,) + p.shape, jnp.float32),
            self.global_vars["params"],
        )
        if "c_stack" in tree:
            if self._state_mode == "device":
                self.c_stack = restore_like(self.c_stack, tree["c_stack"])
            else:
                # a device-mode checkpoint restores into a spilled run
                stack = restore_like(zeros_stack(), tree["c_stack"])
                self._c_store.reset_to(np.arange(n), jax.device_get(stack))
        else:
            idx = np.asarray(tree["c_rows_idx"])
            leaves, treedef = jax.tree_util.tree_flatten(
                self.global_vars["params"]
            )
            rows = jax.tree_util.tree_unflatten(
                treedef,
                [np.asarray(tree[f"c_rows_{i}"]) for i in range(len(leaves))],
            )
            if self._state_mode == "device":
                # a spilled checkpoint restores into a device-mode run
                self.c_stack = jax.tree_util.tree_map(
                    lambda s, r: s.at[jnp.asarray(idx)].set(jnp.asarray(r)),
                    zeros_stack(),
                    rows,
                )
            else:
                self._c_store.reset_to(idx, rows)

    def train_round(self, round_idx: int):
        sampled, _steps, _bs = self._round_plan(round_idx)
        # batch via the shared warmup/pipeline stash contract — a
        # pipelined run pops the batch the host prepared during the
        # previous round's device execution (byte-identical by the
        # determinism contract, fedavg._round_placed)
        placed = self._round_placed(round_idx, sampled)
        if self._state_mode == "device":
            (
                self.global_vars,
                self.c_server,
                self.c_stack,
                metrics,
            ) = self._scaffold_round(
                self.global_vars,
                self.c_server,
                self.c_stack,
                self._place_client_indices(sampled),
                *placed,
            )
            return sampled, metrics
        # spilled store: host-gather the cohort's control rows (prefetched
        # last round when possible), run the cohort-form round, scatter
        # the updated rows back to disk
        ids, n_real = self._spill_pad_ids(sampled)
        c_rows = self._place_cohort_rows(self._c_prefetch.take(round_idx, ids))
        (
            self.global_vars,
            self.c_server,
            new_rows,
            metrics,
        ) = self._scaffold_round(
            self.global_vars,
            self.c_server,
            c_rows,
            *placed,
        )
        # the round is dispatched async: start reading the NEXT cohort's
        # rows off disk while the device computes this one. Rows being
        # scattered below are excluded from the background read and
        # re-fetched synchronously at the next take() — no torn rows.
        if round_idx + 1 < self.config.fed.comm_round:
            nxt_ids, _ = self._spill_pad_ids(self._round_plan(round_idx + 1)[0])
            self._c_prefetch.launch(
                round_idx + 1, nxt_ids,
                exclude=set(int(i) for i in np.asarray(sampled)),
            )
        host_rows = jax.device_get(new_rows)
        self._c_store.scatter(
            np.asarray(sampled),
            jax.tree_util.tree_map(lambda r: r[:n_real], host_rows),
        )
        return sampled, metrics
