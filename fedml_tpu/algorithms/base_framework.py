"""Minimal distributed-algorithm templates (ref:
fedml_api/distributed/base_framework/ — central worker sums scalars from
clients (algorithm_api.py:16-21, central_worker.py:28-32, client_worker.py:
10-12) — and fedml_api/distributed/decentralized_framework/ — serverless
gossip skeleton (decentralized_worker_manager.py:8-46)).

These are the "write your own algorithm here" starting points: subclass,
replace the payload/handlers, keep the actor wiring. Both run over any
BaseCommManager."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from fedml_tpu.core.comm import BaseCommManager
from fedml_tpu.core.loopback import LoopbackCommManager, LoopbackHub
from fedml_tpu.core.managers import ClientManager, ServerManager
from fedml_tpu.core.message import Message

MSG_C2S_VALUE = "base_c2s_value"
MSG_S2C_START = "base_s2c_start"
MSG_FINISH = "base_finish"
MSG_GOSSIP = "gossip_result"


class BaseCentralWorker(ServerManager):
    """Sums one scalar from every client (ref central_worker.py:28-32)."""

    def __init__(self, comm: BaseCommManager, worker_num: int):
        super().__init__(comm, rank=0)
        self.worker_num = worker_num
        self.values: List[float] = []
        self.total: Optional[float] = None

    def start(self):
        for w in range(1, self.worker_num + 1):
            self.send_message(Message(MSG_S2C_START, 0, w))

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_C2S_VALUE, self._on_value)

    def _on_value(self, msg: Message):
        self.values.append(float(msg.get("value")))
        if len(self.values) == self.worker_num:
            self.total = sum(self.values)
            for w in range(1, self.worker_num + 1):
                self.send_message(Message(MSG_FINISH, 0, w))
            self.finish()


class BaseClientWorker(ClientManager):
    """Replies with its payload (ref client_worker.py:10-12 returns
    client_index)."""

    def __init__(self, comm: BaseCommManager, rank: int, value_fn: Callable[[], float]):
        super().__init__(comm, rank)
        self.value_fn = value_fn

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_S2C_START, self._on_start)
        self.register_message_receive_handler(MSG_FINISH, lambda m: self.finish())

    def _on_start(self, msg: Message):
        out = Message(MSG_C2S_VALUE, self.rank, 0)
        out.add_params("value", float(self.value_fn()))
        self.send_message(out)


def run_base_framework(worker_values: List[float]) -> float:
    """Loopback demo run (ref FedML_Base_distributed, algorithm_api.py:16-21).
    Returns the central sum."""
    hub = LoopbackHub()
    K = len(worker_values)
    server = BaseCentralWorker(LoopbackCommManager(hub, 0), K)
    clients = [
        BaseClientWorker(
            LoopbackCommManager(hub, r), r, (lambda v=v: v)
        )
        for r, v in enumerate(worker_values, start=1)
    ]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    assert server.total is not None
    return server.total


class DecentralizedWorkerManager(ClientManager):
    """Serverless gossip template (ref decentralized_worker_manager.py:8-46:
    each worker trains, sends to topology out-neighbors, waits for all
    in-neighbors, then averages)."""

    def __init__(
        self,
        comm: BaseCommManager,
        rank: int,
        topology,
        value: np.ndarray,
        rounds: int = 1,
    ):
        super().__init__(comm, rank)
        self.topology = topology
        self.value = np.asarray(value, np.float64)
        self.rounds = rounds
        self.round_idx = 0
        # Keyed by (round, sender): a fast neighbor's round r+1 message must
        # not complete (or overwrite a value in) the round-r barrier (ref
        # decentralized_worker_manager.py:29-46 per-round barrier semantics).
        self._inbox: Dict[tuple, np.ndarray] = {}

    def register_message_receive_handlers(self):
        self.register_message_receive_handler(MSG_GOSSIP, self._on_gossip)

    def run(self):
        # Round 0 is initiated from THIS thread, before the receive loop
        # starts: handlers also run on this thread, so every mutation of
        # (round_idx, value, inbox) is single-threaded. Starting gossip
        # from the launcher thread instead is a deadlock: a worker whose
        # in-neighbors all delivered can advance to round 1 in its receive
        # thread before the launcher sends its round-0 value, after which
        # the launcher's send carries a round-1 tag and the round-0 value
        # is never published — its neighbors wait on (0, rank) forever.
        self.start_gossip()
        super().run()

    def start_gossip(self):
        for j in self.topology.get_out_neighbor_idx_list(self.rank):
            m = Message(MSG_GOSSIP, self.rank, j)
            m.add_params("value", self.value)
            m.add_params("round", self.round_idx)
            self.send_message(m)

    def _on_gossip(self, msg: Message):
        self._inbox[(int(msg.get("round")), msg.get_sender_id())] = msg.get("value")
        in_neighbors = self.topology.get_in_neighbor_idx_list(self.rank)
        # Advance while the *current* round's barrier is complete; buffered
        # future-round values stay in the inbox until their round arrives.
        while all((self.round_idx, j) in self._inbox for j in in_neighbors):
            # weighted mix with the confusion-matrix row (ref __train:41-46;
            # the reference's symmetric manager returns the row for both
            # in/out, symmetric_topology_manager.py:55-61)
            w = self.topology.get_out_neighbor_weights(self.rank)
            mixed = self.value * w[self.rank]
            for j in in_neighbors:
                v = self._inbox.pop((self.round_idx, j))
                mixed = mixed + np.asarray(v) * w[j]
            self.value = mixed
            self.round_idx += 1
            if self.round_idx >= self.rounds:
                self.finish()
                return
            self.start_gossip()
