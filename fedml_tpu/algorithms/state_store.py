"""Disk-backed per-client ALGORITHM state — the spill tier for stateful
federated algorithms (SCAFFOLD control variates, Ditto personal models).

The round-3 stateful algorithms pinned their N × |params| state as one
stacked pytree in HBM and hard-refused past 8 GiB while the data layer
already scaled to 100k clients on disk (VERDICT r3 Weak #3). This module
closes that asymmetry with the data layer's own tiering
(data/mmap_store.py):

    disk (np.memmap, all N clients' state rows)
        -> host RAM (sampled cohort's rows only)
        -> HBM (cohort rows enter the jitted cohort-form round)

Layout on disk (one directory): ``leaf_{i}.npy`` — one np.lib.format
array per pytree leaf, shape [N, *leaf_shape] — plus ``init_mask.npy``
and ``meta.json``. Rows are LAZILY initialized: ``open_memmap`` creates
sparse zero files instantly (no 100k-row write at construction), and a
per-client bitmap records which rows have ever been scattered; a gather
of an untouched row returns the algorithm's initial state (zeros for
SCAFFOLD's c_i, the broadcast w_0 for Ditto's v_k) without any disk
write having happened. Per round, only the cohort's rows are read and
written — O(|S| · params) IO, independent of N.

Math contract: gather/scatter are exact row copies (float32 in, float32
out), so a spilled run is BIT-IDENTICAL to the in-HBM run at the same
seed — pinned by tests/test_state_spill.py against ScaffoldAPI/DittoAPI
with the device store.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import weakref
from typing import Optional, Sequence

import jax
import numpy as np


class MmapClientState:
    """[N, ...] per-client state pytree spilled to one memmap per leaf.

    ``init_tree`` is ONE client's initial state (no leading N axis); its
    tree structure, shapes, and dtypes define the store's schema.
    """

    def __init__(self, init_tree, n_clients: int, path: Optional[str] = None):
        self.n = int(n_clients)
        leaves, self._treedef = jax.tree_util.tree_flatten(init_tree)
        self._init_leaves = [np.asarray(l) for l in leaves]
        path = path or None  # "" (FedConfig.state_dir default) == unset
        self.path = path or tempfile.mkdtemp(prefix="fedml_tpu_state_")
        if path is None:
            # a self-created temp spill dir is scratch, not a deliverable:
            # without cleanup every 100k-client run leaks N x |params|
            # bytes of /tmp (advisor r4). User-supplied paths are THEIRS
            # (resume target) and are never removed.
            self._cleanup = weakref.finalize(
                self, shutil.rmtree, self.path, ignore_errors=True
            )
        else:
            self._cleanup = None
        os.makedirs(self.path, exist_ok=True)
        meta_path = os.path.join(self.path, "meta.json")
        schema = [
            {"shape": list(l.shape), "dtype": str(l.dtype)}
            for l in self._init_leaves
        ]
        if os.path.exists(meta_path):
            # resume: reopen an existing store — schema must match exactly
            # (a silent mismatch would scatter rows into the wrong layout)
            with open(meta_path) as f:
                meta = json.load(f)
            if meta["n"] != self.n or meta["leaves"] != schema:
                raise ValueError(
                    f"existing state store at {self.path} has schema "
                    f"{meta}, expected n={self.n}, leaves={schema}"
                )
            self._mms = [
                np.load(
                    os.path.join(self.path, f"leaf_{i}.npy"), mmap_mode="r+"
                )
                for i in range(len(self._init_leaves))
            ]
            self._init_mask = np.load(
                os.path.join(self.path, "init_mask.npy"), mmap_mode="r+"
            )
            self._advise_random()
        else:
            # open_memmap w+ creates SPARSE zero-filled files — O(1) in
            # data written, whatever N is
            self._mms = [
                np.lib.format.open_memmap(
                    os.path.join(self.path, f"leaf_{i}.npy"),
                    mode="w+",
                    dtype=l.dtype,
                    shape=(self.n,) + l.shape,
                )
                for i, l in enumerate(self._init_leaves)
            ]
            self._init_mask = np.lib.format.open_memmap(
                os.path.join(self.path, "init_mask.npy"),
                mode="w+",
                dtype=np.bool_,
                shape=(self.n,),
            )
            with open(meta_path, "w") as f:
                json.dump({"n": self.n, "leaves": schema}, f)
            self._advise_random()

    def _advise_random(self) -> None:
        # cohort rows are random by construction: kernel readahead on
        # the sparse [N, ...] files amplifies every row fault into a
        # full readahead window (measured 280x on the sharded tier at
        # 1M clients — see data.mmap_store.advise_random)
        from fedml_tpu.data.mmap_store import advise_random

        for mm in self._mms:
            advise_random(mm)
        advise_random(self._init_mask)

    @property
    def state_bytes_total(self) -> int:
        """Logical size of the full store (the number the HBM path would
        have to pin) — for logging; actual disk use is cohort-sparse."""
        return self.n * sum(l.nbytes for l in self._init_leaves)

    def gather(self, idx: Sequence[int]):
        """Cohort rows as a HOST pytree [C, ...] (copies — safe to ship to
        device). Untouched rows come back as the initial state."""
        idx = np.asarray(idx, np.int64)
        inited = np.asarray(self._init_mask[idx])
        out = []
        for mm, base in zip(self._mms, self._init_leaves):
            rows = np.array(mm[idx])  # fancy-index copy off the mmap
            if not inited.all():
                rows[~inited] = base
            out.append(rows)
        return jax.tree_util.tree_unflatten(self._treedef, out)

    def scatter(self, idx: Sequence[int], rows_tree) -> None:
        """Write the cohort's updated rows back (host arrays in)."""
        idx = np.asarray(idx, np.int64)
        leaves = jax.tree_util.tree_leaves(rows_tree)
        for mm, r in zip(self._mms, leaves):
            mm[idx] = np.asarray(r, dtype=mm.dtype)
        self._init_mask[idx] = True

    def flush(self) -> None:
        for mm in self._mms:
            mm.flush()
        self._init_mask.flush()

    def initialized_ids(self) -> np.ndarray:
        """Client ids whose rows have ever been scattered — together with
        :meth:`gather` of those ids this is the store's ENTIRE information
        content (every other row is the initial state), which is what
        checkpoint_state embeds so checkpoints are self-contained (a
        checkpoint that merely recorded the live directory's path would
        roll forward as training continues, and would dangle after a
        tmp-cleaner pass)."""
        return np.flatnonzero(np.asarray(self._init_mask))

    def reset_to(self, idx: Sequence[int], rows_tree) -> None:
        """Roll the store back to exactly {initial state everywhere except
        ``idx``, which holds ``rows_tree``} — the restore side of the
        self-contained checkpoint."""
        inited = self.initialized_ids()
        if len(inited):
            # rows touched after the checkpoint revert to the initial state
            for mm, base in zip(self._mms, self._init_leaves):
                mm[inited] = base
            self._init_mask[inited] = False
        if len(np.asarray(idx)):
            self.scatter(idx, rows_tree)

    def initialized_count(self) -> int:
        return int(np.count_nonzero(self._init_mask))


class CohortPrefetcher:
    """Overlap the next round's cohort gather (disk read) with the current
    round's device compute.

    Correctness contract: rows the caller is about to scatter THIS round
    must be passed in ``exclude`` — the background thread never reads
    them, and :meth:`take` re-fetches them synchronously after the scatter
    has landed, so a prefetched cohort can never contain torn or stale
    rows. A take() whose (round, ids) doesn't match the pending prefetch
    falls back to a plain synchronous gather."""

    def __init__(self, store: MmapClientState):
        self.store = store
        self._pending = None  # (round_idx, ids_bytes, safe_mask, result)
        self._thread = None

    def launch(self, round_idx: int, ids, exclude=()) -> None:
        import threading

        self.cancel()
        ids = np.asarray(ids, np.int64)
        excl = set(int(i) for i in exclude)
        safe_mask = np.fromiter(
            (int(i) not in excl for i in ids), bool, count=len(ids)
        )
        safe_ids = ids[safe_mask]
        result = {}

        def work():
            try:
                result["rows"] = self.store.gather(safe_ids)
            except Exception as e:  # noqa: BLE001 — surface at take()
                result["err"] = e

        t = threading.Thread(target=work, daemon=True)
        t.start()
        self._pending = (int(round_idx), ids.tobytes(), safe_mask, result)
        self._thread = t

    def take(self, round_idx: int, ids):
        ids = np.asarray(ids, np.int64)
        if (
            self._pending is None
            or self._pending[0] != int(round_idx)
            or self._pending[1] != ids.tobytes()
        ):
            self.cancel()
            return self.store.gather(ids)
        _, _, safe_mask, result = self._pending
        self._thread.join()
        self._pending, self._thread = None, None
        if "rows" not in result:
            # the background gather died (disk error, dir removed): retry
            # synchronously — a persistent failure re-raises HERE with the
            # true error, attributed to the caller's round
            return self.store.gather(ids)
        pre = result["rows"]
        if safe_mask.all():
            return pre
        missing = self.store.gather(ids[~safe_mask])

        def merge(p, m):
            out = np.empty((len(ids),) + p.shape[1:], p.dtype)
            out[safe_mask] = p
            out[~safe_mask] = m
            return out

        return jax.tree_util.tree_map(merge, pre, missing)

    def cancel(self) -> None:
        if self._thread is not None:
            self._thread.join()
        self._pending, self._thread = None, None


def resolve_state_store(
    config_fed, state_bytes: int, n_clients: int = 0, population=None
) -> str:
    """"device" | "mmap" | "sharded" from FedConfig.state_store, the
    state size, and the population. ``auto`` keeps the stack in HBM
    while it fits the budget; past it, spill goes to the per-leaf mmap
    tier — or, at/above the population threshold
    (PopulationConfig.ocohort_threshold), to the record-major sharded
    tier (population/state_tier.py: one contiguous record per client
    instead of one scattered row per pytree leaf)."""
    mode = config_fed.state_store
    if mode == "auto":
        if state_bytes <= config_fed.state_budget_bytes:
            return "device"
        threshold = (
            population.ocohort_threshold if population is not None else 65536
        )
        return "sharded" if n_clients and n_clients >= threshold else "mmap"
    if mode not in ("device", "mmap", "sharded"):
        raise ValueError(
            f"FedConfig.state_store must be 'auto', 'device', 'mmap' or "
            f"'sharded'; got {mode!r}"
        )
    return mode


def make_spill_store(
    mode: str, init_tree, n_clients: int, path=None, population=None
):
    """Construct the spill tier named by a resolved non-device mode —
    the ONE mapping from mode string to store class, shared by SCAFFOLD
    and Ditto (and any future stateful algorithm), so the two can never
    wire the tiers differently."""
    if mode == "sharded":
        from fedml_tpu.population.state_tier import ShardedClientState

        return ShardedClientState(
            init_tree,
            n_clients,
            path,
            shard_bits=(
                population.state_shard_bits if population is not None else 16
            ),
        )
    if mode == "mmap":
        return MmapClientState(init_tree, n_clients, path)
    raise ValueError(f"not a spill-store mode: {mode!r}")
