"""Measured fused-vs-eager round planner.

The fused multi-round scan (``make_fedavg_multiround``) exists to
amortize per-round host dispatch; whether it actually WINS depends on
the model, the backend, and everything the compile runtime has since
changed about the eager path's cost (BENCH_r05 measured the fused
north-star row 36% SLOWER than eager — the config heuristic "fuse
whenever ``fused_rounds > 1``" had gone stale). This module replaces
that heuristic with a measurement: under ``FedConfig.fused_plan =
"measured"``, the first rounds of a run probe BOTH schedules and the
planner commits to the measured winner, per

    (algorithm, steps-class, batch-size, cohort-size)

— the tuple that determines the programs both schedules dispatch.

The probe reads its per-round costs from the PR-12 flight recorder
(telemetry/flight.py), not from new instrumentation: probed segments are
executed with an explicit device sync inside their ``round`` span (the
ordinary async dispatch makes an unsynced span measure host dispatch
only), so the folded record's wall IS the honest schedule cost — a
fused chunk's record carries ``fused_rounds`` and divides down to
per-round. Each arm keeps its best (min) observed per-round cost:
minimum-of-K is the standard microbenchmark statistic, robust to a
compile-tainted first sample and to host noise, and — decisive for the
test contract — a DETERMINISTIC function of the observed records: the
same flight history always commits the same schedule. Ties break toward
fused (it amortizes dispatch; with measured costs equal, fewer
dispatches is the better bet).

After every active key has committed, the planner detaches from the
recorder (and detaches the recorder from the tracer when the planner
created it privately) — steady-state rounds carry zero probe overhead
and the span stream has no extra listener.

The committed decision and both arms' measured costs land in
summary.json under ``flight/planner_*`` / ``flight/probe_*`` keys
(docs/OBSERVABILITY.md) — the ci.sh fused-vs-eager gate reads the
winner off those, never off a config echo."""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Optional

# Folded records per arm before committing. Two suffice: the first
# sample of an arm may carry a lazy compile or cold cache effects; the
# min over two keeps the clean one.
PROBE_SAMPLES = 2


@dataclasses.dataclass(frozen=True)
class PlanKey:
    """What determines the programs both schedules would dispatch."""

    algo: str
    steps: int
    bs: int
    cohort: int

    def label(self) -> str:
        return f"{self.algo}:s{self.steps}b{self.bs}c{self.cohort}"


class _KeyState:
    __slots__ = ("fused", "eager", "decision")

    def __init__(self):
        self.fused: list = []  # per-round seconds, fused arm
        self.eager: list = []  # per-round seconds, eager arm
        self.decision: Optional[str] = None


class SchedulePlanner:
    """Probe-then-commit schedule selection over flight-recorder folds.

    Wiring (FedAvgAPI): ``plan(key, round_idx, fusible_len)`` replaces
    the tail of ``_fused_chunk_len`` — it returns the chunk length to
    run (``fusible_len`` for the fused arm / a committed fused decision,
    1 for the eager arm / a committed eager decision) and is idempotent
    per ``round_idx`` (warmup and the train loop both consult it).
    ``wants_sync(round_idx)`` tells the train loop to block on the
    device inside the round span, so the fold measures schedule cost,
    not dispatch cost."""

    def __init__(self, log_fn: Optional[Callable[[dict], None]] = None):
        self._lock = threading.Lock()
        self._states: Dict[PlanKey, _KeyState] = {}
        # probe segments in flight: start round -> (key, arm, length)
        self._pending: Dict[int, tuple] = {}
        # idempotence: round -> planned chunk length (warmup + train both
        # ask; the answer must not depend on how often they ask)
        self._planned: Dict[int, int] = {}
        self._log_fn = log_fn
        self._recorder = None
        self._tracer = None
        self._owns_recorder = False
        self._detached = False

    # -- wiring --------------------------------------------------------------

    def attach(self, tracer, config=None) -> "SchedulePlanner":
        """Listen on ``tracer``'s flight recorder, adopting an ambient
        one (the CLI's ``--telemetry_dir``/serve-layer recorder) or
        attaching a private one — the probe reads MEASURED phase folds
        either way, it never re-instruments."""
        from fedml_tpu.telemetry.flight import FlightRecorder, attached_recorder

        rec = attached_recorder(tracer)
        if rec is None:
            rec = (
                FlightRecorder.from_config(config)
                if config is not None
                else FlightRecorder()
            )
            rec.attach(tracer)
            self._owns_recorder = True
        self._recorder = rec
        self._tracer = tracer
        rec.add_listener(self.observe)
        self._detached = False
        return self

    def close(self) -> None:
        """Stop listening (idempotent). Called automatically once every
        probed key has committed."""
        if self._recorder is not None and not self._detached:
            self._recorder.remove_listener(self.observe)
            if self._owns_recorder:
                self._recorder.detach()
            self._detached = True
        with self._lock:
            # probe bookkeeping is dead once every key committed — the
            # steady state must hold zero per-round memory
            self._planned.clear()

    # -- the planning surface ------------------------------------------------

    def plan(self, key: PlanKey, round_idx: int, fusible_len: int) -> int:
        """Chunk length for the segment starting at ``round_idx``, given
        the structural planner allows ``fusible_len`` fused rounds."""
        r = int(round_idx)
        reattach = False
        try:
            with self._lock:
                cached = self._planned.get(r)
                if cached is not None:
                    return min(cached, fusible_len) if cached > 1 else cached
                st = self._states.get(key)
                if st is None:
                    st = self._states[key] = _KeyState()
                    # a NEW key after the probe closed (mid-run cohort or
                    # steps-class change): re-subscribe so its folds are
                    # observed — otherwise its probe segments would hang
                    # in _pending forever and the key could never commit
                    reattach = self._detached and self._tracer is not None
                return self._plan_locked(st, key, r, fusible_len)
        finally:
            if reattach:
                self.attach(self._tracer)

    def _plan_locked(
        self, st: "_KeyState", key: PlanKey, r: int, fusible_len: int
    ) -> int:
        if st.decision is not None:
            # committed: the answer is a pure function of the
            # decision — nothing to memoize (the _planned cache is
            # for the probe phase only; caching here would grow one
            # entry per round for the run's whole life)
            return fusible_len if st.decision == "fused" else 1
        # probe: fill the fused arm first (its samples are chunks —
        # fewer, costlier), then the eager arm, then commit
        in_flight_f = sum(
            1 for k, a, _ in self._pending.values()
            if k == key and a == "fused"
        )
        in_flight_e = sum(
            1 for k, a, _ in self._pending.values()
            if k == key and a == "eager"
        )
        if len(st.fused) + in_flight_f < PROBE_SAMPLES:
            arm, L = "fused", fusible_len
        elif len(st.eager) + in_flight_e < PROBE_SAMPLES:
            arm, L = "eager", 1
        else:
            # both arms fully scheduled but not yet folded (a caller
            # planning ahead of execution): run fused — the probe
            # decides retroactively, and fused is the amortizing
            # default while undecided. Not a probe segment.
            self._planned[r] = fusible_len
            return fusible_len
        self._pending[r] = (key, arm, L)
        self._planned[r] = L
        return L

    def wants_sync(self, round_idx: int) -> bool:
        """True when the segment starting at ``round_idx`` is a probe —
        the train loop must block on the device inside the round span so
        the folded wall measures the schedule, not the dispatch."""
        with self._lock:
            return int(round_idx) in self._pending

    def decision(self, key: PlanKey) -> Optional[str]:
        with self._lock:
            st = self._states.get(key)
            return st.decision if st is not None else None

    # -- fold feedback -------------------------------------------------------

    def observe(self, rec: dict) -> None:
        """Flight-recorder fold listener. Attributes probe records to
        their arm and commits a key once both arms have
        :data:`PROBE_SAMPLES` samples. Pure in the record stream — the
        same history always yields the same decisions (test contract)."""
        row = None
        with self._lock:
            seg = self._pending.pop(int(rec.get("round", -1)), None)
            if seg is None:
                return
            key, arm, L = seg
            st = self._states.get(key)
            if st is None or st.decision is not None:
                return
            per_round = float(rec["t_s"]) / max(
                int(rec.get("fused_rounds", 1)), 1
            )
            (st.fused if arm == "fused" else st.eager).append(per_round)
            if (
                len(st.fused) >= PROBE_SAMPLES
                and len(st.eager) >= PROBE_SAMPLES
            ):
                fused_s, eager_s = min(st.fused), min(st.eager)
                # tie → fused: equal measured cost, fewer dispatches
                st.decision = "fused" if fused_s <= eager_s else "eager"
                row = {
                    "flight/planner_schedule": st.decision,
                    "flight/planner_key": key.label(),
                    "flight/probe_fused_per_round_s": round(fused_s, 6),
                    "flight/probe_eager_per_round_s": round(eager_s, 6),
                    "flight/planner_probe_rounds": len(st.fused)
                    + len(st.eager),
                }
            done = not self._pending and all(
                s.decision is not None for s in self._states.values()
            )
        if row is not None and self._log_fn is not None:
            self._log_fn(row)
        if row is not None and done:
            # every active key committed — the probe is over; stop
            # taxing the span stream
            self.close()

    # -- introspection -------------------------------------------------------

    def summary_row(self) -> dict:
        """Flat ``flight/planner_*`` row of the latest state (the commit
        itself already logged through ``log_fn``; this is the pull-side
        surface for bench/tests)."""
        with self._lock:
            row: dict = {}
            for key, st in self._states.items():
                if st.decision is None:
                    continue
                row.setdefault("flight/planner_schedule", st.decision)
                row.setdefault("flight/planner_key", key.label())
                if st.fused:
                    row.setdefault(
                        "flight/probe_fused_per_round_s",
                        round(min(st.fused), 6),
                    )
                if st.eager:
                    row.setdefault(
                        "flight/probe_eager_per_round_s",
                        round(min(st.eager), 6),
                    )
            row["flight/planner_keys"] = len(self._states)
            return row
