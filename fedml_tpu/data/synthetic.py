"""Synthetic federated datasets.

Parity target: the reference's synthetic_1_1 generator
(fedml_api/data_preprocessing/synthetic_1_1/, per the FedProx synthetic(α,β)
family) plus a generic classification generator used by tests/benchmarks when
real data is not vendored (the reference downloads real datasets in CI;
CI-install.sh:39-80 — not possible here, so synthetic stands in).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.partition.noniid import homo_partition, lda_partition


def synthetic_classification(
    num_clients: int = 10,
    num_classes: int = 10,
    feat_shape=(28, 28, 1),
    samples_per_client: int = 64,
    partition_method: str = "homo",
    partition_alpha: float = 0.5,
    seed: int = 0,
    ragged: bool = True,
) -> FederatedDataset:
    """Gaussian-blob classification data, partitioned across clients.

    With ``ragged=True`` client shard sizes vary (power-law-ish), matching the
    non-uniform client sizes of leaf datasets (ref MNIST/data_loader.py:14+).
    """
    rng = np.random.default_rng(seed)
    n_total = num_clients * samples_per_client
    dim = int(np.prod(feat_shape))
    # Class means spread in feature space.
    means = rng.normal(0.0, 1.0, size=(num_classes, dim))
    y = rng.integers(0, num_classes, size=n_total).astype(np.int32)
    x = (means[y] + rng.normal(0.0, 1.0, size=(n_total, dim))).astype(np.float32)
    x = x.reshape((n_total,) + tuple(feat_shape))

    if partition_method == "homo":
        idx_map = homo_partition(n_total, num_clients, rng)
    else:
        idx_map = lda_partition(y, num_clients, partition_alpha, seed=seed)

    client_x, client_y = [], []
    for i in range(num_clients):
        idxs = idx_map[i]
        if ragged and partition_method == "homo":
            # Trim each shard by a client-specific factor to create raggedness.
            keep = max(2, int(len(idxs) * rng.uniform(0.5, 1.0)))
            idxs = idxs[:keep]
        client_x.append(x[idxs])
        client_y.append(y[idxs])

    n_test = max(num_classes * 8, 64)
    yt = rng.integers(0, num_classes, size=n_test).astype(np.int32)
    xt = (means[yt] + rng.normal(0.0, 1.0, size=(n_test, dim))).astype(np.float32)
    xt = xt.reshape((n_test,) + tuple(feat_shape))
    return FederatedDataset(
        name="synthetic",
        client_x=client_x,
        client_y=client_y,
        test_x=xt,
        test_y=yt,
        num_classes=num_classes,
    )


def synthetic_segmentation(
    num_clients: int = 4,
    num_classes: int = 4,
    image_size: int = 16,
    samples_per_client: int = 16,
    seed: int = 0,
) -> FederatedDataset:
    """Per-pixel labeled synthetic data for the segmentation task (stand-in
    for the reference's fedseg datasets, which require external downloads).
    Class signal is injected into channel 0 so models can actually learn."""
    rng = np.random.default_rng(seed)
    H = image_size

    def gen(n):
        x = rng.normal(size=(n, H, H, 3)).astype(np.float32)
        y = rng.integers(0, num_classes, size=(n, H, H)).astype(np.int32)
        for c in range(num_classes):
            x[..., 0] += 1.5 * c * (y == c)
        return x, y

    client_x, client_y = [], []
    for _ in range(num_clients):
        x, y = gen(samples_per_client)
        client_x.append(x)
        client_y.append(y)
    tx, ty = gen(max(16, samples_per_client))
    return FederatedDataset(
        name="seg_synth",
        client_x=client_x,
        client_y=client_y,
        test_x=tx,
        test_y=ty,
        num_classes=num_classes,
    )


def synthetic_shakespeare(
    num_clients: int = 64,
    samples_per_client: int = 60,
    seq_len: int = 80,
    vocab_size: int = 90,
    seed: int = 0,
    seq_targets: bool = False,
) -> FederatedDataset:
    """Shakespeare-GEOMETRY next-char data (ref shakespeare: 80-char
    windows over a 90-char vocab, leaf JSON user shards) from a synthetic
    Markov character process — real leaf downloads are unavailable in this
    environment, so the RNN accuracy loop runs on matched shapes instead.

    The process: char c transitions to (c*7+3) mod V with prob 0.85, else
    uniform — a structure an LSTM learns quickly (ceiling ≈ 0.85 next-char
    accuracy) while a constant-prediction baseline stays at ~1/V. Each
    client's chain starts from a client-specific state; shard sizes are
    ragged (uniform 50-100% of ``samples_per_client``)."""
    rng = np.random.default_rng(seed)
    succ = (np.arange(vocab_size) * 7 + 3) % vocab_size

    def chain(n_chars: int, state: int) -> np.ndarray:
        jump = rng.random(n_chars) < 0.85
        noise = rng.integers(0, vocab_size, n_chars)
        out = np.empty(n_chars, np.int32)
        for t in range(n_chars):
            state = succ[state] if jump[t] else noise[t]
            out[t] = state
        return out

    def windows(n: int, state: int):
        text = chain(n + seq_len, state)
        x = np.stack([text[i : i + seq_len] for i in range(n)]).astype(np.int32)
        if seq_targets:
            # causal-LM labels: every position's next char (task "nwp",
            # transformer path) instead of the window's single next char
            y = np.stack(
                [text[i + 1 : i + 1 + seq_len] for i in range(n)]
            ).astype(np.int32)
        else:
            y = text[seq_len : seq_len + n].astype(np.int32)
        return x, y

    client_x, client_y = [], []
    for c in range(num_clients):
        n = max(4, int(samples_per_client * rng.uniform(0.5, 1.0)))
        x, y = windows(n, int(rng.integers(0, vocab_size)))
        client_x.append(x)
        client_y.append(y)
    xt, yt = windows(256, 1)
    return FederatedDataset(
        name="shakespeare_synth_lm" if seq_targets else "shakespeare_synth",
        client_x=client_x,
        client_y=client_y,
        test_x=xt,
        test_y=yt,
        num_classes=vocab_size,
    )


def synthetic_fedprox(
    alpha: float = 1.0,
    beta: float = 1.0,
    num_clients: int = 30,
    num_classes: int = 10,
    dim: int = 60,
    seed: int = 0,
    min_samples: int = 10,
    max_samples: int = 200,
) -> FederatedDataset:
    """FedProx-style synthetic(α, β): per-client logistic models drawn around
    client-specific means (ref fedml_api/data_preprocessing/synthetic_1_1 and
    the FedProx paper's generator). α controls model heterogeneity, β controls
    data heterogeneity."""
    rng = np.random.default_rng(seed)
    # Power-law client sizes.
    sizes = np.clip(
        (rng.lognormal(4, 2, num_clients)).astype(int), min_samples, max_samples
    )
    B = rng.normal(0, beta, num_clients)
    diag = np.array([(j + 1) ** -1.2 for j in range(dim)])
    client_x, client_y = [], []
    test_x, test_y = [], []
    for i in range(num_clients):
        u = rng.normal(B[i], 1.0, dim)
        W = rng.normal(0, alpha, (dim, num_classes)) + rng.normal(0, 1) * alpha
        b = rng.normal(0, alpha, num_classes)
        n = int(sizes[i]) + 16
        xx = rng.multivariate_normal(u, np.diag(diag), n).astype(np.float32)
        logits = xx @ W + b
        yy = np.argmax(logits, axis=1).astype(np.int32)
        client_x.append(xx[:-16])
        client_y.append(yy[:-16])
        test_x.append(xx[-16:])
        test_y.append(yy[-16:])
    return FederatedDataset(
        name=f"synthetic_{alpha}_{beta}",
        client_x=client_x,
        client_y=client_y,
        test_x=np.concatenate(test_x),
        test_y=np.concatenate(test_y),
        num_classes=num_classes,
    )
