"""Backdoor-poisoned federated datasets + attack-success metric.

TPU-native analog of the reference's edge-case poisoning pipeline
(fedml_api/data_preprocessing/edge_case_examples/data_loader.py:283+
``load_poisoned_dataset``: southwest-airline images labeled "truck",
ARDIS digits labeled as an attacker-chosen class, injected into attacker
clients' shards) and the poisoned-task eval harness
(fedml_api/distributed/fedavg_robust/FedAvgRobustAggregator.py:14-60,
which tracks targeted/backdoor accuracy next to main-task accuracy).

The reference ships real edge-case images; those downloads are unavailable
here, so the trigger is the classic BadNets pixel patch: a bright corner
block + attacker-chosen target label. The *threat model* is identical —
attacker clients hold a mix of clean and poisoned samples, and attack
success is measured as the fraction of triggered non-target test samples
the global model classifies as the target.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

from fedml_tpu.data.base import FederatedDataset


@dataclasses.dataclass(frozen=True)
class PoisonSpec:
    target_label: int = 0
    poison_frac: float = 0.5  # fraction of each attacker shard poisoned
    trigger_size: int = 3  # corner patch side length
    trigger_value: float = 2.5  # written into every channel of the patch


def apply_trigger(x: np.ndarray, spec: PoisonSpec) -> np.ndarray:
    """Stamp the trigger patch onto a batch [N, H, W, C] (or flat [N, D]
    treated as a square image when possible — falls back to the first
    trigger_size**2 features)."""
    x = np.array(x, copy=True)
    t = spec.trigger_size
    if x.ndim >= 3:
        x[:, :t, :t, ...] = spec.trigger_value
    else:
        x[:, : t * t] = spec.trigger_value
    return x


def poison_clients(
    data: FederatedDataset,
    attacker_ids: Sequence[int],
    spec: PoisonSpec = PoisonSpec(),
    seed: int = 0,
) -> FederatedDataset:
    """Return a copy of ``data`` where each attacker client's shard has
    ``poison_frac`` of its samples triggered + relabeled to the target
    (ref load_poisoned_dataset mixes edge-case images into the attacker's
    local loader)."""
    rng = np.random.default_rng(seed)
    client_x = [np.array(cx, copy=True) for cx in data.client_x]
    client_y = [np.array(cy, copy=True) for cy in data.client_y]
    for a in attacker_ids:
        n = len(client_y[a])
        k = max(1, int(round(spec.poison_frac * n)))
        idx = rng.choice(n, size=k, replace=False)
        client_x[a][idx] = apply_trigger(client_x[a][idx], spec)
        client_y[a][idx] = spec.target_label
    return dataclasses.replace(
        data, client_x=client_x, client_y=client_y, name=f"{data.name}_poisoned"
    )


def backdoor_test_set(
    data: FederatedDataset, spec: PoisonSpec = PoisonSpec()
) -> Tuple[np.ndarray, np.ndarray]:
    """Triggered test set for ASR: every *non-target* test sample with the
    trigger stamped; labels are the attacker's target (ref targeted-task
    eval, FedAvgRobustAggregator.py:14-60)."""
    keep = np.asarray(data.test_y) != spec.target_label
    x = apply_trigger(np.asarray(data.test_x)[keep], spec)
    y = np.full(int(keep.sum()), spec.target_label, dtype=np.int32)
    return x, y


def attack_success_rate(model, variables, data, spec: PoisonSpec, eval_fn=None) -> float:
    """Fraction of triggered non-target test samples classified as the
    target — the backdoor accuracy of the reference's harness."""
    from fedml_tpu.train.evaluate import evaluate

    x, y = backdoor_test_set(data, spec)
    _, asr = evaluate(model, variables, x, y, eval_fn=eval_fn)
    return asr
