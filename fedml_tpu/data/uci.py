"""UCI SUSY / Room-Occupancy streaming loader (ref:
fedml_api/data_preprocessing/data_loader_for_susy_and_ro — well,
fedml_api/data_preprocessing/UCI/data_loader_for_susy_and_ro.py, 150 LoC).

The reference feeds decentralized ONLINE learning: each client receives a
stream of (x, y) samples; a β fraction of the stream is "adversarial" —
distributed by k-means cluster (each client gets one cluster's regime, so
streams are locally non-IID in time) — and the remainder is stochastic
(round-robin of the shuffled tail). Labels are binary (SUSY signal /
room occupied). Same construction here with a small numpy k-means (the
reference uses sklearn.KMeans; the dependency isn't worth it for ≤16
centroids), emitting the [N, T, D] / [N, T] worker-major arrays
DecentralizedAPI consumes."""

from __future__ import annotations

import csv
from typing import Optional, Tuple

import numpy as np


def _kmeans(x: np.ndarray, k: int, seed: int = 0, iters: int = 20) -> np.ndarray:
    rng = np.random.default_rng(seed)
    centers = x[rng.choice(len(x), size=k, replace=False)]
    assign = np.zeros(len(x), np.int64)
    for _ in range(iters):
        d = ((x[:, None, :] - centers[None]) ** 2).sum(-1)
        new_assign = d.argmin(1)
        if (new_assign == assign).all():
            break
        assign = new_assign
        for c in range(k):
            m = assign == c
            if m.any():
                centers[c] = x[m].mean(0)
    return assign


def read_uci_csv(
    path: str, label_col: int = 0, max_rows: Optional[int] = None, skip_header: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """CSV → (x [n, d] float32, y [n] {0,1}). SUSY: label first column;
    Room Occupancy: label last (pass label_col=-1), header row present."""
    xs, ys = [], []
    with open(path) as f:
        reader = csv.reader(f)
        if skip_header:
            next(reader, None)
        for i, row in enumerate(reader):
            if max_rows is not None and i >= max_rows:
                break
            vals = [v for v in row if v != ""]
            y = float(vals[label_col])
            feats = vals[:label_col] + vals[label_col + 1 :] if label_col != -1 else vals[:-1]
            xs.append([float(v) for v in feats])
            ys.append(int(y > 0.5))
    return np.asarray(xs, np.float32), np.asarray(ys, np.int32)


def load_uci_streaming(
    path: str,
    num_clients: int,
    samples_per_client: int,
    beta: float = 0.5,
    label_col: int = 0,
    skip_header: bool = False,
    seed: int = 0,
    max_rows: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Build the streaming tensors: x [N, T, D], y [N, T].

    First β·T samples of each client's stream come from "its" k-means
    cluster (adversarial regime, ref load_adversarial_data); the remaining
    (1−β)·T are drawn round-robin from the shuffled remainder (stochastic
    regime, ref load_stochastic_data)."""
    x, y = read_uci_csv(
        path, label_col=label_col, max_rows=max_rows, skip_header=skip_header
    )
    need = num_clients * samples_per_client
    if len(y) < need:
        raise ValueError(f"{path}: need {need} samples, file has {len(y)}")
    rng = np.random.default_rng(seed)
    T = samples_per_client
    t_adv = int(round(beta * T))

    assign = _kmeans(x, num_clients, seed=seed)
    xs = np.zeros((num_clients, T, x.shape[1]), np.float32)
    ys = np.zeros((num_clients, T), np.int32)
    used = np.zeros(len(y), bool)
    for c in range(num_clients):
        idx = np.flatnonzero(assign == c)[:t_adv]
        xs[c, : len(idx)] = x[idx]
        ys[c, : len(idx)] = y[idx]
        used[idx] = True
    pool = np.flatnonzero(~used)
    rng.shuffle(pool)
    ptr = 0
    for c in range(num_clients):
        have = min(t_adv, int((assign == c).sum()))
        take = T - have
        sel = pool[ptr : ptr + take]
        ptr += take
        xs[c, have:] = x[sel]
        ys[c, have:] = y[sel]
    return xs, ys
