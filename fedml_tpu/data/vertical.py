"""Vertical-FL datasets: NUS-WIDE and Lending Club (ref:
fedml_api/data_preprocessing/NUS_WIDE/nus_wide_dataset.py 266 LoC +
lending_club_loan/{lending_club_dataset.py,lending_club_feature_group.py}
305 LoC). These are the reference's real feature-partitioned datasets —
round 1 ran VFL only on synthetic splits.

``VerticalDataset`` is the contract VFLAPI consumes: party-major feature
arrays over the SAME samples (party 0 = guest holds the labels), plus a
test split."""

from __future__ import annotations

import csv
import dataclasses
import os
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class VerticalDataset:
    name: str
    train_xs: List[np.ndarray]  # per party [n, d_k], shared sample axis
    train_y: np.ndarray  # [n] binary
    test_xs: List[np.ndarray]
    test_y: np.ndarray

    @property
    def feature_splits(self):
        return [x.shape[1] for x in self.train_xs]


def zscore(x: np.ndarray, eps: float = 1e-8) -> np.ndarray:
    """StandardScaler equivalent (ref normalize(), lending_club_dataset.py)."""
    x = np.asarray(x, np.float32)
    return (x - x.mean(0)) / (x.std(0) + eps)


# --------------------------------------------------------------------------
# NUS-WIDE (ref nus_wide_dataset.py): party A = 634 low-level image
# features, party B = 1k text tags; labels = top-k concept one-hots reduced
# to "is target concept". On-disk layout mirrored from the reference:
#   Groundtruth/TrainTestLabels/Labels_<concept>_<Train|Test>.txt
#   Low_Level_Features/<Train|Test>_Normalized_<kind>.dat  (space-sep)
#   NUS_WID_Tags/<Train|Test>_Tags1k.dat                   (tab-sep)
# --------------------------------------------------------------------------


def _read_matrix(path: str, sep: Optional[str]) -> np.ndarray:
    rows = []
    with open(path) as f:
        for line in f:
            vals = line.split(sep) if sep else line.split()
            vals = [v for v in vals if v.strip() != ""]
            if vals:
                rows.append([float(v) for v in vals])
    return np.asarray(rows, np.float32)


def _nus_split(data_dir: str, labels: Sequence[str], dtype: str):
    lab_dir = os.path.join(data_dir, "Groundtruth", "TrainTestLabels")
    cols = [
        _read_matrix(os.path.join(lab_dir, f"Labels_{l}_{dtype}.txt"), None)[:, 0]
        for l in labels
    ]
    onehot = np.stack(cols, axis=1)
    # samples carrying exactly one selected concept (ref sum(axis=1)==1)
    keep = onehot.sum(1) == 1 if len(labels) > 1 else np.ones(len(onehot), bool)

    feat_dir = os.path.join(data_dir, "Low_Level_Features")
    feats = [
        _read_matrix(os.path.join(feat_dir, f), None)
        for f in sorted(os.listdir(feat_dir))
        if f.startswith(f"{dtype}_Normalized")
    ]
    xa = np.concatenate(feats, axis=1)[keep]
    tags_path = os.path.join(data_dir, "NUS_WID_Tags", f"{dtype}_Tags1k.dat")
    xb = _read_matrix(tags_path, "\t")[keep]
    y = onehot[keep].argmax(1).astype(np.int32)
    return xa, xb, y


def load_nus_wide(
    data_dir: str,
    selected_labels: Sequence[str] = ("buildings", "grass", "animal", "water", "person"),
    target_label_idx: int = 0,
    parties: int = 2,
    max_samples: int = -1,
) -> VerticalDataset:
    """2-party (image features | tags) or 3-party (tags halved — ref
    get_labeled_data_with_3_party) vertical dataset; y = 1 iff the sample's
    concept == selected_labels[target_label_idx]."""
    out = []
    for dtype in ("Train", "Test"):
        xa, xb, y = _nus_split(data_dir, selected_labels, dtype)
        if max_samples != -1:
            xa, xb, y = xa[:max_samples], xb[:max_samples], y[:max_samples]
        yy = (y == target_label_idx).astype(np.float32)
        if parties == 2:
            xs = [xa, xb]
        elif parties == 3:
            h = xb.shape[1] // 2
            xs = [xa, xb[:, :h], xb[:, h:]]
        else:
            raise ValueError("parties must be 2 or 3")
        out.append((xs, yy))
    (train_xs, train_y), (test_xs, test_y) = out
    return VerticalDataset("nus_wide", train_xs, train_y, test_xs, test_y)


# --------------------------------------------------------------------------
# Lending Club (ref lending_club_dataset.py + lending_club_feature_group.py):
# one CSV of loan records; the VFL parties are the reference's FEATURE
# GROUPS — qualification features vs loan/debt/repayment features — and the
# binary target is good/bad loan.
# --------------------------------------------------------------------------

# Column groups from the reference's feature-group module (subset kept to
# numeric columns; categorical maps below mirror lending_club_dataset.py).
QUALIFICATION_FEATURES = [
    "annual_inc", "emp_length", "home_ownership", "verification_status", "grade",
]
LOAN_FEATURES = [
    "loan_amnt", "int_rate", "installment", "term", "purpose", "dti",
]
REPAYMENT_FEATURES = [
    "total_pymnt", "total_rec_int", "total_rec_prncp", "last_pymnt_amnt",
]

GRADE_MAP = {"A": 6, "B": 5, "C": 4, "D": 3, "E": 2, "F": 1, "G": 0}
EMP_LENGTH_MAP = {
    "": 0, "< 1 year": 1, "1 year": 2, "2 years": 2, "3 years": 2,
    "4 years": 3, "5 years": 3, "6 years": 3, "7 years": 4, "8 years": 4,
    "9 years": 4, "10+ years": 5,
}
HOME_OWNERSHIP_MAP = {"RENT": 0, "MORTGAGE": 1, "OWN": 2, "ANY": 3, "NONE": 3, "OTHER": 3}
VERIFICATION_MAP = {"Not Verified": 0, "Source Verified": 1, "Verified": 2}
TERM_MAP = {" 36 months": 0, "36 months": 0, " 60 months": 1, "60 months": 1}
PURPOSE_MAP = {
    "debt_consolidation": 0, "credit_card": 0, "small_business": 1,
    "educational": 2, "car": 3, "other": 3, "vacation": 3, "house": 3,
    "home_improvement": 3, "major_purchase": 3, "medical": 3,
    "renewable_energy": 3, "moving": 3, "wedding": 3,
}
BAD_LOAN_STATUSES = {
    "Charged Off", "Default",
    "Does not meet the credit policy. Status:Charged Off",
    "In Grace Period", "Late (16-30 days)", "Late (31-120 days)",
}
_CATEGORICAL = {
    "grade": GRADE_MAP,
    "emp_length": EMP_LENGTH_MAP,
    "home_ownership": HOME_OWNERSHIP_MAP,
    "verification_status": VERIFICATION_MAP,
    "term": TERM_MAP,
    "purpose": PURPOSE_MAP,
}


def _encode(col: str, val: str) -> float:
    table = _CATEGORICAL.get(col)
    if table is not None:
        return float(table.get(val, 0))
    try:
        return float(val)
    except ValueError:
        return 0.0


def load_lending_club(
    csv_path: str,
    max_rows: Optional[int] = None,
    test_frac: float = 0.2,
    seed: int = 0,
) -> VerticalDataset:
    """CSV → 3-party vertical dataset: guest holds qualification features +
    the good/bad-loan label; hosts hold loan-terms and repayment features
    (ref target_map + loan_condition, lending_club_dataset.py)."""
    groups = [QUALIFICATION_FEATURES, LOAN_FEATURES, REPAYMENT_FEATURES]
    with open(csv_path) as f:
        reader = csv.DictReader(f)
        rows = []
        for i, r in enumerate(reader):
            if max_rows is not None and i >= max_rows:
                break
            rows.append(r)
    if not rows:
        raise ValueError(f"{csv_path}: empty CSV")
    present = [[c for c in g if c in rows[0]] for g in groups]
    if any(not g for g in present):
        raise ValueError(
            f"{csv_path}: each party needs at least one of its columns; "
            f"have {sorted(rows[0])}"
        )
    xs = [
        zscore(np.asarray([[_encode(c, r[c]) for c in g] for r in rows], np.float32))
        for g in present
    ]
    y = np.asarray(
        [1.0 if r.get("loan_status", "") in BAD_LOAN_STATUSES else 0.0 for r in rows],
        np.float32,
    )
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(y))
    n_test = max(1, int(round(test_frac * len(y))))
    te, tr = perm[:n_test], perm[n_test:]
    return VerticalDataset(
        "lending_club",
        [x[tr] for x in xs],
        y[tr],
        [x[te] for x in xs],
        y[te],
    )


def run_vfl(dataset: VerticalDataset, epochs: int = 10, lr: float = 0.05, batch_size: int = 64, hidden_dim: int = 16, seed: int = 0):
    """Train VFLAPI on a VerticalDataset; returns (api, final_stats) — the
    wiring that makes VFL run on real-shaped data (VERDICT r1 missing #3)."""
    from fedml_tpu.algorithms.vertical_fl import VFLAPI

    api = VFLAPI(
        feature_splits=dataset.feature_splits,
        hidden_dim=hidden_dim,
        lr=lr,
        seed=seed,
    )
    stats = {}
    for _ in range(epochs):
        stats = api.train_epoch(dataset.train_xs, dataset.train_y, batch_size=batch_size)
    return api, stats
