"""Centralized image datasets + LDA partitioning — CIFAR-10/100, CINIC-10
(ref: fedml_api/data_preprocessing/base.py:100-260 CifarDataLoader template +
cifar10/cifar100/cinic10 subclasses).

These datasets ship as one global train set; the federated split is
synthesized by the LDA/homo partitioner (partition/noniid.py — the pure-numpy
port of fedml_core/non_iid_partition/). Normalization constants match the
reference exactly (cifar10/data_loader.py:6-7, cifar100:12-13, cinic10:14-15).
Cutout/random-crop augmentation (base.py:136-146) is deliberately host-free:
it runs inside the jit'd train step (train/augment.py, enabled with
``TrainConfig.augment="cifar"``), so stored samples stay canonical and the
HBM-resident store keeps working; eval parity doesn't need it."""

from __future__ import annotations

import os
import pickle
from typing import List, Optional, Tuple

import numpy as np

from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.partition.noniid import homo_partition, lda_partition

CIFAR10_MEAN = (0.49139968, 0.48215827, 0.44653124)
CIFAR10_STD = (0.24703233, 0.24348505, 0.26158768)
CIFAR100_MEAN = (0.5071, 0.4865, 0.4409)
CIFAR100_STD = (0.2673, 0.2564, 0.2762)
CINIC10_MEAN = (0.47889522, 0.47227842, 0.43047404)
CINIC10_STD = (0.24205776, 0.23828046, 0.25874835)


def _normalize(x_u8: np.ndarray, mean, std) -> np.ndarray:
    x = x_u8.astype(np.float32) / 255.0
    return (x - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)


def _load_cifar10_raw(data_dir: str):
    d = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(d):
        raise FileNotFoundError(
            f"CIFAR-10 not found at {d} (python pickle batches; "
            "ref data/cifar10/download_cifar10.sh)"
        )

    def read(fname):
        with open(os.path.join(d, fname), "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        x = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y = np.asarray(batch[b"labels"], np.int32)
        return x, y

    xs, ys = zip(*(read(f"data_batch_{i}") for i in range(1, 6)))
    tx, ty = read("test_batch")
    return np.concatenate(xs), np.concatenate(ys), tx, ty


def _load_cifar100_raw(data_dir: str):
    d = os.path.join(data_dir, "cifar-100-python")
    if not os.path.isdir(d):
        raise FileNotFoundError(f"CIFAR-100 not found at {d}")

    def read(fname):
        with open(os.path.join(d, fname), "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        x = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        y = np.asarray(batch[b"fine_labels"], np.int32)
        return x, y

    x, y = read("train")
    tx, ty = read("test")
    return x, y, tx, ty


def _load_cinic10_raw(data_dir: str):
    """CINIC-10 ImageFolder (train/ test/ with one subdir per class)."""
    from PIL import Image

    root = data_dir
    if not os.path.isdir(os.path.join(root, "train")):
        raise FileNotFoundError(
            f"CINIC-10 not found at {root} (ImageFolder layout train/<class>/*.png)"
        )

    def read(split):
        xs, ys = [], []
        classes = sorted(os.listdir(os.path.join(root, split)))
        for yi, c in enumerate(classes):
            cdir = os.path.join(root, split, c)
            for fn in sorted(os.listdir(cdir)):
                with Image.open(os.path.join(cdir, fn)) as im:
                    xs.append(np.asarray(im.convert("RGB"), np.uint8))
                ys.append(yi)
        return np.stack(xs), np.asarray(ys, np.int32)

    x, y = read("train")
    tx, ty = read("test")
    return x, y, tx, ty


_DATASETS = {
    "cifar10": (_load_cifar10_raw, CIFAR10_MEAN, CIFAR10_STD, 10),
    "cifar100": (_load_cifar100_raw, CIFAR100_MEAN, CIFAR100_STD, 100),
    "cinic10": (_load_cinic10_raw, CINIC10_MEAN, CINIC10_STD, 10),
}


def load_cifar_family(
    name: str,
    data_dir: str,
    num_clients: int,
    partition_method: str = "hetero",
    partition_alpha: float = 0.5,
    seed: int = 0,
) -> FederatedDataset:
    """Global train set → LDA ('hetero') or uniform ('homo') client shards
    (ref base.py:165-212 partition_data)."""
    loader, mean, std, num_classes = _DATASETS[name]
    x, y, tx, ty = loader(data_dir)
    x = _normalize(x, mean, std)
    tx = _normalize(tx, mean, std)
    if partition_method == "homo":
        idx_map = homo_partition(len(y), num_clients, np.random.default_rng(seed))
    else:
        idx_map = lda_partition(y, num_clients, partition_alpha, seed=seed)
    client_x = [x[idx_map[i]] for i in range(num_clients)]
    client_y = [y[idx_map[i]] for i in range(num_clients)]
    return FederatedDataset(
        name=name,
        client_x=client_x,
        client_y=client_y,
        test_x=tx,
        test_y=ty,
        num_classes=num_classes,
    )
