"""StackOverflow loaders — tag prediction (LR, multi-label) and next-word
prediction (ref: fedml_api/data_preprocessing/{stackoverflow_lr,
stackoverflow_nwp}/; h5 'examples'/{cid}/{tokens,title,tags}; vocab from
stackoverflow.word_count / stackoverflow.tag_count sidecar files).

- **lr** (ref stackoverflow_lr/utils.py:68-97): input = mean bag-of-words over
  the top-10k vocab of tokens+title, target = multi-hot over top-500 tags →
  task "tag" (sigmoid BCE).
- **nwp** (ref stackoverflow_nwp/utils.py): token ids over top-10k vocab with
  pad/bos/eos + hash-bucket OOV, sequences of 20 + next-word targets →
  task "nwp".

The full dataset is 342k clients; ``max_clients`` bounds host RAM."""

from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from fedml_tpu.data.base import FederatedDataset

WORD_COUNT_FILE = "stackoverflow.word_count"
TAG_COUNT_FILE = "stackoverflow.tag_count"
TRAIN_FILE = "stackoverflow_train.h5"
TEST_FILE = "stackoverflow_test.h5"
_EXAMPLE = "examples"


def _require(path: str):
    if not os.path.exists(path):
        raise FileNotFoundError(f"stackoverflow file not found: {path}")
    return path


def load_word_vocab(data_dir: str, vocab_size: int = 10000) -> dict:
    """Top-N words from the word_count file (one 'word count' line each,
    ref stackoverflow_lr/utils.py:35-55)."""
    with open(_require(os.path.join(data_dir, WORD_COUNT_FILE))) as f:
        words = [next(f).split()[0] for _ in range(vocab_size)]
    return {w: i for i, w in enumerate(words)}


def load_tag_vocab(data_dir: str, tag_size: int = 500) -> dict:
    """Top-N tags from the tag_count JSON (ref utils.py:42-45)."""
    with open(_require(os.path.join(data_dir, TAG_COUNT_FILE))) as f:
        tags = json.load(f)
    return {t: i for i, t in enumerate(list(tags.keys())[:tag_size])}


def _decode(arr) -> List[str]:
    return [s.decode("utf-8") if isinstance(s, bytes) else str(s) for s in arr]


def _bag_of_words(sentences: List[str], word_dict: dict) -> np.ndarray:
    V = len(word_dict)
    out = np.zeros((len(sentences), V), np.float32)
    for i, s in enumerate(sentences):
        toks = s.split(" ")
        hits = [word_dict[t] for t in toks if t in word_dict]
        if toks:
            for h in hits:
                out[i, h] += 1.0
            out[i] /= len(toks)  # mean over tokens incl. OOV (ref :78-83)
    return out


def _multi_hot_tags(tag_strs: List[str], tag_dict: dict) -> np.ndarray:
    T = len(tag_dict)
    out = np.zeros((len(tag_strs), T), np.float32)
    for i, ts in enumerate(tag_strs):
        for t in ts.split("|"):
            if t in tag_dict:
                out[i, tag_dict[t]] = 1.0
    return out


def _to_ids(sentence: str, word_dict: dict, max_seq_len: int = 20, oov_buckets: int = 1):
    """pad=0, vocab ids shifted +1, bos/eos, hash OOV (ref nwp/utils.py)."""
    V = len(word_dict)
    bos, eos = V + 1, V + 2

    def wid(w):
        return word_dict[w] + 1 if w in word_dict else V + 3 + (hash(w) % oov_buckets)

    toks = [bos] + [wid(w) for w in sentence.split(" ")[:max_seq_len]] + [eos]
    toks = toks[: max_seq_len + 1]
    toks += [0] * (max_seq_len + 1 - len(toks))
    return toks


def load_stackoverflow_lr(
    data_dir: str, max_clients: Optional[int] = 1000, vocab_size: int = 10000, tag_size: int = 500
) -> FederatedDataset:
    import h5py

    word_dict = load_word_vocab(data_dir, vocab_size)
    tag_dict = load_tag_vocab(data_dir, tag_size)

    def prep(g):
        sents = [
            f"{t} {ti}".strip()
            for t, ti in zip(_decode(g["tokens"]), _decode(g["title"]))
        ]
        return _bag_of_words(sents, word_dict), _multi_hot_tags(
            _decode(g["tags"]), tag_dict
        )

    with h5py.File(_require(os.path.join(data_dir, TRAIN_FILE)), "r") as tr, h5py.File(
        _require(os.path.join(data_dir, TEST_FILE)), "r"
    ) as te:
        ids = sorted(tr[_EXAMPLE].keys())
        if max_clients:
            ids = ids[:max_clients]
        client_x, client_y = [], []
        for cid in ids:
            x, y = prep(tr[_EXAMPLE][cid])
            client_x.append(x)
            client_y.append(y)
        t_ids = sorted(te[_EXAMPLE].keys())[: max_clients or None]
        txs, tys = zip(*(prep(te[_EXAMPLE][c]) for c in t_ids))
    return FederatedDataset(
        name="stackoverflow_lr",
        client_x=client_x,
        client_y=client_y,
        test_x=np.concatenate(txs),
        test_y=np.concatenate(tys),
        num_classes=tag_size,
    )


def load_stackoverflow_nwp(
    data_dir: str, max_clients: Optional[int] = 1000, vocab_size: int = 10000, max_seq_len: int = 20
) -> FederatedDataset:
    import h5py

    word_dict = load_word_vocab(data_dir, vocab_size)

    def prep(g):
        seqs = np.asarray(
            [_to_ids(s, word_dict, max_seq_len) for s in _decode(g["tokens"])],
            np.int32,
        )
        if not len(seqs):
            seqs = np.zeros((0, max_seq_len + 1), np.int32)
        return seqs[:, :-1], seqs[:, 1:]

    with h5py.File(_require(os.path.join(data_dir, TRAIN_FILE)), "r") as tr, h5py.File(
        _require(os.path.join(data_dir, TEST_FILE)), "r"
    ) as te:
        ids = sorted(tr[_EXAMPLE].keys())
        if max_clients:
            ids = ids[:max_clients]
        client_x, client_y = [], []
        for cid in ids:
            x, y = prep(tr[_EXAMPLE][cid])
            client_x.append(x)
            client_y.append(y)
        t_ids = sorted(te[_EXAMPLE].keys())[: max_clients or None]
        txs, tys = zip(*(prep(te[_EXAMPLE][c]) for c in t_ids))
    return FederatedDataset(
        name="stackoverflow_nwp",
        client_x=client_x,
        client_y=client_y,
        test_x=np.concatenate([t for t in txs if len(t)]),
        test_y=np.concatenate([t for t in tys if len(t)]),
        num_classes=vocab_size + 4,
    )
