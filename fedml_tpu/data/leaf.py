"""Leaf-format (JSON user shards) dataset loaders — MNIST, FEMNIST,
Shakespeare (ref: fedml_api/data_preprocessing/MNIST/data_loader.py:14-110,
shakespeare/data_loader.py:19-60; format: .json files with keys ``users``,
``user_data`` {uid: {"x": [...], "y": [...]}}, ``num_samples``).

Raw data is not vendored (the reference downloads it in CI,
CI-install.sh:39-80); loaders raise FileNotFoundError with the expected
layout when the directory is missing."""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from fedml_tpu.data.base import FederatedDataset, concat_nonempty
from fedml_tpu.data import text as T


def _read_leaf_dir(path: str) -> Tuple[List[str], Dict]:
    if not os.path.isdir(path):
        raise FileNotFoundError(
            f"leaf data dir not found: {path} (expected *.json files with "
            "'users'/'user_data' keys, as produced by the leaf benchmark "
            "download scripts — ref data/MNIST/download_and_unzip.sh)"
        )
    users: List[str] = []
    user_data: Dict = {}
    for f in sorted(os.listdir(path)):
        if not f.endswith(".json"):
            continue
        with open(os.path.join(path, f)) as fd:
            cdata = json.load(fd)
        users.extend(cdata["users"])
        user_data.update(cdata["user_data"])
    return sorted(set(users)), user_data


def load_leaf(
    data_dir: str,
    transform_x: Callable[[list], np.ndarray],
    transform_y: Callable[[list], np.ndarray],
    num_classes: int,
    name: str,
    max_clients: Optional[int] = None,
) -> FederatedDataset:
    """Generic leaf reader: train/ and test/ subdirs, same user sets
    (ref MNIST read_data, data_loader.py:19-57)."""
    train_users, train_data = _read_leaf_dir(os.path.join(data_dir, "train"))
    _, test_data = _read_leaf_dir(os.path.join(data_dir, "test"))
    if max_clients:
        train_users = train_users[:max_clients]
    client_x, client_y, ctest_x, ctest_y = [], [], [], []
    for u in train_users:
        client_x.append(transform_x(train_data[u]["x"]))
        client_y.append(transform_y(train_data[u]["y"]))
        td = test_data.get(u, {"x": [], "y": []})
        ctest_x.append(transform_x(td["x"]))
        ctest_y.append(transform_y(td["y"]))
    test_x = concat_nonempty(ctest_x, client_x[0])
    test_y = concat_nonempty(ctest_y, client_y[0])
    return FederatedDataset(
        name=name,
        client_x=client_x,
        client_y=client_y,
        test_x=test_x,
        test_y=test_y,
        num_classes=num_classes,
        client_test_x=ctest_x,
        client_test_y=ctest_y,
    )


def _mnist_x(rows: list) -> np.ndarray:
    a = np.asarray(rows, np.float32)
    return a.reshape((-1, 28, 28, 1)) if a.size else a.reshape((0, 28, 28, 1))


def _int_y(rows: list) -> np.ndarray:
    return np.asarray(rows, np.int32)


def load_mnist(data_dir: str, max_clients: Optional[int] = None) -> FederatedDataset:
    """Leaf MNIST: 1000 users, flat-784 floats (ref MNIST/data_loader.py).
    Reshaped to 28×28×1 NHWC for TPU convs; the LR model flattens again."""
    return load_leaf(data_dir, _mnist_x, _int_y, 10, "mnist", max_clients)


def load_femnist_leaf(data_dir: str, max_clients: Optional[int] = None) -> FederatedDataset:
    return load_leaf(data_dir, _mnist_x, _int_y, 62, "femnist", max_clients)


def _shakespeare_x(rows: list) -> np.ndarray:
    if not rows:
        return np.zeros((0, T.SEQUENCE_LENGTH), np.int32)
    return np.asarray([T.chars_to_ids(s) for s in rows], np.int32)


def _shakespeare_y(rows: list) -> np.ndarray:
    return np.asarray([T.char_to_id(c) for c in rows], np.int32)


def load_shakespeare(data_dir: str, max_clients: Optional[int] = None) -> FederatedDataset:
    """Leaf Shakespeare: x = 80-char window, y = next char → next-char
    classification over the 90-symbol vocab (ref shakespeare/data_loader.py +
    language_utils.py word_to_indices/letter_to_index)."""
    return load_leaf(
        data_dir, _shakespeare_x, _shakespeare_y, T.VOCAB_SIZE, "shakespeare", max_clients
    )
