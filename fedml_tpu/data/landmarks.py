"""Google Landmarks (gld23k/gld160k) federated loader (ref:
fedml_api/data_preprocessing/Landmarks/data_loader.py, 297 LoC).

The reference reads CSV mapping files — rows of (user_id, image_id, class)
— and builds one shard per user_id (a *naturally federated* split, unlike
the synthetic LDA partitions): ``get_mapping_per_user`` at
data_loader.py:60-101. Same here: the train CSV defines clients keyed by
user_id; the test CSV (no user column needed) is the central test set.
Images load from ``data_dir/images/<image_id>.<ext>`` via PIL (or .npy
fixtures), normalized with the reference's 0.5/0.5 statistics."""

from __future__ import annotations

import csv
import os
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from fedml_tpu.data.base import FederatedDataset

MEAN, STD = 0.5, 0.5
_EXTS = (".jpg", ".jpeg", ".png", ".npy")


def _read_mapping(path: str) -> List[dict]:
    with open(path) as f:
        rows = list(csv.DictReader(f))
    if rows and not {"image_id", "class"} <= set(rows[0]):
        raise ValueError(
            f"{path}: mapping CSV needs image_id and class columns "
            f"(got {sorted(rows[0])})"  # ref raises the same complaint
        )
    return rows


def _find_image(images_dir: str, image_id: str) -> str:
    for ext in _EXTS:
        p = os.path.join(images_dir, image_id + ext)
        if os.path.exists(p):
            return p
    raise FileNotFoundError(f"no image file for id {image_id} in {images_dir}")


def _load(images_dir: str, image_id: str, image_size: int) -> np.ndarray:
    path = _find_image(images_dir, image_id)
    if path.endswith(".npy"):
        return np.asarray(np.load(path), np.float32)
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB").resize((image_size, image_size))
        return (np.asarray(im, np.float32) / 255.0 - MEAN) / STD


def load_landmarks(
    data_dir: str,
    train_map_file: str = "mini_gld_train_split.csv",
    test_map_file: str = "mini_gld_test.csv",
    image_size: int = 224,
    max_clients: Optional[int] = None,
) -> FederatedDataset:
    images_dir = os.path.join(data_dir, "images")
    train_rows = _read_mapping(os.path.join(data_dir, train_map_file))
    test_rows = _read_mapping(os.path.join(data_dir, test_map_file))

    per_user: Dict[str, List[dict]] = defaultdict(list)
    for r in train_rows:
        per_user[r.get("user_id", "0")].append(r)
    users = sorted(per_user)[: max_clients or None]

    classes = sorted(
        {r["class"] for r in train_rows} | {r["class"] for r in test_rows}
    )
    cls_idx = {c: i for i, c in enumerate(classes)}

    client_x, client_y = [], []
    for u in users:
        rows = per_user[u]
        client_x.append(
            np.stack([_load(images_dir, r["image_id"], image_size) for r in rows])
        )
        client_y.append(
            np.asarray([cls_idx[r["class"]] for r in rows], np.int32)
        )
    test_x = np.stack(
        [_load(images_dir, r["image_id"], image_size) for r in test_rows]
    )
    test_y = np.asarray([cls_idx[r["class"]] for r in test_rows], np.int32)
    return FederatedDataset(
        name="landmarks",
        client_x=client_x,
        client_y=client_y,
        test_x=test_x,
        test_y=test_y,
        num_classes=len(classes),
    )
