"""FEMNIST-shaped synthetic benchmark data.

The real Federated-EMNIST download (ref CI-install.sh:39-80,
data/FederatedEMNIST/download.sh) needs network access; for benchmarking and
dry-runs we generate data with the exact FEMNIST geometry — 28×28×1 images,
62 classes, power-law ragged client shards around the real dataset's ~226
samples/client mean — so compiled shapes and FLOPs match the real workload.
The real h5 loader lives in data/femnist.py and is used when files exist."""

from __future__ import annotations

import numpy as np

from fedml_tpu.data.base import FederatedDataset


def femnist_synthetic(
    num_clients: int = 3400,
    mean_samples: int = 226,
    seed: int = 0,
    num_classes: int = 62,
) -> FederatedDataset:
    rng = np.random.default_rng(seed)
    sizes = np.clip(
        rng.lognormal(np.log(mean_samples), 0.4, num_clients).astype(int),
        16,
        1024,
    )
    means = rng.normal(0.0, 1.0, size=(num_classes, 16))
    proj = rng.normal(0.0, 0.3, size=(16, 28 * 28)).astype(np.float32)

    def gen(n):
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        lat = means[y] + rng.normal(0.0, 0.6, size=(n, 16))
        x = (lat @ proj + rng.normal(0, 0.3, size=(n, 28 * 28))).astype(
            np.float32
        )
        return x.reshape(n, 28, 28, 1), y

    client_x, client_y = [], []
    for i in range(num_clients):
        x, y = gen(int(sizes[i]))
        client_x.append(x)
        client_y.append(y)
    tx, ty = gen(2048)
    return FederatedDataset(
        name="femnist_synth",
        client_x=client_x,
        client_y=client_y,
        test_x=tx,
        test_y=ty,
        num_classes=num_classes,
    )


def femnist_synthetic_lda(
    num_clients: int = 128,
    alpha: float = 0.5,
    mean_samples: int = 120,
    seed: int = 0,
    num_classes: int = 62,
    class_sep: float = 0.55,
    latent_noise: float = 1.0,
    pixel_noise: float = 0.45,
    label_noise: float = 0.08,
) -> FederatedDataset:
    """The HARD femnist-geometry benchmark regime (VERDICT r2 Missing #1):
    same 28x28x1 / 62-class shapes, but built so a round-budget benchmark
    can FAIL and discriminate algorithms —

    - clients are LDA(alpha) label-skewed (partition/noniid.py, the
      reference's non-IID story, noniid_partition.py:6-73): at alpha=0.1 a
      client sees a handful of classes, so multi-epoch local training
      drifts and plain FedAvg pays for it;
    - classes overlap (class_sep shrinks the latent mean spread, latent/
      pixel noise grow) and label_noise caps the reachable accuracy well
      below 100%, so nothing saturates in tens of rounds;
    - the latent->pixel map is fixed per seed, so fp32-vs-bf16 parity is
      judged on a non-trivial decision boundary.

    Unlike :func:`femnist_synthetic` (uniform labels per client, wide
    separation — saturates in ~30 rounds), this regime needs 100+ rounds
    of FedAvg at the reference's 10-clients-per-round cadence to cross a
    ~0.6 target."""
    from fedml_tpu.partition.noniid import lda_partition

    rng = np.random.default_rng(seed)
    n_total = num_clients * mean_samples
    means = rng.normal(0.0, class_sep, size=(num_classes, 16))
    proj = rng.normal(0.0, 0.3, size=(16, 28 * 28)).astype(np.float32)

    def gen(n, r):
        y = r.integers(0, num_classes, size=n).astype(np.int32)
        lat = means[y] + r.normal(0.0, latent_noise, size=(n, 16))
        x = (lat @ proj + r.normal(0, pixel_noise, size=(n, 28 * 28))).astype(
            np.float32
        )
        flip = r.random(n) < label_noise
        y = np.where(flip, r.integers(0, num_classes, size=n), y).astype(
            np.int32
        )
        return x.reshape(n, 28, 28, 1), y

    x, y = gen(n_total, rng)
    idx_map = lda_partition(y, num_clients, alpha, seed=seed)
    client_x = [x[idx] for idx in idx_map.values()]
    client_y = [y[idx] for idx in idx_map.values()]
    tx, ty = gen(4096, np.random.default_rng(seed + 1))
    return FederatedDataset(
        name=f"femnist_lda{alpha}",
        client_x=client_x,
        client_y=client_y,
        test_x=tx,
        test_y=ty,
        num_classes=num_classes,
    )
