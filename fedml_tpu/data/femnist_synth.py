"""FEMNIST-shaped synthetic benchmark data.

The real Federated-EMNIST download (ref CI-install.sh:39-80,
data/FederatedEMNIST/download.sh) needs network access; for benchmarking and
dry-runs we generate data with the exact FEMNIST geometry — 28×28×1 images,
62 classes, power-law ragged client shards around the real dataset's ~226
samples/client mean — so compiled shapes and FLOPs match the real workload.
The real h5 loader lives in data/femnist.py and is used when files exist."""

from __future__ import annotations

import numpy as np

from fedml_tpu.data.base import FederatedDataset


def femnist_synthetic(
    num_clients: int = 3400,
    mean_samples: int = 226,
    seed: int = 0,
    num_classes: int = 62,
) -> FederatedDataset:
    rng = np.random.default_rng(seed)
    sizes = np.clip(
        rng.lognormal(np.log(mean_samples), 0.4, num_clients).astype(int),
        16,
        1024,
    )
    means = rng.normal(0.0, 1.0, size=(num_classes, 16))
    proj = rng.normal(0.0, 0.3, size=(16, 28 * 28)).astype(np.float32)

    def gen(n):
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        lat = means[y] + rng.normal(0.0, 0.6, size=(n, 16))
        x = (lat @ proj + rng.normal(0, 0.3, size=(n, 28 * 28))).astype(
            np.float32
        )
        return x.reshape(n, 28, 28, 1), y

    client_x, client_y = [], []
    for i in range(num_clients):
        x, y = gen(int(sizes[i]))
        client_x.append(x)
        client_y.append(y)
    tx, ty = gen(2048)
    return FederatedDataset(
        name="femnist_synth",
        client_x=client_x,
        client_y=client_y,
        test_x=tx,
        test_y=ty,
        num_classes=num_classes,
    )
