from fedml_tpu.data.base import ClientBatch, FederatedDataset, stack_clients

__all__ = ["ClientBatch", "FederatedDataset", "stack_clients", "load_dataset"]


def load_dataset(config):
    """Dataset-name → loader dispatch (ref fedml_experiments/base.py:49-101)."""
    from fedml_tpu.data import registry

    return registry.load(config)
