"""ImageNet federated loader (ref:
fedml_api/data_preprocessing/ImageNet/data_loader.py + datasets.py, 543 LoC).

The reference wraps torchvision ImageFolder / an HDF5 dump and partitions
sample indices across clients (`ImageNetTruncated` + net_dataidx_map). Here:
an ImageFolder-style tree is scanned directly —

    data_dir/train/<class_name>/*.{jpg,png,npy}
    data_dir/val/<class_name>/*.{jpg,png,npy}

— decoded with PIL (or np.load for .npy fixtures), resized, normalized with
the standard ImageNet statistics (data_loader.py IMAGENET_MEAN/STD), and
partitioned with the shared homo/LDA partitioners. Images are materialised
as float32 NHWC numpy so the result plugs into stack_clients / the device
store like every other dataset; for datasets that exceed host RAM, pass a
smaller ``image_size`` (the reference's 224 crop is the default) or
``max_per_class``."""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from fedml_tpu.data.base import FederatedDataset
from fedml_tpu.partition.noniid import homo_partition, lda_partition

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".npy")


def _load_image(path: str, image_size: int) -> np.ndarray:
    if path.endswith(".npy"):
        arr = np.asarray(np.load(path), np.float32)
        if arr.shape[:2] != (image_size, image_size):
            raise ValueError(
                f"{path}: npy fixture must already be {image_size}x{image_size}"
            )
        return arr
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB").resize((image_size, image_size))
        return np.asarray(im, np.float32) / 255.0


def _scan_split(split_dir: str, image_size: int, max_per_class: Optional[int]):
    classes = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d))
    )
    xs: List[np.ndarray] = []
    ys: List[int] = []
    for ci, cname in enumerate(classes):
        cdir = os.path.join(split_dir, cname)
        files = sorted(
            f for f in os.listdir(cdir) if f.lower().endswith(_IMG_EXTS)
        )[: max_per_class or None]
        for f in files:
            xs.append(_load_image(os.path.join(cdir, f), image_size))
            ys.append(ci)
    x = np.stack(xs) if xs else np.zeros((0, image_size, image_size, 3), np.float32)
    x = (x - IMAGENET_MEAN) / IMAGENET_STD
    return x, np.asarray(ys, np.int32), classes


def load_imagenet(
    data_dir: str,
    num_clients: int = 100,
    image_size: int = 224,
    partition_method: str = "homo",
    partition_alpha: float = 0.5,
    max_per_class: Optional[int] = None,
    max_clients: Optional[int] = None,
    seed: int = 0,
) -> FederatedDataset:
    num_clients = max_clients or num_clients
    train_x, train_y, classes = _scan_split(
        os.path.join(data_dir, "train"), image_size, max_per_class
    )
    val_dir = os.path.join(data_dir, "val")
    if os.path.isdir(val_dir):
        test_x, test_y, _ = _scan_split(val_dir, image_size, max_per_class)
    else:  # no val split vendored: hold out the tail of train
        k = max(1, len(train_y) // 10)
        test_x, test_y = train_x[-k:], train_y[-k:]
        train_x, train_y = train_x[:-k], train_y[:-k]

    rng = np.random.default_rng(seed)
    if partition_method == "homo":
        idx_map = homo_partition(len(train_y), num_clients, rng)
    else:
        idx_map = lda_partition(train_y, num_clients, partition_alpha, seed=seed)
    return FederatedDataset(
        name="imagenet",
        client_x=[train_x[idx_map[i]] for i in range(num_clients)],
        client_y=[train_y[idx_map[i]] for i in range(num_clients)],
        test_x=test_x,
        test_y=test_y,
        num_classes=len(classes),
    )


def _scan_split_paths(split_dir: str, max_per_class: Optional[int]):
    """Metadata-only scan: (file paths, labels, class names) — no decode."""
    classes = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d))
    )
    paths: List[str] = []
    ys: List[int] = []
    for ci, cname in enumerate(classes):
        cdir = os.path.join(split_dir, cname)
        files = sorted(
            f for f in os.listdir(cdir) if f.lower().endswith(_IMG_EXTS)
        )[: max_per_class or None]
        paths.extend(os.path.join(cdir, f) for f in files)
        ys.extend([ci] * len(files))
    return paths, np.asarray(ys, np.int32), classes


def load_imagenet_streaming(
    data_dir: str,
    store_dir: str,
    num_clients: int = 100,
    image_size: int = 224,
    partition_method: str = "homo",
    partition_alpha: float = 0.5,
    max_per_class: Optional[int] = None,
    seed: int = 0,
    chunk_rows: int = 2048,
    test_cap: int = 8192,
):
    """ImageNet at real scale: decode ONCE into a disk-backed mmap store
    (data/mmap_store.py), stream cohort rows per round thereafter.

    Closes the r2 'partial': `load_imagenet` materialises every decoded
    image in host RAM (224^2*3 fp32 = 600 KB/image — the real 1.28M-image
    train set is ~770 GB decoded, far beyond RAM), where the reference
    streams via torchvision ImageFolder (ImageNet/datasets.py). Here the
    metadata scan partitions FILES across clients, then the streaming
    writer decodes at most ``chunk_rows`` images at a time into
    flat_x.npy; training reads only each round's sampled cohort from the
    mmap. Idempotent per (store_dir): reuses an existing store."""
    import json

    from fedml_tpu.data.mmap_store import load_mmap_dataset, write_mmap_dataset

    # every partition-shaping parameter is baked into the store name: a
    # store built for different parameters must NOT be silently reused
    name = (
        f"imagenet_stream_c{num_clients}_s{image_size}_{partition_method}"
        f"_a{partition_alpha}_m{max_per_class}_t{test_cap}_seed{seed}"
    )
    meta = os.path.join(store_dir, "meta.json")
    if os.path.exists(meta):
        with open(meta) as f:
            existing = json.load(f).get("name")
        if existing == name:
            return load_mmap_dataset(store_dir)
        raise ValueError(
            f"store_dir {store_dir} holds a store built with different "
            f"parameters ({existing!r} != {name!r}) — pass a fresh "
            "store_dir or delete the old store"
        )
    paths, train_y, classes = _scan_split_paths(
        os.path.join(data_dir, "train"), max_per_class
    )
    val_dir = os.path.join(data_dir, "val")
    holdout_paths, holdout_y = None, None
    if not os.path.isdir(val_dir):
        # no val split vendored: HOLD OUT a train slice (removed from the
        # client partition — same discipline as load_imagenet; evaluating
        # on trained-on rows would inflate Test/Acc)
        k = min(max(1, len(train_y) // 10), test_cap)
        rng_h = np.random.default_rng(seed + 1)
        hold = rng_h.choice(len(train_y), k, replace=False)
        keep = np.setdiff1d(np.arange(len(train_y)), hold)
        holdout_paths = [paths[i] for i in hold]
        holdout_y = train_y[hold]
        paths = [paths[i] for i in keep]
        train_y = train_y[keep]
    if partition_method == "homo":
        idx_map = homo_partition(
            len(train_y), num_clients, np.random.default_rng(seed)
        )
    else:
        idx_map = lda_partition(
            train_y, num_clients, partition_alpha, seed=seed
        )
    order = np.concatenate([idx_map[i] for i in range(num_clients)])
    sizes = [len(idx_map[i]) for i in range(num_clients)]

    def gen_chunk(start, n):
        rows = order[start:start + n]
        x = np.stack([_load_image(paths[i], image_size) for i in rows])
        x = (x - IMAGENET_MEAN) / IMAGENET_STD
        return x.astype(np.float32), train_y[rows]

    if holdout_paths is None:
        vp, vy, _ = _scan_split_paths(val_dir, max_per_class)
        if len(vp) > test_cap:
            # val lists are class-sorted: a front-truncation would keep
            # only the first classes — subsample uniformly instead
            pick = np.random.default_rng(seed + 2).choice(
                len(vp), test_cap, replace=False
            )
            vp = [vp[i] for i in pick]
            vy = np.asarray(vy)[pick]
    else:
        vp, vy = holdout_paths, holdout_y
    tx = np.stack([_load_image(p, image_size) for p in vp])
    tx = ((tx - IMAGENET_MEAN) / IMAGENET_STD).astype(np.float32)
    write_mmap_dataset(
        store_dir, sizes, gen_chunk, (tx, np.asarray(vy, np.int32)),
        num_classes=len(classes), name=name,
        chunk_rows=chunk_rows,
    )
    return load_mmap_dataset(store_dir)
