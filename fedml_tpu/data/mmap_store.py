"""Memory-mapped on-disk federated store — clients >> host RAM.

The reference's StackOverflow benchmark row federates 342,477 clients
(benchmark/README.md:57); its loaders (and round 2 of this repo) hold every
client shard in host RAM as Python lists, which caps the client count at
whatever the host can materialize (VERDICT r2 Missing #2). This module is
the host tier below data/device_store.py:

    disk (np.memmap, all clients)  ->  host RAM (sampled cohort only)
        ->  HBM (stacked round batch)

Layout on disk (one directory):
    flat_x.npy / flat_y.npy   np.lib.format arrays, clients concatenated
                              along axis 0 (memory-mapped at load)
    offsets.npy               int64 [num_clients+1] row offsets
    test_x.npy / test_y.npy   central test set (small, loaded eagerly)
    meta.json                 {name, num_classes}

Per round, only the sampled cohort's rows are read from disk (the mmap
slice copy in stack_clients); building the store is a streaming write —
no point in time holds more than one chunk of clients in RAM. The round
math is IDENTICAL to the in-RAM path: MmapFederatedDataset exposes the
same client_x/client_y indexing contract, so stack_clients/bucket_steps
produce bit-identical batches (tested in tests/test_mmap_store.py).
"""

from __future__ import annotations

import json
import os
import struct
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from fedml_tpu.data.base import FederatedDataset


def advise_random(arr) -> None:
    """``madvise(MADV_RANDOM)`` a numpy memmap — the one-line fix for a
    pathology that dominates cohort-sparse stores at population scale:
    the kernel's default readahead treats every random-row page fault as
    the start of a sequential scan and drags in a whole readahead window
    of (sparse, zero) pages. Measured on the sharded state tier at 1M
    clients: an 8-row cohort gather costs 184 ms with default readahead
    and 0.65 ms under MADV_RANDOM — 280×, the difference between a
    round-time flat in N and one that drowns in page faults. No-op on
    platforms/arrays without the madvise surface (plain ndarrays, old
    Pythons); purely an access-pattern hint — bytes read are identical."""
    mm = getattr(arr, "_mmap", None)
    if mm is not None and hasattr(mm, "madvise"):
        import mmap as _mmap

        if hasattr(_mmap, "MADV_RANDOM"):
            mm.madvise(_mmap.MADV_RANDOM)


class _ClientView:
    """List-like lazy view of per-client shards over (flat, offsets).

    ``view[i]`` is a zero-copy mmap slice; nothing is read from disk until
    the slice is actually consumed. Supports the exact subset of the list
    protocol the data paths use (len, index, iterate)."""

    def __init__(self, flat: np.ndarray, offsets: np.ndarray):
        self._flat = flat
        self._offsets = offsets

    def __len__(self) -> int:
        return len(self._offsets) - 1

    def __getitem__(self, i: int) -> np.ndarray:
        return self._flat[self._offsets[i]:self._offsets[i + 1]]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


class MmapFederatedDataset(FederatedDataset):
    """FederatedDataset whose client shards live on disk (np.memmap)."""

    def __init__(self, name, flat_x, flat_y, offsets, test_x, test_y, num_classes):
        super().__init__(
            name=name,
            client_x=_ClientView(flat_x, offsets),
            client_y=_ClientView(flat_y, offsets),
            test_x=test_x,
            test_y=test_y,
            num_classes=num_classes,
        )
        self._offsets = np.asarray(offsets, np.int64)
        self._flat_x = flat_x
        self._flat_y = flat_y

    @property
    def num_clients(self) -> int:
        return len(self._offsets) - 1

    @property
    def train_sample_counts(self) -> np.ndarray:
        return np.diff(self._offsets)

    def total_train_samples(self) -> int:
        return int(self._offsets[-1])

    # population_index() is inherited: FederatedDataset's form reads
    # train_sample_counts, which HERE is already the vectorized
    # np.diff(offsets) — no per-client lazy view is ever touched.

    @property
    def total_train_bytes(self) -> int:
        """O(1) size for the HBM-budget guard — iterating 100k lazy views
        to sum nbytes would defeat the point of the store."""
        row = self._flat_x.dtype.itemsize * int(
            np.prod(self._flat_x.shape[1:], dtype=np.int64)
        ) + self._flat_y.dtype.itemsize * int(
            np.prod(self._flat_y.shape[1:], dtype=np.int64)
        )
        return int(self._offsets[-1]) * row


def write_mmap_dataset(
    path: str,
    client_sizes: Sequence[int],
    gen_chunk: Callable[[int, int], Tuple[np.ndarray, np.ndarray]],
    test: Tuple[np.ndarray, np.ndarray],
    num_classes: int,
    name: str = "mmap",
    chunk_rows: int = 1 << 20,
    log_fn: Optional[Callable[[object], None]] = None,
) -> str:
    """Streaming writer. ``gen_chunk(start_row, n_rows) -> (x, y)``
    produces the next n_rows of the flattened (client-concatenated) data;
    it is called with bounded n_rows, so generation never materializes the
    whole dataset. ``log_fn`` (optional) receives chunk progress strings
    while writing and one ``mmap_build/*`` summary dict at the end — the
    row a million-client build surfaces in summary.json instead of going
    dark for minutes."""
    os.makedirs(path, exist_ok=True)
    t0 = time.perf_counter()
    sizes = np.asarray(client_sizes, np.int64)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    total = int(offsets[-1])
    x0, y0 = gen_chunk(0, 1)
    fx = np.lib.format.open_memmap(
        os.path.join(path, "flat_x.npy"), mode="w+",
        dtype=x0.dtype, shape=(total,) + x0.shape[1:],
    )
    fy = np.lib.format.open_memmap(
        os.path.join(path, "flat_y.npy"), mode="w+",
        dtype=y0.dtype, shape=(total,) + y0.shape[1:],
    )
    row = 0
    while row < total:
        n = min(chunk_rows, total - row)
        x, y = gen_chunk(row, n)
        fx[row:row + n] = x
        fy[row:row + n] = y
        row += n
        if log_fn is not None:
            log_fn(f"mmap build: {row}/{total} rows written")
    fx.flush()
    fy.flush()
    np.save(os.path.join(path, "offsets.npy"), offsets)
    np.save(os.path.join(path, "test_x.npy"), test[0])
    np.save(os.path.join(path, "test_y.npy"), test[1])
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"name": name, "num_classes": num_classes}, f)
    if log_fn is not None:
        row_bytes = int(fx.dtype.itemsize * np.prod(fx.shape[1:], dtype=np.int64)) + int(
            fy.dtype.itemsize * np.prod(fy.shape[1:], dtype=np.int64)
        )
        log_fn({
            "mmap_build/rows": total,
            "mmap_build/clients": len(sizes),
            "mmap_build/bytes": total * row_bytes,
            "mmap_build/seconds": round(time.perf_counter() - t0, 3),
        })
    return path


# Reserved on-disk npy header size for the incremental builder: the header
# is written FIRST with a placeholder shape and rewritten at finalize with
# the true row count — 128 bytes fits any practical descr/shape string and
# keeps the array data 64-byte aligned (np.lib.format's own alignment).
_NPY_HEADER_RESERVE = 128


def _write_npy_header(f, dtype: np.dtype, shape: Tuple[int, ...]) -> None:
    """(Re)write a numpy format-1.0 header of exactly
    ``_NPY_HEADER_RESERVE`` bytes at the start of ``f``."""
    magic = b"\x93NUMPY\x01\x00"
    hlen = _NPY_HEADER_RESERVE - len(magic) - 2
    header = "{'descr': %r, 'fortran_order': False, 'shape': %r, }" % (
        np.lib.format.dtype_to_descr(np.dtype(dtype)),
        tuple(int(s) for s in shape),
    )
    if len(header) + 1 > hlen:
        raise ValueError(
            f"npy header {header!r} exceeds the {_NPY_HEADER_RESERVE}-byte "
            "reserve — feature rank too exotic for the incremental builder"
        )
    header = header.ljust(hlen - 1) + "\n"
    f.seek(0)
    f.write(magic + struct.pack("<H", hlen) + header.encode("latin1"))


class MmapStoreBuilder:
    """Bounded-memory incremental builder for the on-disk store.

    :func:`write_mmap_dataset` needs the full ``(client_sizes,
    gen_chunk)`` contract up front — right for synthetic geometry, wrong
    for real-format loaders (LEAF/StackOverflow file walks) that discover
    clients one at a time and never know the total row count until the
    walk ends. This builder accepts ``add_client(x, y)`` in arrival order
    and holds at most ``flush_bytes`` of buffered rows in RAM: appends
    stream into the final ``flat_x.npy``/``flat_y.npy`` through a
    reserved fixed-size header that :meth:`finalize` rewrites with the
    true shape — one pass over the data, one disk image, a RAM ceiling
    that does not grow with the population. ``stats()`` returns the
    ``mmap_build/*`` summary row (rows/bytes/clients/flushes/peak
    buffer/seconds) so a long build is measurable, not dark."""

    def __init__(
        self,
        path: str,
        flush_bytes: int = 64 << 20,
        log_fn: Optional[Callable[[str], None]] = None,
    ):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.flush_bytes = int(flush_bytes)
        self.log_fn = log_fn
        self._bx: list = []
        self._by: list = []
        self._buffered = 0
        self._sizes: list = []
        self._fx = self._fy = None
        self._dtype_x = self._dtype_y = None
        self._feat = self._lab = None
        self._rows_written = 0
        self._flushes = 0
        self._peak_buffer = 0
        self._finalized = False
        self._t0 = time.perf_counter()

    def add_client(self, x: np.ndarray, y: np.ndarray) -> None:
        """Append one client's shard (row-aligned x/y). The rows are
        buffered and flushed to disk whenever the buffer crosses the
        ceiling — RAM held is O(flush_bytes), never O(dataset)."""
        if self._finalized:
            raise RuntimeError("builder already finalized")
        x = np.ascontiguousarray(x)
        y = np.ascontiguousarray(y)
        if len(x) != len(y):
            raise ValueError(f"client rows misaligned: {len(x)} x vs {len(y)} y")
        if self._fx is None:
            self._dtype_x, self._dtype_y = x.dtype, y.dtype
            self._feat, self._lab = x.shape[1:], y.shape[1:]
            self._fx = open(os.path.join(self.path, "flat_x.npy"), "w+b")
            self._fy = open(os.path.join(self.path, "flat_y.npy"), "w+b")
            # placeholder headers reserve the slot; finalize rewrites them
            _write_npy_header(self._fx, self._dtype_x, (0,) + self._feat)
            _write_npy_header(self._fy, self._dtype_y, (0,) + self._lab)
        elif (
            x.dtype != self._dtype_x
            or y.dtype != self._dtype_y
            or x.shape[1:] != self._feat
            or y.shape[1:] != self._lab
        ):
            raise ValueError(
                f"client shard shape/dtype drift: got x{x.shape} {x.dtype} / "
                f"y{y.shape} {y.dtype}, store holds x(*, {self._feat}) "
                f"{self._dtype_x} / y(*, {self._lab}) {self._dtype_y}"
            )
        self._sizes.append(len(x))
        self._bx.append(x)
        self._by.append(y)
        self._buffered += int(x.nbytes) + int(y.nbytes)
        self._peak_buffer = max(self._peak_buffer, self._buffered)
        if self._buffered >= self.flush_bytes:
            self._flush()

    def _flush(self) -> None:
        if not self._bx:
            return
        for a in self._bx:
            self._fx.write(a.data)
        for a in self._by:
            self._fy.write(a.data)
        self._rows_written = int(sum(self._sizes))
        self._flushes += 1
        self._bx, self._by = [], []
        self._buffered = 0
        if self.log_fn is not None:
            self.log_fn(
                f"mmap build: {self._rows_written} rows / "
                f"{len(self._sizes)} clients flushed ({self._flushes} flushes)"
            )

    def finalize(
        self,
        test: Tuple[np.ndarray, np.ndarray],
        num_classes: int,
        name: str = "mmap",
    ) -> str:
        """Flush the tail, rewrite the reserved headers with the true row
        count, and write offsets/test/meta — the store is then exactly
        what :func:`load_mmap_dataset` expects."""
        if self._fx is None:
            raise ValueError("finalize() before any add_client()")
        self._flush()
        total = int(sum(self._sizes))
        _write_npy_header(self._fx, self._dtype_x, (total,) + self._feat)
        _write_npy_header(self._fy, self._dtype_y, (total,) + self._lab)
        for f in (self._fx, self._fy):
            f.flush()
            f.close()
        self._fx = self._fy = None
        offsets = np.concatenate(
            [[0], np.cumsum(np.asarray(self._sizes, np.int64))]
        )
        np.save(os.path.join(self.path, "offsets.npy"), offsets)
        np.save(os.path.join(self.path, "test_x.npy"), test[0])
        np.save(os.path.join(self.path, "test_y.npy"), test[1])
        with open(os.path.join(self.path, "meta.json"), "w") as f:
            json.dump({"name": name, "num_classes": num_classes}, f)
        self._finalized = True
        if self.log_fn is not None:
            self.log_fn(self.stats())
        return self.path

    def stats(self) -> dict:
        """Flat ``mmap_build/*`` summary row (MetricsLogger-shaped)."""
        row_bytes = 0
        if self._dtype_x is not None:
            row_bytes = int(
                self._dtype_x.itemsize * np.prod(self._feat, dtype=np.int64)
            ) + int(self._dtype_y.itemsize * np.prod(self._lab, dtype=np.int64))
        total = int(sum(self._sizes))
        return {
            "mmap_build/rows": total,
            "mmap_build/clients": len(self._sizes),
            "mmap_build/bytes": total * row_bytes,
            "mmap_build/flushes": self._flushes,
            "mmap_build/peak_buffer_bytes": self._peak_buffer,
            "mmap_build/seconds": round(time.perf_counter() - self._t0, 3),
        }


def load_mmap_dataset(path: str) -> MmapFederatedDataset:
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    return MmapFederatedDataset(
        name=meta["name"],
        flat_x=np.load(os.path.join(path, "flat_x.npy"), mmap_mode="r"),
        flat_y=np.load(os.path.join(path, "flat_y.npy"), mmap_mode="r"),
        offsets=np.load(os.path.join(path, "offsets.npy")),
        test_x=np.load(os.path.join(path, "test_x.npy")),
        test_y=np.load(os.path.join(path, "test_y.npy")),
        num_classes=meta["num_classes"],
    )


def synth_stackoverflow_mmap(
    path: str,
    num_clients: int = 100_000,
    mean_samples: int = 64,
    vocab: int = 10_000,
    seq_len: int = 20,
    seed: int = 0,
) -> MmapFederatedDataset:
    """StackOverflow-geometry synthetic NWP data written straight to an
    mmap store (ref benchmark/README.md:57: 342,477 clients next-word
    prediction; data/stackoverflow.py holds the real-format loader). Token
    ids are Zipf-distributed like natural text; y is the next-token shift
    of x. Idempotent: reuses the store if the directory already matches."""
    meta_path = os.path.join(path, "meta.json")
    name = f"so_synth_{num_clients}c"
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            if json.load(f).get("name") == name:
                return load_mmap_dataset(path)
    rng = np.random.default_rng(seed)
    sizes = np.clip(
        rng.lognormal(np.log(mean_samples), 0.6, num_clients).astype(np.int64),
        8,
        512,
    )

    def gen_chunk(start, n):
        r = np.random.default_rng(seed * 7919 + start)
        # zipf via inverse-CDF over a truncated power law (zipf(1.3))
        u = r.random((n, seq_len))
        vals = u ** (-1 / 0.3)
        x = np.where(
            np.isfinite(vals), np.minimum(vals, vocab - 1), vocab - 1
        ).astype(np.int32)
        y = np.roll(x, -1, axis=1)
        y[:, -1] = 0
        return x, y

    tx, ty = gen_chunk(10**9, 512)
    write_mmap_dataset(
        path, sizes, gen_chunk, (tx, ty), num_classes=vocab, name=name,
    )
    return load_mmap_dataset(path)
