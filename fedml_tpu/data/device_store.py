"""HBM-resident federated data store.

The reference re-materialises every sampled client's tensors on the training
device each round (fedavg_api.py:59-63 re-points Client objects;
my_model_trainer_classification.py:22 `.to(device)` per local train). On TPU
— especially through a remote-device transport, where host→device bandwidth
can be O(10 MB/s) — shipping the stacked batch every round dominates the
round (measured: 1.3 s transfer vs 74 ms compute for the north-star CNN
round). The TPU-native design: upload the *flat concatenation* of all client
shards to HBM once, and per round send only a [C, S·B] int32 index matrix
(tens of KB); the sampled clients' samples are gathered on-device.

This also pins compiled shapes: the index matrix is bucketed exactly like
:func:`fedml_tpu.data.base.stack_clients`, so rounds reuse the same small
set of jitted shapes, and the per-round host work is building a few KB of
indices instead of copying the batch.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.data.base import ClientBatch, FederatedDataset, bucket_steps

# HBM budget guard: datasets larger than this stay on host (override with
# env FEDML_TPU_DEVICE_CACHE_MAX_BYTES; v5e has 16 GB per chip).
_DEFAULT_MAX_BYTES = 4_000_000_000


def fits_on_device(data: FederatedDataset) -> bool:
    cap = int(
        os.environ.get("FEDML_TPU_DEVICE_CACHE_MAX_BYTES", _DEFAULT_MAX_BYTES)
    )
    # mmap-backed datasets report their size in O(1); summing nbytes over
    # 100k lazy per-client views would walk the whole store
    total = getattr(data, "total_train_bytes", None)
    if total is None:
        total = sum(cx.nbytes for cx in data.client_x) + sum(
            cy.nbytes for cy in data.client_y
        )
    return total <= cap


def _gather(flat_x, flat_y, idx, mask):
    """Gather + zero padded slots (padded indices point at row 0; zeroing
    keeps the result bit-identical to host stack_clients, which zero-pads).
    Plain traced function: the fused multi-round scan inlines it inside
    its own program, and :func:`gather_program` wraps it (plus the
    per-class reshape) for the eager per-round dispatch."""
    x = jnp.take(flat_x, idx, axis=0)
    y = jnp.take(flat_y, idx, axis=0)
    mx = mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim))
    my = mask.reshape(mask.shape + (1,) * (y.ndim - mask.ndim))
    return x * mx.astype(x.dtype), y * my.astype(y.dtype)


def gather_program(steps: int, bs: int):
    """The eager round-batch program for one (steps, bs) shape class:
    gather + zero-pad + reshape to [C, S, B, ...] as ONE ProgramCache-
    routed jit. Routing it through the cache (instead of the bare
    module-level jit it used to be, plus three eager reshapes) means (a)
    the AOT warmup pre-enumeration can compile it per class up front —
    the reshape ops were separate lazy dispatches warmup could not reach
    — and (b) it persists through the executable cache like every other
    round program (zero-cold-start)."""
    from fedml_tpu.compile import get_program_cache

    def builder():
        def fn(flat_x, flat_y, idx, mask):
            x, y = _gather(flat_x, flat_y, idx, mask)
            C = idx.shape[0]
            feat = flat_x.shape[1:]
            lab = flat_y.shape[1:]
            return (
                x.reshape((C, steps, bs) + feat),
                y.reshape((C, steps, bs) + lab),
                mask.reshape((C, steps, bs)),
            )

        return jax.jit(fn)

    return get_program_cache().get_or_build(
        "device_store_gather",
        {"kind": "device_store_gather", "steps": steps, "bs": bs},
        builder,
    )


class DeviceDataStore:
    """Upload-once, gather-per-round client data store."""

    def __init__(self, data: FederatedDataset):
        counts = data.train_sample_counts
        self.offsets = np.concatenate([[0], np.cumsum(counts)])
        self.counts = counts
        self.flat_x = jnp.asarray(np.concatenate(data.client_x, axis=0))
        self.flat_y = jnp.asarray(np.concatenate(data.client_y, axis=0))

    def round_indices(
        self,
        client_indices: Sequence[int],
        batch_size: int,
        seed: int = 0,
        pad_bucket: int = 1,
        shuffle: bool = True,
        force_steps: int = None,
    ):
        """Host-side index/mask matrices for one round's gather:
        (idx [C, cap] int32, mask [C, cap] float32, steps, bs, ns).
        ``ns`` is the per-client true sample count — the single source for
        aggregation weights (eager and fused paths must not re-derive it).
        ``force_steps`` overrides the bucketed step count so a fused
        multi-round scan can use one uniform shape across rounds (the extra
        all-padding steps are gated no-ops in the local-train scan)."""
        ns = [int(self.counts[i]) for i in client_indices]
        steps, bs, cap = bucket_steps(ns, batch_size, pad_bucket)
        if force_steps is not None:
            if force_steps < steps:
                raise ValueError(
                    f"force_steps={force_steps} < required steps={steps}"
                )
            steps, cap = force_steps, force_steps * bs

        rng = np.random.default_rng(seed)
        C = len(client_indices)
        idx = np.zeros((C, cap), dtype=np.int32)
        mask = np.zeros((C, cap), dtype=np.float32)
        for j, ci in enumerate(client_indices):
            n = ns[j]
            order = rng.permutation(n) if shuffle else np.arange(n)
            idx[j, :n] = self.offsets[ci] + order
            mask[j, :n] = 1.0
        return idx, mask, steps, bs, ns

    def round_batch(
        self,
        client_indices: Sequence[int],
        batch_size: int,
        seed: int = 0,
        pad_bucket: int = 1,
        shuffle: bool = True,
    ) -> ClientBatch:
        """Device-array ClientBatch for the sampled clients. Same bucketed
        shape contract as :func:`stack_clients`; padded slots index row 0
        and are mask-0."""
        idx, mask, steps, bs, ns = self.round_indices(
            client_indices, batch_size, seed=seed, pad_bucket=pad_bucket,
            shuffle=shuffle,
        )
        x, y, mask_dev = gather_program(steps, bs)(
            self.flat_x, self.flat_y, jnp.asarray(idx), jnp.asarray(mask)
        )
        return ClientBatch(
            x=x,
            y=y,
            mask=mask_dev,
            num_samples=np.array(ns, dtype=np.float32),
        )
