"""Federated dataset containers.

Replaces the reference's typed dataset hierarchy
(fedml_api/data_preprocessing/base.py:15-80: Dataset/LocalDataset/
FederatedDataset/DistributedDataset + DataLoader ABCs with four load modes)
with one numpy-backed container plus a *stacking* operation that turns a set of
sampled clients into dense, padded, masked device arrays — the shape contract
every jit-compiled round function consumes.

Design note (SURVEY §7 "hard parts"): non-IID client shards are ragged by
design; XLA needs static shapes. We pad each sampled client's data up to a
bucketed common length and carry a float mask; weighted aggregation uses true
sample counts, and all losses are mask-weighted means, so padding never changes
the math (ref semantics: FedAVGAggregator.py:66-71 weighted averaging,
my_model_trainer_classification.py:34-53 batch-mean loss).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np


def _ceil_to(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


def concat_nonempty(parts: Sequence[np.ndarray], like: np.ndarray) -> np.ndarray:
    """Concatenate, tolerating an all-empty list (returns a well-formed
    (0, *feat) array shaped/typed like ``like``'s rows)."""
    parts = [p for p in parts if len(p)]
    if not parts:
        return np.zeros((0,) + tuple(like.shape[1:]), like.dtype)
    return np.concatenate(parts, axis=0)


def _next_pow2(x: int) -> int:
    return 1 << (x - 1).bit_length() if x > 1 else 1


def size_class(n: int) -> int:
    """THE size-class policy for jit-shape axes (local-step counts AND DP
    cohort sizes — one definition so the two can never diverge): power-of-
    two up to 16, multiples of 8 above. Pure pow2 wastes up to ~2× in
    padding at larger counts; the 8-classes cap that waste at <⅓ while
    keeping the set of compiled shapes small."""
    return _next_pow2(n) if n <= 16 else _ceil_to(n, 8)


def bucket_steps(ns: Sequence[int], batch_size: int, pad_bucket: int):
    """Shared shape contract for a stacked client batch: given per-client
    sample counts, return (steps, bs, cap). Used by BOTH host stacking
    (:func:`stack_clients`) and the device store
    (data/device_store.py) — one definition, so the two paths can never
    diverge. ``batch_size == -1`` = full batch (oracle mode).

    Step counts are size-class bucketed via :func:`size_class` (full-batch
    mode is exempt: S is 1 there)."""
    max_n = max(ns)
    bs = max_n if batch_size == -1 else batch_size
    steps = _ceil_to(_ceil_to(max_n, bs) // bs, pad_bucket)
    if batch_size != -1:
        steps = size_class(steps)
    return steps, bs, steps * bs


# size_class as a lookup for steps <= 16 (pow2 rounding): exact integer
# table instead of float log2, so the vectorized path can never drift
# from the scalar one by rounding.
_POW2_LUT = np.array(
    [size_class(i) for i in range(17)], dtype=np.int64
)


def steps_class_array(counts, batch_size: int, pad_bucket: int) -> np.ndarray:
    """Vectorized per-client singleton-bucket step counts:
    ``steps_class_array(counts, bs, pb)[i] ==
    bucket_steps([counts[i]], bs, pb)[0]`` for every i, as one numpy
    pass — the O(N)-python-loop-free form :class:`PopulationIndex` and
    :func:`partition_shape_classes` run at million-client populations.
    ``batch_size == -1`` (full batch: bs = n, steps constant) is the one
    mode this cannot express; callers keep the scalar loop there."""
    if batch_size == -1:
        raise ValueError("steps_class_array: full-batch mode has no "
                         "shared bs; use bucket_steps per client")
    c = np.asarray(counts, np.int64)
    steps = -(-np.maximum(c, 0) // batch_size)  # ceil(n / bs)
    steps = -(-steps // pad_bucket) * pad_bucket  # ceil_to pad_bucket
    small = steps <= 16
    out = np.where(
        small, _POW2_LUT[np.minimum(steps, 16)], -(-steps // 8) * 8
    )
    return out.astype(np.int64)


def partition_shape_classes(counts, batch_size: int, pad_bucket: int):
    """Every (steps, bs) jit-shape class this partition can produce, as
    ``{(steps, bs): first client index in that class}``.

    A cohort's class is :func:`bucket_steps` of its members' counts, and
    the bucket math only reads ``max(ns)`` — which is always SOME
    client's count — so the reachable classes are exactly the per-client
    singleton buckets. This is the warmup pre-enumeration contract
    (compile/warmup.py): AOT-compiling the round/local-train program for
    each class here means rounds 1..R never hit a lazy shape-bucket
    compile, no matter which cohorts the scheduler draws.

    Vectorized (one numpy pass + ``np.unique``) for fixed batch sizes so
    a million-client partition enumerates in milliseconds; full-batch
    mode (``batch_size == -1``) keeps the scalar loop — there ``bs``
    varies per client and populations are tiny (the CI oracle)."""
    if batch_size == -1:
        classes: Dict[tuple, int] = {}
        for i, n in enumerate(counts):
            klass = bucket_steps([int(n)], batch_size, pad_bucket)[:2]
            classes.setdefault(klass, i)
        return classes
    steps = steps_class_array(counts, batch_size, pad_bucket)
    uniq, first = np.unique(steps, return_index=True)
    return {
        (int(s), int(batch_size)): int(i) for s, i in zip(uniq, first)
    }


@dataclasses.dataclass
class ClientBatch:
    """Dense, device-ready data for a set of sampled clients.

    Shapes: x [C, S, B, *feat], y [C, S, B, *lab], mask [C, S, B] float32,
    num_samples [C] float32 — C clients, S optimizer steps per local epoch,
    B batch size. Padded entries have mask 0.
    """

    x: np.ndarray
    y: np.ndarray
    mask: np.ndarray
    num_samples: np.ndarray

    @property
    def num_clients(self) -> int:
        return self.x.shape[0]


@dataclasses.dataclass
class FederatedDataset:
    """Host-side federated dataset: one (x, y) shard per client plus a global
    test set. This is the client-state store of SURVEY §7 — clients live in
    host RAM as numpy; each round the sampled subset is stacked and shipped to
    device once (never JSON, never per-tensor Python lists —
    ref message.py:47-59 is the anti-pattern)."""

    name: str
    client_x: List[np.ndarray]
    client_y: List[np.ndarray]
    test_x: np.ndarray
    test_y: np.ndarray
    num_classes: int
    # Optional per-client test shards (for local test parity with
    # fedavg_api.py:117-180 _local_test_on_all_clients).
    client_test_x: Optional[List[np.ndarray]] = None
    client_test_y: Optional[List[np.ndarray]] = None

    @property
    def num_clients(self) -> int:
        return len(self.client_x)

    @property
    def train_sample_counts(self) -> np.ndarray:
        return np.array([len(y) for y in self.client_y], dtype=np.int64)

    def total_train_samples(self) -> int:
        return int(self.train_sample_counts.sum())

    def centralized_train(self) -> tuple:
        """All client shards concatenated — for the federated==centralized
        oracle (ref CI-script-fedavg.sh:42-48)."""
        return (
            np.concatenate(self.client_x, axis=0),
            np.concatenate(self.client_y, axis=0),
        )

    def population_index(self):
        """This partition's metadata as a packed
        :class:`~fedml_tpu.population.PopulationIndex` — the split of
        per-client METADATA (counts, weights, jit-shape classes) from
        the materialized shards that lets selection, warmup
        pre-enumeration, and the bucket math run without touching shard
        containers. O(N) to build, once; the mmap store's subclass reads
        it straight off the offsets vector."""
        from fedml_tpu.population import PopulationIndex

        return PopulationIndex.from_dataset(self)


def pad_clients_to(batch: ClientBatch, target: int) -> ClientBatch:
    """Pad the client axis to ``target`` with all-mask-zero dummy clients.

    THE dummy-client contract (one definition; mesh padding and DP cohort
    padding both ride it): dummies carry num_samples == 0, so weighted
    aggregation ignores them exactly and DP's inclusion mask excludes
    them; their mask is all-zero, so the local-train no-op gate leaves
    their parameters untouched (delta exactly 0 — pinned by tests).
    Handles both host (numpy) and device-store (jax) batches."""
    extra = target - batch.num_clients
    if extra <= 0:
        return batch
    import jax.numpy as jnp

    def pad0(a):
        pad = [(0, extra)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, pad) if isinstance(a, np.ndarray) else jnp.pad(a, pad)

    return ClientBatch(
        x=pad0(batch.x),
        y=pad0(batch.y),
        mask=pad0(batch.mask),
        num_samples=pad0(batch.num_samples),
    )


def stack_clients(
    data: FederatedDataset,
    client_indices: Sequence[int],
    batch_size: int,
    seed: int = 0,
    pad_bucket: int = 1,
    shuffle: bool = True,
    force_steps: Optional[int] = None,
) -> ClientBatch:
    """Build a dense ClientBatch for the sampled clients.

    ``batch_size == -1`` means full batch (one step containing every sample) —
    the degenerate config the CI oracle uses (ref fedavg full-batch mode,
    CI-script-fedavg.sh:42).

    Steps-per-epoch S is ceil(max_n / B) rounded up to its size class
    (see :func:`bucket_steps`: pow2 up to 16, multiples of 8 above, and to
    ``pad_bucket``) so repeated rounds with ragged client sizes reuse a
    small set of compiled shapes instead of recompiling per distinct
    max-size (full-batch mode is exempt: S is 1 there, but the batch dim
    varies).
    """
    ns = [len(data.client_y[i]) for i in client_indices]
    steps, bs, cap = bucket_steps(ns, batch_size, pad_bucket)
    if force_steps is not None:
        # Callers that co-batch several stacks into one program (the
        # hierarchical mesh runtime pads every group to the global step
        # count) force a uniform S. Extra steps are all-padding no-ops and
        # the mask-aware shuffle keeps minibatch composition independent of
        # capacity (train/client.py epoch_body), so the math is unchanged.
        if force_steps < steps:
            raise ValueError(f"force_steps={force_steps} < required {steps}")
        steps = force_steps
        cap = steps * bs

    rng = np.random.default_rng(seed)
    feat_shape = data.client_x[client_indices[0]].shape[1:]
    lab_shape = data.client_y[client_indices[0]].shape[1:]
    C = len(client_indices)
    x = np.zeros((C, cap) + feat_shape, dtype=data.client_x[client_indices[0]].dtype)
    y = np.zeros((C, cap) + lab_shape, dtype=data.client_y[client_indices[0]].dtype)
    mask = np.zeros((C, cap), dtype=np.float32)
    from fedml_tpu import native

    for j, ci in enumerate(client_indices):
        n = ns[j]
        order = rng.permutation(n) if shuffle else np.arange(n)
        # threaded row-gather (native/src/fastpack.cpp); numpy fallback inside
        native.gather_rows(data.client_x[ci], order, x[j, :n])
        native.gather_rows(data.client_y[ci], order, y[j, :n])
        mask[j, :n] = 1.0
    x = x.reshape((C, steps, bs) + feat_shape)
    y = y.reshape((C, steps, bs) + lab_shape)
    mask = mask.reshape((C, steps, bs))
    return ClientBatch(
        x=x, y=y, mask=mask, num_samples=np.array(ns, dtype=np.float32)
    )
