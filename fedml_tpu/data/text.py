"""Text preprocessing shared by the Shakespeare loaders (ref:
fedml_api/data_preprocessing/{shakespeare/language_utils.py,
fed_shakespeare/utils.py} — both use the TFF text-generation tutorial's
86-char vocabulary with pad/bos/eos/oov, VOCAB_SIZE 90)."""

from __future__ import annotations

from typing import Iterable, List, Tuple

import numpy as np

CHAR_VOCAB = list(
    "dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#'/37;?bfjnrvzBFJNRVZ\"&*.26:\naeimquyAEIMQUY]!%)-159\r"
)
PAD_ID = 0
_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(CHAR_VOCAB)}
BOS_ID = len(CHAR_VOCAB) + 1
EOS_ID = len(CHAR_VOCAB) + 2
OOV_ID = len(CHAR_VOCAB) + 3
VOCAB_SIZE = len(CHAR_VOCAB) + 4  # 90

SEQUENCE_LENGTH = 80  # McMahan et al. AISTATS 2017


def char_to_id(c: str) -> int:
    return _CHAR_TO_ID.get(c, OOV_ID)


def chars_to_ids(s: str) -> List[int]:
    return [char_to_id(c) for c in s]


def preprocess_snippets(
    sentences: Iterable[str], max_seq_len: int = SEQUENCE_LENGTH
) -> np.ndarray:
    """TFF-style snippet → fixed windows of max_seq_len+1 token ids with
    bos/eos and pad to a multiple (ref fed_shakespeare/utils.py:28-46).
    Returns [N, max_seq_len+1] int32."""
    seqs: List[List[int]] = []
    for sen in sentences:
        tokens = [BOS_ID] + chars_to_ids(sen) + [EOS_ID]
        if len(tokens) % (max_seq_len + 1) != 0:
            tokens += [PAD_ID] * ((-len(tokens)) % (max_seq_len + 1))
        for i in range(0, len(tokens), max_seq_len + 1):
            seqs.append(tokens[i : i + max_seq_len + 1])
    if not seqs:
        return np.zeros((0, max_seq_len + 1), np.int32)
    return np.asarray(seqs, np.int32)


def split_xy(sequences: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[N, T+1] → (x [N, T], y [N, T]) next-char targets
    (ref fed_shakespeare/utils.py:49-53)."""
    return sequences[:, :-1], sequences[:, 1:]
