"""Dataset registry: name → FederatedDataset loader dispatch
(ref fedml_experiments/base.py:49-101 load_data; DATASETS tuple base.py:28-40)."""

from __future__ import annotations

from fedml_tpu.data.base import FederatedDataset

# dataset → training task (selects loss/metrics; ref trainer selection by
# dataset, FedAvgAPI.py:33-39).
TASKS = {
    "mnist": "classification",
    "femnist": "classification",
    "femnist_synth": "classification",
    "shakespeare_synth": "classification",  # next-char from 80-char window
    "shakespeare_synth_lm": "nwp",  # per-position next-char (transformer LM)
    "shakespeare": "classification",  # next-char from 80-char window
    "fed_shakespeare": "nwp",
    "fed_cifar100": "classification",
    "cifar10": "classification",
    "cifar100": "classification",
    "cinic10": "classification",
    "stackoverflow_lr": "tag",
    "stackoverflow_nwp": "nwp",
    "synthetic": "classification",
    "seg_synth": "segmentation",
    "imagenet": "classification",
    "landmarks": "classification",
}


def task_for_dataset(name: str) -> str:
    base = name.lower()
    if base.startswith("synthetic"):
        return "classification"
    return TASKS.get(base, "classification")


def load(config) -> FederatedDataset:
    """``config`` is a RunConfig (uses .data.* and .fed.client_num_in_total)."""
    d = config.data
    name = d.dataset.lower()
    n_clients = config.fed.client_num_in_total

    if name == "synthetic":
        from fedml_tpu.data.synthetic import synthetic_classification

        return synthetic_classification(
            num_clients=n_clients,
            partition_method=d.partition_method,
            partition_alpha=d.partition_alpha,
            seed=config.seed,
        )
    if name.startswith("synthetic_"):
        # synthetic_<alpha>_<beta>, e.g. synthetic_1_1 (ref
        # fedml_api/data_preprocessing/synthetic_1_1/).
        from fedml_tpu.data.synthetic import synthetic_fedprox

        parts = name.split("_")
        alpha, beta = float(parts[1]), float(parts[2])
        return synthetic_fedprox(
            alpha=alpha, beta=beta, num_clients=n_clients, seed=config.seed
        )
    if name == "seg_synth":
        from fedml_tpu.data.synthetic import synthetic_segmentation

        return synthetic_segmentation(num_clients=n_clients, seed=config.seed)
    if name == "femnist_synth":
        from fedml_tpu.data.femnist_synth import femnist_synthetic

        return femnist_synthetic(num_clients=n_clients, seed=config.seed)
    if name == "shakespeare_synth":
        from fedml_tpu.data.synthetic import synthetic_shakespeare

        return synthetic_shakespeare(num_clients=n_clients, seed=config.seed)
    if name == "shakespeare_synth_lm":
        from fedml_tpu.data.synthetic import synthetic_shakespeare

        return synthetic_shakespeare(
            num_clients=n_clients, seed=config.seed, seq_targets=True
        )
    if name in _FILE_LOADERS:
        import importlib

        mod_name, fn_name = _FILE_LOADERS[name]
        fn = getattr(importlib.import_module(mod_name), fn_name)
        return fn(d.data_dir, max_clients=n_clients or None)
    if name in ("cifar10", "cifar100", "cinic10"):
        from fedml_tpu.data.cifar import load_cifar_family

        return load_cifar_family(
            name,
            d.data_dir,
            num_clients=n_clients,
            partition_method=d.partition_method,
            partition_alpha=d.partition_alpha,
            seed=config.seed,
        )
    available = ", ".join(
        ["synthetic", "synthetic_<a>_<b>", "femnist_synth",
         "shakespeare_synth", "shakespeare_synth_lm", "seg_synth"]
        + sorted(_FILE_LOADERS)
        + ["cifar10", "cifar100", "cinic10"]
    )
    raise KeyError(f"unknown dataset {d.dataset!r}; available: {available}")


# datasets loaded from files on disk: name -> (module, loader fn); every
# loader takes (data_dir, max_clients=...).
_FILE_LOADERS = {
    "mnist": ("fedml_tpu.data.leaf", "load_mnist"),
    "femnist": ("fedml_tpu.data.tff_h5", "load_femnist"),
    "shakespeare": ("fedml_tpu.data.leaf", "load_shakespeare"),
    "fed_shakespeare": ("fedml_tpu.data.tff_h5", "load_fed_shakespeare"),
    "fed_cifar100": ("fedml_tpu.data.tff_h5", "load_fed_cifar100"),
    "stackoverflow_lr": ("fedml_tpu.data.stackoverflow", "load_stackoverflow_lr"),
    "stackoverflow_nwp": ("fedml_tpu.data.stackoverflow", "load_stackoverflow_nwp"),
    "imagenet": ("fedml_tpu.data.imagenet", "load_imagenet"),
    "landmarks": ("fedml_tpu.data.landmarks", "load_landmarks"),
}
