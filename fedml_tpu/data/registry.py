"""Dataset registry: name → FederatedDataset loader dispatch
(ref fedml_experiments/base.py:49-101 load_data)."""

from __future__ import annotations

from fedml_tpu.data.base import FederatedDataset


def load(config) -> FederatedDataset:
    """``config`` is a RunConfig (uses .data.* and .fed.client_num_in_total)."""
    d = config.data
    name = d.dataset.lower()
    if name == "synthetic":
        from fedml_tpu.data.synthetic import synthetic_classification

        return synthetic_classification(
            num_clients=config.fed.client_num_in_total,
            partition_method=d.partition_method,
            partition_alpha=d.partition_alpha,
            seed=config.seed,
        )
    if name.startswith("synthetic_"):
        # synthetic_<alpha>_<beta>, e.g. synthetic_1_1 (ref
        # fedml_api/data_preprocessing/synthetic_1_1/).
        from fedml_tpu.data.synthetic import synthetic_fedprox

        parts = name.split("_")
        alpha, beta = float(parts[1]), float(parts[2])
        return synthetic_fedprox(
            alpha=alpha,
            beta=beta,
            num_clients=config.fed.client_num_in_total,
            seed=config.seed,
        )
    raise KeyError(
        f"unknown dataset {d.dataset!r}; available: synthetic, synthetic_<a>_<b>"
    )
