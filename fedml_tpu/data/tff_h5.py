"""TFF-exported h5 dataset loaders — FederatedEMNIST, fed_cifar100,
fed_shakespeare (ref: fedml_api/data_preprocessing/{FederatedEMNIST,
fed_cifar100, fed_shakespeare}/data_loader.py; layout: h5 group 'examples'
keyed by client id with per-client datasets)."""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from fedml_tpu.data.base import FederatedDataset, concat_nonempty
from fedml_tpu.data import text as T

_EXAMPLE = "examples"

FEMNIST_TRAIN = "fed_emnist_train.h5"
FEMNIST_TEST = "fed_emnist_test.h5"
CIFAR100_TRAIN = "fed_cifar100_train.h5"
CIFAR100_TEST = "fed_cifar100_test.h5"
SHAKES_TRAIN = "shakespeare_train.h5"
SHAKES_TEST = "shakespeare_test.h5"


def _open(path: str):
    import h5py

    if not os.path.exists(path):
        raise FileNotFoundError(
            f"TFF h5 file not found: {path} (ref data/*/download*.sh fetch "
            "these from fedml.ai / TFF mirrors)"
        )
    return h5py.File(path, "r")


def load_femnist(data_dir: str, max_clients: Optional[int] = None) -> FederatedDataset:
    """'pixels' [N,28,28] float, 'label' int per client
    (ref FederatedEMNIST/data_loader.py:18-60)."""
    with _open(os.path.join(data_dir, FEMNIST_TRAIN)) as tr, _open(
        os.path.join(data_dir, FEMNIST_TEST)
    ) as te:
        ids = sorted(tr[_EXAMPLE].keys())
        if max_clients:
            ids = ids[:max_clients]
        client_x, client_y, ctx, cty = [], [], [], []
        for cid in ids:
            g = tr[_EXAMPLE][cid]
            client_x.append(
                np.asarray(g["pixels"], np.float32).reshape(-1, 28, 28, 1)
            )
            client_y.append(np.asarray(g["label"], np.int32))
            if cid in te[_EXAMPLE]:
                gt = te[_EXAMPLE][cid]
                ctx.append(np.asarray(gt["pixels"], np.float32).reshape(-1, 28, 28, 1))
                cty.append(np.asarray(gt["label"], np.int32))
            else:
                ctx.append(np.zeros((0, 28, 28, 1), np.float32))
                cty.append(np.zeros((0,), np.int32))
    return FederatedDataset(
        name="femnist",
        client_x=client_x,
        client_y=client_y,
        test_x=concat_nonempty(ctx, client_x[0]),
        test_y=concat_nonempty(cty, client_y[0]),
        num_classes=62,
        client_test_x=ctx,
        client_test_y=cty,
    )


def load_fed_cifar100(
    data_dir: str, max_clients: Optional[int] = None, crop: int = 24
) -> FederatedDataset:
    """'image' [N,32,32,3] uint8, 'label' int per client; per-image
    standardization + center crop to 24×24 (the reference applies random
    crop/flip at train time, fed_cifar100/data_loader.py:57-80 — here the
    deterministic part is host-side; random aug belongs in the jit pipeline)."""
    off = (32 - crop) // 2

    def prep(img_u8):
        x = np.asarray(img_u8, np.float32) / 255.0
        m = x.mean(axis=(1, 2, 3), keepdims=True)
        s = x.std(axis=(1, 2, 3), keepdims=True) + 1e-6
        x = (x - m) / s
        return x[:, off : off + crop, off : off + crop, :]

    with _open(os.path.join(data_dir, CIFAR100_TRAIN)) as tr, _open(
        os.path.join(data_dir, CIFAR100_TEST)
    ) as te:
        ids = sorted(tr[_EXAMPLE].keys())
        if max_clients:
            ids = ids[:max_clients]
        client_x = [prep(tr[_EXAMPLE][c]["image"]) for c in ids]
        client_y = [np.asarray(tr[_EXAMPLE][c]["label"], np.int32) for c in ids]
        test_ids = sorted(te[_EXAMPLE].keys())
        tx = np.concatenate([prep(te[_EXAMPLE][c]["image"]) for c in test_ids])
        ty = np.concatenate(
            [np.asarray(te[_EXAMPLE][c]["label"], np.int32) for c in test_ids]
        )
    return FederatedDataset(
        name="fed_cifar100",
        client_x=client_x,
        client_y=client_y,
        test_x=tx,
        test_y=ty,
        num_classes=100,
    )


def load_fed_shakespeare(data_dir: str, max_clients: Optional[int] = None) -> FederatedDataset:
    """'snippets' string arrays per client → 80-token next-char sequences
    (ref fed_shakespeare/data_loader.py + utils.py preprocess/split)."""

    def prep(snippets) -> tuple:
        sents = [
            s.decode("utf-8") if isinstance(s, bytes) else str(s) for s in snippets
        ]
        seqs = T.preprocess_snippets(sents)
        return T.split_xy(seqs)

    with _open(os.path.join(data_dir, SHAKES_TRAIN)) as tr, _open(
        os.path.join(data_dir, SHAKES_TEST)
    ) as te:
        ids = sorted(tr[_EXAMPLE].keys())
        if max_clients:
            ids = ids[:max_clients]
        client_x, client_y = [], []
        for cid in ids:
            x, y = prep(tr[_EXAMPLE][cid]["snippets"])
            client_x.append(x)
            client_y.append(y)
        txs, tys = [], []
        for cid in sorted(te[_EXAMPLE].keys()):
            x, y = prep(te[_EXAMPLE][cid]["snippets"])
            if len(x):
                txs.append(x)
                tys.append(y)
    return FederatedDataset(
        name="fed_shakespeare",
        client_x=client_x,
        client_y=client_y,
        test_x=concat_nonempty(txs, client_x[0]),
        test_y=concat_nonempty(tys, client_y[0]),
        num_classes=T.VOCAB_SIZE,
    )
