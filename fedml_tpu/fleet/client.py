"""Fleet client — the OS-process wire client the launcher preforks.

This module is the forkserver preload target (fleet/launcher.py): the
forkserver parent imports it ONCE — paying the jax / transport import
cost once — and every one of the ≥1000 fleet children is a cheap fork of
that warm parent instead of a cold ``python -m fedml_tpu`` interpreter.

A fleet child is a REAL wire client: it builds its own
:class:`~fedml_tpu.core.grpc_comm.GrpcCommManager` on ``base_port +
rank``, dials the tenant's rank-0 endpoint, and runs the stock manager
FSM — :class:`~fedml_tpu.algorithms.fedbuff.FedBuffClientManager`
(entering through the C2S_JOIN admission door, leaving through C2S_LEAVE
when its seeded churn budget is spent) or
:class:`~fedml_tpu.algorithms.fedavg_transport.FedAvgClientManager`
(fixed sync fleet). Faults come from the same per-process
:class:`~fedml_tpu.scheduler.faults.FaultInjector` the CLI wire path
uses; every injected event is captured by a tiny health shim
(:class:`FaultEventLog`) and shipped back in the child's result file so
the launcher can merge a fleet-wide
:class:`~fedml_tpu.scheduler.faults.FaultTrace`.

``LiteTrainer`` replaces the jitted local-train program with a
numpy-only pseudo-update, deterministic in (seed, client, round): a
fleet child exercises the WIRE (join/dispatch/upload/leave, retries,
chaos, backpressure) without ever initializing a jax backend — which is
what makes a 1000-process fleet feasible on one host.

Exit codes (collected by the launcher):
    0  completed (ran until the server's FINISH, after doing work)
    10 left (spent its churn budget, left through the admission door)
    11 finished early (FINISH before any assignment: refused at the
       admission door, or joined a tenant that was already done)
    12 orphaned (server unreachable past the deadman deadline)
    13 error
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

EXIT_COMPLETED = 0
EXIT_LEFT = 10
EXIT_FINISHED_EARLY = 11
EXIT_ORPHANED = 12
EXIT_ERROR = 13

#: test hook — ranks listed here (comma-separated) hang instead of
#: running, simulating a zombie client the launcher must reap at its
#: kill deadline. Never set outside tests.
HANG_ENV = "FLEET_TEST_HANG_RANKS"


class FaultEventLog:
    """Duck-typed stand-in for the server's ClientHealthRegistry on the
    injector's ``health`` slot: records every injected fault event as a
    plain row so the child can ship it home for the launcher's
    fleet-wide FaultTrace merge (O(events injected), bounded by the
    child's own lifetime)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events: List[list] = []

    def observe_fault(
        self, client_id: int, round_idx: int, kind: str, detail: float = 0.0
    ) -> None:
        with self._lock:
            self.events.append(
                [int(client_id), int(round_idx), str(kind), float(detail)]
            )

    def rows(self) -> List[list]:
        with self._lock:
            return [list(e) for e in self.events]


class LiteTrainer:
    """Numpy-only trainer with the LocalTrainer protocol
    (``update_dataset`` / ``train`` / ``client_index`` / ``last_loss``):
    the pseudo-update perturbs every float leaf deterministically in
    (seed, client, round), so uploads are real model-shaped payloads and
    two runs of the same fleet upload identical bytes — without a jax
    backend, a dataset, or a compile anywhere in the child."""

    def __init__(self, seed: int = 0, lr: float = 0.05):
        self.seed = int(seed)
        self.lr = float(lr)
        self.client_index = 0
        self.last_loss: Optional[float] = None

    def update_dataset(self, client_index) -> None:
        self.client_index = int(client_index or 0)

    def train(self, round_idx, variables: dict) -> Tuple[dict, int]:
        rng = np.random.default_rng([
            self.seed & 0x7FFFFFFF,
            int(self.client_index),
            int(round_idx) & 0x7FFFFFFF,
        ])

        def _step(leaf):
            a = np.asarray(leaf)
            if np.issubdtype(a.dtype, np.floating):
                noise = rng.standard_normal(a.shape).astype(a.dtype)
                return a - np.asarray(self.lr, a.dtype) * noise
            return a

        out = _tree_map(_step, variables)
        self.last_loss = float(rng.random())
        return out, 8


def _tree_map(fn, tree):
    """Minimal pytree map over dict/list/tuple containers, visiting dict
    keys in sorted order (jax's convention) — keeps the child free of
    any jax dependency at train time."""
    if isinstance(tree, dict):
        return {k: _tree_map(fn, tree[k]) for k in sorted(tree)}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_map(fn, v) for v in tree)
    return fn(tree)


def _client_config(payload: dict):
    from fedml_tpu.config import (
        CommConfig,
        DataConfig,
        FedConfig,
        RunConfig,
        TrainConfig,
    )

    return RunConfig(
        data=DataConfig(batch_size=int(payload.get("batch_size", 8))),
        fed=FedConfig(
            client_num_in_total=int(payload["population"]),
            client_num_per_round=int(
                payload.get("client_num_per_round", payload["population"])
            ),
            comm_round=int(payload["rounds"]),
            async_buffer_k=int(payload.get("async_buffer_k", 4)),
            fault_plan=str(payload.get("fault_plan", "")),
            deadline_s=float(payload.get("deadline_s", 0.0)),
            min_clients=int(payload.get("min_clients", 1)),
        ),
        train=TrainConfig(client_optimizer="sgd", lr=0.1),
        comm=CommConfig(
            send_retries=int(payload.get("send_retries", 6)),
            send_timeout_s=float(payload.get("send_timeout_s", 20.0)),
            send_fault_p=float(payload.get("send_fault_p", 0.0)),
            beacons=bool(payload.get("beacons", True)),
        ),
        seed=int(payload.get("seed", 0)),
    )


def _make_comm(payload: dict, config):
    from fedml_tpu.core.grpc_comm import GrpcCommManager

    rank = int(payload["rank"])
    # the child only ever dials rank 0; expected_peers=2 keeps its own
    # (unused) inbound executor at the floor instead of fleet-sized
    return GrpcCommManager(
        rank,
        {0: "127.0.0.1", rank: "127.0.0.1"},
        base_port=int(payload["base_port"]),
        send_timeout_s=config.comm.send_timeout_s,
        expected_peers=2,
    )


def run_fleet_client(payload: dict) -> Tuple[int, dict]:
    """Run one fleet client to completion in THIS process. Returns
    ``(exit_code, result_row)`` — importable directly by tests (no fork
    required) and by :func:`client_process_main` (the forkserver entry)."""
    from fedml_tpu.scheduler.faults import FaultInjector

    rank = int(payload["rank"])
    config = _client_config(payload)
    events = FaultEventLog()
    injector = FaultInjector.from_config(config, health=events)
    comm = _make_comm(payload, config)
    t0 = time.perf_counter()
    if payload.get("algorithm", "fedbuff") == "fedbuff":
        code, extra = _run_fedbuff(payload, config, comm, injector)
    else:
        code, extra = _run_sync(payload, config, comm, injector)
    result = {
        "rank": rank,
        "exit": code,
        "wall_s": round(time.perf_counter() - t0, 4),
        "fault_events": events.rows(),
    }
    result.update(extra)
    return code, result


def _run_fedbuff(payload, config, comm, injector) -> Tuple[int, dict]:
    from fedml_tpu.algorithms.fedbuff import FedBuffClientManager
    from fedml_tpu.core.message import Message, MessageType as MT

    class FleetWorker(FedBuffClientManager):
        """Stock async worker + the seeded churn budget: after
        ``max_assignments`` handled dispatches the NEXT dispatch is
        answered with C2S_LEAVE — the leave half of the fleet's churn
        waves (the launcher back-fills the freed slot)."""

        def __init__(self, *args, max_assignments: int = 0, **kw):
            super().__init__(*args, **kw)
            self.max_assignments = int(max_assignments)
            self.assignments_done = 0

        def _on_model(self, msg):
            if (
                self.max_assignments
                and not self._leave_requested
                and self.assignments_done >= self.max_assignments
            ):
                self.request_leave()
            prev = self._last_handled_tag
            super()._on_model(msg)
            if not self.left and self._last_handled_tag != prev:
                self.assignments_done += 1

    rank = int(payload["rank"])
    worker = FleetWorker(
        config,
        comm,
        rank,
        LiteTrainer(seed=int(payload.get("seed", 0))),
        orphan_deadline_s=float(payload.get("orphan_deadline_s", 60.0)),
        faults=injector,
        max_assignments=int(payload.get("assignment_budget", 0)),
    )
    # the join announcement precedes run(): the reply (a dispatch when
    # admitted, FINISH when refused at max_workers) queues in the inbox
    # and is handled as soon as run() registers handlers — the same
    # ordering FedSession.add_worker uses for in-process elastic joins
    worker.send_message(Message(MT.C2S_JOIN, rank, 0))
    worker.run()
    if worker.left:
        code = EXIT_LEFT
    elif worker.orphaned:
        code = EXIT_ORPHANED
    elif worker.assignments_done == 0:
        code = EXIT_FINISHED_EARLY
    else:
        code = EXIT_COMPLETED
    return code, {"assignments": worker.assignments_done}


def _run_sync(payload, config, comm, injector) -> Tuple[int, dict]:
    from fedml_tpu.algorithms.fedavg_transport import FedAvgClientManager

    client = FedAvgClientManager(
        config,
        comm,
        int(payload["rank"]),
        LiteTrainer(seed=int(payload.get("seed", 0))),
        faults=injector,
    )
    client.run()  # rounds until the server's FINISH
    return EXIT_COMPLETED, {}


def client_process_main(payload: dict, result_path: Optional[str]) -> None:
    """The forkserver child entry: run the client, write the result row
    (atomically — the launcher may be polling), exit with the class
    code. ``os._exit`` on purpose: a fleet child must never run the
    parent's atexit hooks (telemetry writers, exporters)."""
    rank = int(payload["rank"])
    # the launcher threads the env through the payload: children of a
    # long-lived forkserver inherit the FORKSERVER's environment (frozen
    # at its start), so reading os.environ here alone would miss a hook
    # set after the first fleet ran in this interpreter
    hang = str(payload.get("_test_hang", "")) or os.environ.get(HANG_ENV, "")
    if hang and str(rank) in {r for r in hang.split(",") if r}:
        # zombie simulation (tests): never joins, never exits — the
        # launcher's straggler reaper must SIGTERM/SIGKILL us
        time.sleep(3600)
        os._exit(EXIT_ERROR)
    code = EXIT_ERROR
    result: Dict[str, object] = {"rank": rank, "exit": EXIT_ERROR}
    try:
        code, result = run_fleet_client(payload)
    except BaseException as e:  # noqa: BLE001 — the exit code IS the report
        result = {"rank": rank, "exit": EXIT_ERROR, "error": repr(e)}
        code = EXIT_ERROR
    if result_path:
        try:
            tmp = f"{result_path}.tmp"
            with open(tmp, "w") as f:
                json.dump(result, f)
            os.replace(tmp, result_path)
        except OSError:
            pass
    os._exit(code)


# Forkserver warmth: the whole point of preloading this module is that
# the heavy imports below land in the forkserver parent ONCE — every
# child forks with them already in memory instead of paying a cold
# import per process. Import only (no grpc channels/servers, no jax
# backend init): importing is fork-safe, running is not.
from fedml_tpu import config as _warm_config  # noqa: E402,F401
from fedml_tpu.algorithms import fedavg_transport as _warm_sync  # noqa: E402,F401
from fedml_tpu.algorithms import fedbuff as _warm_fedbuff  # noqa: E402,F401
from fedml_tpu.core import grpc_comm as _warm_grpc  # noqa: E402,F401
from fedml_tpu.core import message as _warm_message  # noqa: E402,F401
from fedml_tpu.scheduler import faults as _warm_faults  # noqa: E402,F401
