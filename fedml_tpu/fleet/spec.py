"""FleetSpec — the declarative description of a wire fleet.

One JSON document describes everything the fleet launcher
(fleet/launcher.py) needs to materialize ≥1000 OS-process gRPC clients
against one serve-layer tenant: the population size, the DeviceProfile
tier mix (reusing the fault-plan ``"fleet"`` shorthand,
scheduler/faults.py), the seed-deterministic churn schedule (per-client
assignment budgets — a client leaves through the admission door after
its budget is spent, and the launcher back-fills the freed slot from the
remaining population: the join/leave waves ARE the rolling population),
the chaos knobs (``send_fault_p`` transport chaos rides the PR-10 retry
layer), and the connection budgets the server side enforces
(``grpc_max_workers`` / ``grpc_stream_budget`` / tenant ``max_workers``).

Everything derived here is pure in the spec (notably ``seed``): the same
spec materializes the same tier assignment, the same join order, and the
same per-client assignment budgets in every run — which is what lets a
recorded :class:`~fedml_tpu.scheduler.faults.FaultTrace` replay
byte-identically against the same fleet.

Schema (all keys optional except ``population``)::

    {
      "population": 1000,        # total distinct client processes over the run
      "max_live": 96,            # concurrent client processes (the wave width)
      "algorithm": "fedbuff",    # "fedbuff" (churn fleet) | "fedavg" (fixed K)
      "mode": "lite",            # "lite" (forkserver fleet clients) | "cli"
      "rounds": 30,              # server steps (fedbuff) / comm rounds (sync)
      "max_workers": 64,         # tenant admission cap (fedbuff; 0 = max_live)
      "async_buffer_k": 4,
      "tiers": {"midrange_phone": 0.7, "lowend_phone": 0.3},
      "assignments": [1, 3],     # per-client churn budget range (0 = no churn)
      "seed": 0,
      "base_port": 19400,
      "send_fault_p": 0.02,      # transport chaos (core/retry.py)
      "send_retries": 6,
      "send_timeout_s": 20.0,
      "deadline_s": 60.0,        # sync quorum deadline (required with tiers)
      "grpc_max_workers": 0,     # server executor size (0 = auto from cohort)
      "grpc_stream_budget": 0,   # inbound queue budget (0 = off)
      "orphan_deadline_s": 60.0, # fedbuff client deadman
      "client_deadline_s": 300,  # straggler/zombie reap deadline per client
      "run_deadline_s": 900,     # whole-fleet watchdog
      "fault_plan": "",          # override: replay "trace:<path>" verbatim
      "feat_dim": 8, "num_classes": 3, "batch_size": 8,   # lite model dims
      "cli_args": [...],         # mode="cli": argv tail for python -m fedml_tpu
                                 #   ("{rank}" expands to the process rank)
      "cli_rank0_args": [...]    #   extra args for rank 0 only (e.g. --prom_port)
    }
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

FLEET_MODES = ("lite", "cli")
FLEET_ALGORITHMS = ("fedbuff", "fedavg")

_KNOWN_KEYS = {
    "population", "max_live", "algorithm", "mode", "rounds", "max_workers",
    "async_buffer_k", "tiers", "assignments", "seed", "base_port",
    "send_fault_p", "send_retries", "send_timeout_s", "deadline_s",
    "grpc_max_workers", "grpc_stream_budget", "orphan_deadline_s",
    "client_deadline_s", "run_deadline_s", "fault_plan",
    "feat_dim", "num_classes", "batch_size",
    "cli_args", "cli_rank0_args",
}


class FleetSpec:
    """Parsed + validated fleet description (see module docstring)."""

    def __init__(self, doc: dict):
        unknown = set(doc) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"fleet spec: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_KNOWN_KEYS)})"
            )
        self.population = int(doc.get("population", 0))
        if self.population < 1:
            raise ValueError("fleet spec: population must be >= 1")
        self.max_live = int(doc.get("max_live", min(64, self.population)))
        if not 1 <= self.max_live:
            raise ValueError("fleet spec: max_live must be >= 1")
        self.max_live = min(self.max_live, self.population)
        self.algorithm = str(doc.get("algorithm", "fedbuff"))
        if self.algorithm not in FLEET_ALGORITHMS:
            raise ValueError(
                f"fleet spec: algorithm must be one of {FLEET_ALGORITHMS}, "
                f"got {self.algorithm!r}"
            )
        self.mode = str(doc.get("mode", "lite"))
        if self.mode not in FLEET_MODES:
            raise ValueError(
                f"fleet spec: mode must be one of {FLEET_MODES}, "
                f"got {self.mode!r}"
            )
        self.rounds = int(doc.get("rounds", 20))
        self.max_workers = int(doc.get("max_workers", 0)) or self.max_live
        self.async_buffer_k = int(doc.get("async_buffer_k", 4))
        self.tiers: Dict[str, float] = {
            str(k): float(v) for k, v in (doc.get("tiers") or {}).items()
        }
        asg = doc.get("assignments", [0, 0])
        if not (isinstance(asg, (list, tuple)) and len(asg) == 2):
            raise ValueError(
                "fleet spec: assignments must be a [min, max] budget range"
            )
        self.assignments = (int(asg[0]), int(asg[1]))
        if self.assignments[0] < 0 or self.assignments[1] < self.assignments[0]:
            raise ValueError(
                "fleet spec: assignments range must satisfy 0 <= min <= max"
            )
        self.seed = int(doc.get("seed", 0))
        self.base_port = int(doc.get("base_port", 19400))
        self.send_fault_p = float(doc.get("send_fault_p", 0.0))
        self.send_retries = int(doc.get("send_retries", 6))
        self.send_timeout_s = float(doc.get("send_timeout_s", 20.0))
        self.deadline_s = float(doc.get("deadline_s", 0.0))
        self.grpc_max_workers = int(doc.get("grpc_max_workers", 0))
        self.grpc_stream_budget = int(doc.get("grpc_stream_budget", 0))
        self.orphan_deadline_s = float(doc.get("orphan_deadline_s", 60.0))
        self.client_deadline_s = float(doc.get("client_deadline_s", 300.0))
        self.run_deadline_s = float(doc.get("run_deadline_s", 900.0))
        self.fault_plan = str(doc.get("fault_plan", ""))
        self.feat_dim = int(doc.get("feat_dim", 8))
        self.num_classes = int(doc.get("num_classes", 3))
        self.batch_size = int(doc.get("batch_size", 8))
        self.cli_args: List[str] = [str(a) for a in doc.get("cli_args", [])]
        self.cli_rank0_args: List[str] = [
            str(a) for a in doc.get("cli_rank0_args", [])
        ]
        if self.algorithm == "fedavg":
            # the sync transport has a fixed per-round fleet: every wire
            # rank must exist for the whole run — no rolling population
            if self.population > self.max_live:
                raise ValueError(
                    "fleet spec: algorithm=fedavg needs population <= "
                    "max_live (sync rounds have a fixed wire fleet; churn "
                    "is a fedbuff admission-door feature)"
                )
            if self.assignments != (0, 0):
                raise ValueError(
                    "fleet spec: assignments churn budgets are a fedbuff "
                    "feature (sync workers live for the whole run)"
                )
            if self._plan_has_participation_faults() and self.deadline_s <= 0:
                raise ValueError(
                    "fleet spec: sync fleets with dropout-capable tiers "
                    "need deadline_s > 0 (the server's all-received "
                    "barrier would wait forever)"
                )

    # -- construction ------------------------------------------------------

    @classmethod
    def from_json(cls, doc: dict) -> "FleetSpec":
        return cls(doc)

    @classmethod
    def from_spec(cls, spec: str) -> "FleetSpec":
        """Inline JSON (starts with ``{``) or a path to a JSON file."""
        text = str(spec).strip()
        if not text.startswith("{"):
            if not os.path.exists(text):
                raise ValueError(
                    f"fleet spec {text!r} is neither inline JSON nor an "
                    "existing file"
                )
            with open(text) as f:
                text = f.read()
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"fleet spec is not valid JSON: {e}") from e
        return cls.from_json(doc)

    def to_json(self) -> dict:
        return {
            "population": self.population,
            "max_live": self.max_live,
            "algorithm": self.algorithm,
            "mode": self.mode,
            "rounds": self.rounds,
            "max_workers": self.max_workers,
            "async_buffer_k": self.async_buffer_k,
            "tiers": dict(self.tiers),
            "assignments": list(self.assignments),
            "seed": self.seed,
            "base_port": self.base_port,
            "send_fault_p": self.send_fault_p,
            "send_retries": self.send_retries,
            "send_timeout_s": self.send_timeout_s,
            "deadline_s": self.deadline_s,
            "grpc_max_workers": self.grpc_max_workers,
            "grpc_stream_budget": self.grpc_stream_budget,
            "orphan_deadline_s": self.orphan_deadline_s,
            "client_deadline_s": self.client_deadline_s,
            "run_deadline_s": self.run_deadline_s,
            "fault_plan": self.fault_plan,
            "feat_dim": self.feat_dim,
            "num_classes": self.num_classes,
            "batch_size": self.batch_size,
            "cli_args": list(self.cli_args),
            "cli_rank0_args": list(self.cli_rank0_args),
        }

    # -- derived (all pure in the spec) ------------------------------------

    def fault_plan_spec(self) -> str:
        """The fault-plan string clients and server inject from: an
        explicit ``fault_plan`` override (e.g. ``trace:<path>`` replay)
        wins; otherwise the tier mix materializes through the fault-plan
        ``"fleet"`` shorthand; '' = no faults."""
        if self.fault_plan:
            return self.fault_plan
        if not self.tiers:
            return ""
        return json.dumps({
            "seed": self.seed,
            "fleet": dict(self.tiers),
            "num_clients": self.population,
        }, sort_keys=True)

    def _plan_has_participation_faults(self) -> bool:
        from fedml_tpu.scheduler.faults import FaultPlan

        plan = FaultPlan.from_spec(self.fault_plan_spec())
        return plan is not None and plan.has_participation_faults()

    def assignment_budget(self, rank: int) -> int:
        """Per-client churn budget: how many dispatches client ``rank``
        handles before requesting leave (0 = stay until FINISH). Pure in
        (seed, rank) so a replayed fleet churns identically."""
        lo, hi = self.assignments
        if hi <= 0:
            return 0
        rng = np.random.default_rng(
            [self.seed & 0x7FFFFFFF, int(rank), 0xC4B2]
        )
        return int(rng.integers(lo, hi + 1))

    def join_order(self) -> List[int]:
        """The deterministic order client ranks enter the fleet (the wave
        schedule: the launcher spawns from this list as slots free up)."""
        ranks = np.arange(1, self.population + 1)
        rng = np.random.default_rng([self.seed & 0x7FFFFFFF, 0x10C4])
        rng.shuffle(ranks)
        return [int(r) for r in ranks]

    def client_ranks(self) -> List[int]:
        return list(range(1, self.population + 1))
