"""``python -m fedml_tpu fleet`` — launch a wire fleet from a FleetSpec.

Examples::

    # 1000-process fedbuff churn fleet against one tenant
    python -m fedml_tpu fleet --spec fleet.json --out_dir /tmp/fleet

    # inline spec, ops port for live /fleet + /status
    python -m fedml_tpu fleet --spec '{"population": 64, "rounds": 10}' \\
        --prom_port 9109 --out_dir /tmp/fleet

Exit status is 0 only when the launcher's ``ok`` verdict holds (tenant
finished, zero stuck ranks, zero client errors, thread bound held).
"""

from __future__ import annotations

import argparse
import json
import sys

from fedml_tpu.fleet.launcher import FleetLauncher
from fedml_tpu.fleet.spec import FleetSpec


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m fedml_tpu fleet",
        description="Launch a wire fleet (fedml_tpu/fleet/) from a spec.",
    )
    p.add_argument(
        "--spec", required=True,
        help="fleet spec: inline JSON or a path to a JSON file",
    )
    p.add_argument(
        "--out_dir", default="fleet_out",
        help="run directory (fleet_stats.json, fault_trace.json, "
        "per-tenant telemetry)",
    )
    p.add_argument(
        "--prom_port", type=int, default=None,
        help="ops port for the hosting FederationServer "
        "(/metrics, /status, /fleet)",
    )
    p.add_argument(
        "--population", type=int, default=None,
        help="override the spec's population",
    )
    p.add_argument(
        "--max_live", type=int, default=None,
        help="override the spec's concurrent-process wave width",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the final launcher stats as JSON on stdout",
    )
    return p


def fleet_main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    spec = FleetSpec.from_spec(args.spec)
    if args.population is not None or args.max_live is not None:
        doc = spec.to_json()
        if args.population is not None:
            doc["population"] = args.population
        if args.max_live is not None:
            doc["max_live"] = args.max_live
        spec = FleetSpec(doc)
    launcher = FleetLauncher(
        spec, args.out_dir, prom_port=args.prom_port
    )
    stats = launcher.run()
    if args.json:
        print(json.dumps(stats, indent=2, sort_keys=True))
    else:
        keys = (
            "population", "spawned", "completed", "left", "finished_early",
            "orphaned", "errors", "reaped", "stuck", "joins_accepted",
            "joins_refused", "comm/refused", "grpc_threads_max",
            "grpc_executor_workers", "elapsed_s", "joined_per_s", "ok",
        )
        for k in keys:
            if k in stats:
                print(f"{k}: {stats[k]}")
    return 0 if stats.get("ok") else 1


def main() -> None:
    sys.exit(fleet_main())


if __name__ == "__main__":
    main()
