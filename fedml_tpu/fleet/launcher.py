"""FleetLauncher — supervise ≥1000 OS-process wire clients against one tenant.

The launcher owns both halves of a wire fleet:

- **the tenant**: one server-only :class:`~fedml_tpu.serve.session.FedSession`
  (``external_clients=True``) hosted in a
  :class:`~fedml_tpu.serve.server.FederationServer` in THIS process, its
  rank-0 gRPC endpoint sized by the spec's connection budgets
  (``grpc_max_workers`` / ``grpc_stream_budget``);
- **the fleet**: client OS processes preforked through a ``forkserver``
  context (fleet/client.py is the preload target, so ≥1000 children fork
  from one warm parent instead of paying 1000 cold jax/grpc imports).

The churn loop IS the rolling population: the spec's seed-deterministic
``join_order()`` feeds a spawn queue; at most ``max_live`` children run
concurrently; every reaped exit (a client left after spending its
``assignment_budget``, was refused at the admission door, or completed)
frees a slot that is back-filled from the queue. Join/leave waves at
fleet scale therefore reduce to bounded process supervision:

- O(active) state: per-child result files are folded into aggregate
  counters and deleted as children are reaped; the event log is a
  bounded deque — nothing the launcher keeps grows with the total
  population.
- stragglers/zombies: each child gets a kill deadline
  (``client_deadline_s``); past it the launcher escalates SIGTERM →
  SIGKILL and counts the reap. A whole-fleet watchdog
  (``run_deadline_s``) stops the tenant and fails the run rather than
  hang CI.
- the server thread bound is ASSERTED, not eyeballed: the launcher
  samples the live ``grpc-comm`` executor threads and fails the run if
  they ever exceed the configured executor size.

Launcher stats stream into the process-global
:class:`~fedml_tpu.telemetry.wire.FleetAggregator` (``/fleet`` when the
server has an ops port) and land in ``fleet_stats.json`` next to the
merged fleet-wide ``fault_trace.json`` — which replays byte-identically
through ``fault_plan="trace:<path>"`` on a spec with the same seed.

``mode="cli"`` drives full ``python -m fedml_tpu --rank N`` processes
through the same supervision loop — one code path for the 8-rank CI
parity smoke and the 1000-process lite fleet.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from fedml_tpu.fleet.client import (
    EXIT_COMPLETED,
    EXIT_ERROR,
    EXIT_FINISHED_EARLY,
    EXIT_LEFT,
    EXIT_ORPHANED,
    HANG_ENV,
    client_process_main,
)
from fedml_tpu.fleet.spec import FleetSpec

_EXIT_CLASS = {
    EXIT_COMPLETED: "completed",
    EXIT_LEFT: "left",
    EXIT_FINISHED_EARLY: "finished_early",
    EXIT_ORPHANED: "orphaned",
    EXIT_ERROR: "errors",
}

#: grace after the tenant finishes before leftover children are
#: terminated — long enough for the FINISH broadcast to reach them
_FINISH_GRACE_S = 10.0
#: SIGTERM → SIGKILL escalation gap for reaped stragglers
_KILL_GRACE_S = 5.0
#: how long an empty fleet must persist (tenant still not done) before
#: the launcher declares it exhausted — covers the window where clients
#: have exited on FINISH but the server thread is still finalizing
_EXHAUSTED_GRACE_S = 10.0


def _grpc_comm_threads(prefix: str = "grpc-comm") -> int:
    """Live threads of ONE gRPC executor in THIS process, identified by its
    unique ``thread_name_prefix`` (``GrpcCommManager.thread_prefix``). The
    prefix scoping matters: idle executor threads left behind by earlier
    managers in the same process (previous lite-mode runs, test suites)
    must not count against THIS server's thread bound."""
    return sum(
        1 for t in threading.enumerate() if t.name.startswith(prefix)
    )


class FleetLauncher:
    """Materialize a :class:`FleetSpec` and run it to completion."""

    def __init__(
        self,
        spec: FleetSpec,
        out_dir: str,
        log_fn: Optional[Callable[[str], None]] = None,
        prom_port: Optional[int] = None,
    ):
        self.spec = spec
        self.out_dir = str(out_dir)
        self.prom_port = prom_port
        self._log = log_fn or (lambda m: print(f"[fleet] {m}", flush=True))
        self._client_dir = os.path.join(self.out_dir, "clients")
        # bounded event log: O(max_live), NOT O(population)
        self.recent = deque(maxlen=max(32, 4 * spec.max_live))
        self.stats: Dict[str, object] = {}
        self._fault_events: List[list] = []
        self._server_comm = None
        # ranks refused at the admission door go back in the queue (the
        # server admits a refused rank once a slot opens) — bounded
        # per-rank so a saturated tenant can't spin a rank forever
        self._requeue: deque = deque()
        self._requeue_counts: Dict[int, int] = {}
        self._spawn_pause_until = 0.0

    # -- public ------------------------------------------------------------

    def run(self) -> dict:
        os.makedirs(self._client_dir, exist_ok=True)
        t0 = time.monotonic()
        self.stats = {
            "population": self.spec.population,
            "max_live": self.spec.max_live,
            "algorithm": self.spec.algorithm,
            "mode": self.spec.mode,
            "spawned": 0,
            "completed": 0,
            "left": 0,
            "finished_early": 0,
            "orphaned": 0,
            "errors": 0,
            "reaped": 0,
            "terminated_late": 0,
            "no_result": 0,
            "never_spawned": 0,
            "fault_events": 0,
            "grpc_threads_max": 0,
            "ok": False,
        }
        try:
            if self.spec.mode == "cli":
                self._run_cli()
            else:
                self._run_lite()
        finally:
            self.stats["elapsed_s"] = round(time.monotonic() - t0, 3)
            elapsed = max(1e-9, float(self.stats["elapsed_s"]))
            joined = self.stats.get(
                "joins_accepted", self.stats["spawned"]
            )
            self.stats["joined_per_s"] = round(float(joined) / elapsed, 3)
            self._publish_stats(final=True)
            with open(os.path.join(self.out_dir, "fleet_stats.json"), "w") as f:
                json.dump(self.stats, f, indent=2, sort_keys=True)
            if self.spec.mode == "lite":
                # the server ran in THIS process, so the fleet digests
                # (per-tier train_s/rtt_s percentiles fed by client
                # beacons) are in the process-global aggregator — persist
                # them so out-of-process consumers (bench.py, CI) can read
                # latency percentiles without scraping the /fleet route.
                # cli-mode servers own their aggregator and publish it via
                # their own ops port instead.
                try:
                    from fedml_tpu.telemetry.wire import get_fleet

                    path = os.path.join(self.out_dir, "fleet_telemetry.json")
                    with open(path, "w") as f:
                        json.dump(
                            get_fleet().snapshot(), f,
                            indent=2, sort_keys=True,
                        )
                except Exception:  # noqa: BLE001 — telemetry must not fail the run
                    pass
        return dict(self.stats)

    # -- lite mode (forkserver fleet against an in-process tenant) ---------

    def _run_lite(self) -> None:
        import multiprocessing as mp

        server, session = self._build_tenant()
        ctx = mp.get_context("forkserver")
        try:
            # warm parent: all children fork from one process that has
            # already paid the jax/grpc/fedml imports (fleet/client.py)
            ctx.set_forkserver_preload(["fedml_tpu.fleet.client"])
        except Exception:  # noqa: BLE001 — forkserver already running
            pass
        sync = self.spec.algorithm == "fedavg"
        pending = deque(
            self.spec.client_ranks() if sync else self.spec.join_order()
        )
        live: Dict[int, dict] = {}
        try:
            if sync:
                # the sync INIT broadcast blocks until every wire rank
                # answers — the whole fixed fleet must exist first
                while pending:
                    self._spawn(ctx, pending.popleft(), live)
                server.start([session.name])
            else:
                # fedbuff: the admission door is open from the start;
                # churn waves roll the population through max_live slots
                server.start([session.name])
            self._supervise(ctx, session, pending, live)
            self.stats["never_spawned"] = len(pending)
            try:
                session.wait(timeout=1.0)
            except Exception as e:  # noqa: BLE001 — priced below
                self.stats.setdefault("session_error", repr(e))
            self._collect_session(session)
            self._assert_bounds()
            self.stats["ok"] = (
                session.state == "done"
                and not self.stats.get("session_error")
                and not self.stats.get("watchdog_expired")
                and not self.stats.get("fleet_exhausted")
                and self.stats["errors"] == 0
                and self.stats["orphaned"] == 0
                and self.stats["stuck"] == 0
                and bool(self.stats["thread_bound_ok"])
            )
        finally:
            self._kill_all(live)
            try:
                server.close()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass
        self._write_trace()

    def _build_tenant(self):
        from fedml_tpu.config import (
            CommConfig,
            DataConfig,
            FedConfig,
            RunConfig,
            TrainConfig,
        )
        from fedml_tpu.core.grpc_comm import GrpcCommManager
        from fedml_tpu.data.synthetic import synthetic_classification
        from fedml_tpu.models import create_model
        from fedml_tpu.serve.server import FederationServer

        spec = self.spec
        sync = spec.algorithm == "fedavg"
        config = RunConfig(
            data=DataConfig(batch_size=spec.batch_size),
            fed=FedConfig(
                client_num_in_total=spec.population,
                client_num_per_round=(
                    spec.population if sync else spec.max_live
                ),
                comm_round=spec.rounds,
                epochs=1,
                # eval exactly once, at the final flush: every eval runs
                # (and first compiles) inside the server's single drain
                # thread, and a fleet's clients are all waiting on that
                # thread for their upload replies — mid-run evals at
                # fleet scale turn straight into orphan deadlines
                frequency_of_the_test=spec.rounds,
                async_buffer_k=spec.async_buffer_k,
                fault_plan=spec.fault_plan_spec(),
                deadline_s=spec.deadline_s,
            ),
            train=TrainConfig(client_optimizer="sgd", lr=0.1),
            comm=CommConfig(
                send_retries=spec.send_retries,
                send_timeout_s=spec.send_timeout_s,
                grpc_max_workers=spec.grpc_max_workers,
                grpc_stream_budget=spec.grpc_stream_budget,
            ),
            seed=spec.seed,
        )
        data = synthetic_classification(
            num_clients=spec.population,
            num_classes=spec.num_classes,
            feat_shape=(spec.feat_dim,),
            samples_per_client=16,
            partition_method="homo",
            seed=spec.seed + 1,
        )
        model = create_model(
            "lr", "synthetic", (spec.feat_dim,), spec.num_classes
        )
        table = {r: "127.0.0.1" for r in range(spec.population + 1)}

        def comm_factory(rank: int):
            if rank != 0:
                raise RuntimeError(
                    "fleet tenant is server-only; client comms live in the "
                    f"fleet's OS processes (asked for rank {rank})"
                )
            comm = GrpcCommManager(
                0,
                table,
                base_port=spec.base_port,
                send_timeout_s=spec.send_timeout_s,
                max_workers=spec.grpc_max_workers,
                stream_budget=spec.grpc_stream_budget,
                # concurrency is bounded by the wave width, not the
                # total population — auto-size the executor from it
                expected_peers=spec.max_live,
            )
            self._server_comm = comm
            return comm

        server = FederationServer(log_dir=self.out_dir, prom_port=self.prom_port)
        kw: Dict[str, object] = dict(
            algorithm=spec.algorithm,
            runtime="grpc",
            comm_factory=comm_factory,
            external_clients=True,
        )
        if not sync:
            kw["max_workers"] = spec.max_workers
        session = server.create_session("fleet", config, data, model, **kw)
        return server, session

    def _payload(self, rank: int) -> dict:
        spec = self.spec
        return {
            "rank": rank,
            "population": spec.population,
            "client_num_per_round": (
                spec.population if spec.algorithm == "fedavg"
                else spec.max_live
            ),
            "algorithm": spec.algorithm,
            "rounds": spec.rounds,
            "async_buffer_k": spec.async_buffer_k,
            "seed": spec.seed,
            "base_port": spec.base_port,
            "fault_plan": spec.fault_plan_spec(),
            "send_fault_p": spec.send_fault_p,
            "send_retries": spec.send_retries,
            "send_timeout_s": spec.send_timeout_s,
            "deadline_s": spec.deadline_s,
            "orphan_deadline_s": spec.orphan_deadline_s,
            "assignment_budget": spec.assignment_budget(rank),
            "batch_size": spec.batch_size,
            # test hook, threaded through the payload because forkserver
            # children inherit the forkserver's env, not the launcher's
            "_test_hang": os.environ.get(HANG_ENV, ""),
        }

    def _spawn(self, ctx, rank: int, live: Dict[int, dict]) -> None:
        result_path = os.path.join(self._client_dir, f"rank_{rank}.json")
        proc = ctx.Process(
            target=client_process_main,
            args=(self._payload(rank), result_path),
            name=f"fleet-client-{rank}",
            daemon=True,
        )
        proc.start()
        now = time.monotonic()
        live[rank] = {
            "proc": proc,
            "result": result_path,
            "kill_at": now + self.spec.client_deadline_s,
            "term_at": None,
        }
        self.stats["spawned"] = int(self.stats["spawned"]) + 1

    def _supervise(self, ctx, session, pending, live: Dict[int, dict]) -> None:
        """The churn loop: reap, back-fill, enforce deadlines, sample the
        thread bound — until the tenant is done and the fleet is drained."""
        spec = self.spec
        t0 = time.monotonic()
        done_at: Optional[float] = None
        empty_since: Optional[float] = None
        last_pub = 0.0
        while True:
            now = time.monotonic()
            self._reap(live, late=done_at is not None)
            done = session.done
            if done and done_at is None:
                done_at = now
            if not done:
                while self._requeue:
                    pending.append(self._requeue.popleft())
                while (
                    pending
                    and len(live) < spec.max_live
                    and now >= self._spawn_pause_until
                ):
                    self._spawn(ctx, pending.popleft(), live)
            comm = self._server_comm
            if comm is not None:
                self.stats["grpc_threads_max"] = max(
                    int(self.stats["grpc_threads_max"]),
                    _grpc_comm_threads(
                        getattr(comm, "thread_prefix", "grpc-comm")
                    ),
                )
            if now - last_pub >= 1.0:
                last_pub = now
                self.stats["live"] = len(live)
                self._publish_stats()
            if done and not live:
                break
            if not done and not live and not pending:
                # every client has run and exited but the tenant hasn't
                # reported done yet. Grace before declaring the fleet
                # exhausted: at the natural end of a run the clients exit
                # on FINISH while the server thread is still finalizing
                # (final eval, checkpoint, state flip) — stopping the
                # session in that window would misread a clean finish as
                # starvation. Only a tenant still not done after the
                # grace genuinely ran out of assignment supply.
                if empty_since is None:
                    empty_since = now
                elif now - empty_since > _EXHAUSTED_GRACE_S:
                    self.stats["fleet_exhausted"] = True
                    self._log(
                        "fleet exhausted before the tenant finished — "
                        "stopping tenant (raise population/assignments?)"
                    )
                    try:
                        session.stop()
                    except Exception:  # noqa: BLE001 — teardown best effort
                        pass
                    break
            else:
                empty_since = None
            if done and done_at is not None and now - done_at > _FINISH_GRACE_S:
                # the tenant is finished; whatever is still alive missed
                # its FINISH (late joiner, zombie) — reap it now
                for rec in live.values():
                    rec["kill_at"] = min(rec["kill_at"], now)
                done_at = now  # re-arm so escalation gets its grace too
            if now - t0 > spec.run_deadline_s:
                self.stats["watchdog_expired"] = True
                self._log(
                    f"run deadline {spec.run_deadline_s}s expired with "
                    f"{len(live)} live clients — stopping tenant"
                )
                try:
                    session.stop()
                except Exception:  # noqa: BLE001 — teardown best effort
                    pass
                break
            time.sleep(0.05)
        self.stats["stuck"] = len(live)
        self.stats["live"] = len(live)

    def _reap(self, live: Dict[int, dict], late: bool = False) -> None:
        now = time.monotonic()
        for rank in list(live):
            rec = live[rank]
            proc = rec["proc"]
            if not proc.is_alive():
                proc.join(timeout=1.0)
                self._fold(rank, proc.exitcode, rec["result"], late=late)
                del live[rank]
                continue
            if rec["term_at"] is not None:
                if now - rec["term_at"] > _KILL_GRACE_S:
                    proc.kill()  # SIGTERM was ignored — escalate
            elif now > rec["kill_at"]:
                self.stats["reaped"] = int(self.stats["reaped"]) + 1
                self.recent.append((round(now, 1), rank, "reaped"))
                proc.terminate()
                rec["term_at"] = now

    def _fold(self, rank: int, exitcode, result_path: str, late: bool) -> None:
        """Fold one child into the aggregate counters and DELETE its
        result file — launcher state stays O(active)."""
        cls = None
        if exitcode is not None and exitcode < 0:
            cls = "terminated_late" if late else "errors"
        else:
            cls = _EXIT_CLASS.get(int(exitcode or 0), "errors")
        self.stats[cls] = int(self.stats.get(cls, 0)) + 1
        self.recent.append((round(time.monotonic(), 1), rank, cls))
        if cls == "finished_early" and not late:
            # refused at the admission door while the tenant still runs:
            # the rank gets another shot once a slot opens, and the spawn
            # pump backs off briefly so a saturated door doesn't turn
            # into a fork storm of instant refusals. The retry cap only
            # guards against a PERMANENTLY refused rank looping forever —
            # it must sit far above the attempts a saturated door needs,
            # because a rank dropped here never delivers its assignment
            # budget and a fleet sized supply≈demand (the ci gate) would
            # starve the server of its last uploads
            n = self._requeue_counts.get(rank, 0)
            if n < 50:
                self._requeue_counts[rank] = n + 1
                self._requeue.append(rank)
            self._spawn_pause_until = time.monotonic() + 0.25
        try:
            with open(result_path) as f:
                row = json.load(f)
            os.unlink(result_path)
        except (OSError, ValueError):
            self.stats["no_result"] = int(self.stats["no_result"]) + 1
            return
        events = row.get("fault_events") or []
        self._fault_events.extend(events)
        self.stats["fault_events"] = int(self.stats["fault_events"]) + len(
            events
        )
        if row.get("error"):
            # keep ONE exemplar, not a list that grows with the fleet
            self.stats.setdefault("first_client_error", str(row["error"]))

    def _kill_all(self, live: Dict[int, dict]) -> None:
        for rec in live.values():
            try:
                rec["proc"].kill()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass
        for rank in list(live):
            rec = live.pop(rank)
            rec["proc"].join(timeout=2.0)
            self._fold(rank, rec["proc"].exitcode, rec["result"], late=True)

    def _collect_session(self, session) -> None:
        row = session.status()
        for key in (
            "state",
            "server_steps",
            "version",
            "round",
            "joins_accepted",
            "joins_refused",
            "leaves",
            "comm/refused",
            "comm/send_refused",
        ):
            if key in row:
                self.stats[key] = row[key]

    def _assert_bounds(self) -> None:
        """The thread bound is a hard assertion of the fleet gate: the
        rank-0 executor may never exceed its configured size."""
        comm = self._server_comm
        bound = comm.executor_workers if comm is not None else 0
        self.stats["grpc_executor_workers"] = bound
        ok = bound > 0 and int(self.stats["grpc_threads_max"]) <= bound
        self.stats["thread_bound_ok"] = ok
        if not ok:
            self._log(
                f"THREAD BOUND VIOLATED: saw {self.stats['grpc_threads_max']} "
                f"grpc-comm threads, executor bound {bound}"
            )

    def _write_trace(self) -> None:
        """Merge every child's injected-fault events into one fleet-wide
        FaultTrace — the record half of record/replay."""
        from fedml_tpu.scheduler.faults import FaultTrace

        clients: Dict[int, dict] = {}
        for ev in self._fault_events:
            try:
                cid, rnd, kind, detail = ev
            except (TypeError, ValueError):
                continue
            rec = clients.setdefault(int(cid), {"faults": {}})
            rec["faults"].setdefault(str(kind), []).append(
                [int(rnd), float(detail)]
            )
        for rec in clients.values():
            for rows in rec["faults"].values():
                rows.sort()
            rec["trace_complete"] = True
        trace = FaultTrace(rounds=self.spec.rounds, clients=clients)
        trace.save(os.path.join(self.out_dir, "fault_trace.json"))

    def _publish_stats(self, final: bool = False) -> None:
        from fedml_tpu.telemetry.wire import get_fleet

        snap = dict(self.stats)
        snap["recent"] = [list(e) for e in self.recent]
        snap["final"] = final
        try:
            get_fleet().set_launcher_stats(snap)
        except Exception:  # noqa: BLE001 — stats must never kill the fleet
            pass

    # -- cli mode (full `python -m fedml_tpu` ranks, same supervision) -----

    def _run_cli(self) -> None:
        spec = self.spec
        log_dir = os.path.join(self.out_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        procs: Dict[int, dict] = {}
        t0 = time.monotonic()
        exits: Dict[int, int] = {}
        try:
            for rank in range(spec.population + 1):
                # "{rank}" in any arg expands to the process's rank, so
                # one declarative arg list can give every rank its own
                # --log_dir without 9 hand-rolled shell loops
                argv = [
                    sys.executable, "-m", "fedml_tpu", "--rank", str(rank),
                ] + [a.replace("{rank}", str(rank)) for a in spec.cli_args]
                if rank == 0:
                    argv += [
                        a.replace("{rank}", str(rank))
                        for a in spec.cli_rank0_args
                    ]
                logf = open(os.path.join(log_dir, f"rank_{rank}.log"), "w")
                procs[rank] = {
                    "proc": subprocess.Popen(
                        argv, stdout=logf, stderr=subprocess.STDOUT
                    ),
                    "log": logf,
                    "term_at": None,
                }
                self.stats["spawned"] = int(self.stats["spawned"]) + 1
            last_pub = 0.0
            while procs:
                now = time.monotonic()
                for rank in list(procs):
                    rec = procs[rank]
                    code = rec["proc"].poll()
                    if code is not None:
                        rec["log"].close()
                        exits[rank] = code
                        self.recent.append((round(now, 1), rank, code))
                        del procs[rank]
                        continue
                    if rec["term_at"] is not None:
                        if now - rec["term_at"] > _KILL_GRACE_S:
                            rec["proc"].send_signal(signal.SIGKILL)
                    elif now - t0 > spec.run_deadline_s:
                        self.stats["watchdog_expired"] = True
                        self.stats["reaped"] = (
                            int(self.stats["reaped"]) + 1
                        )
                        rec["proc"].terminate()
                        rec["term_at"] = now
                if now - last_pub >= 1.0:
                    last_pub = now
                    self.stats["live"] = len(procs)
                    self._publish_stats()
                time.sleep(0.1)
        finally:
            for rec in procs.values():
                try:
                    rec["proc"].kill()
                    rec["log"].close()
                except Exception:  # noqa: BLE001 — teardown best effort
                    pass
        bad = {r: c for r, c in exits.items() if c != 0}
        self.stats["completed"] = sum(1 for c in exits.values() if c == 0)
        self.stats["errors"] = len(bad)
        if bad:
            self.stats["bad_exits"] = {
                str(r): int(c) for r, c in sorted(bad.items())[:16]
            }
        self.stats["ok"] = not bad and not self.stats.get("watchdog_expired")
