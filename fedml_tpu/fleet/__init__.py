"""Wire-fleet runtime: thousands of OS-process clients against one tenant.

- :mod:`fedml_tpu.fleet.spec` — :class:`FleetSpec`, the declarative
  fleet description (population, tier mix, churn, chaos, budgets);
- :mod:`fedml_tpu.fleet.launcher` — :class:`FleetLauncher`, the
  forkserver-preforked supervisor (churn loop, straggler reaping,
  bounded logging, thread-bound assertion, FaultTrace merge);
- :mod:`fedml_tpu.fleet.client` — the per-process client entry
  (preload target; numpy-only LiteTrainer over the real gRPC wire);
- :mod:`fedml_tpu.fleet.cli` — ``python -m fedml_tpu fleet``.

See docs/FLEET.md.
"""

from fedml_tpu.fleet.launcher import FleetLauncher
from fedml_tpu.fleet.spec import FleetSpec

__all__ = ["FleetLauncher", "FleetSpec"]
