"""Rényi-DP accountant for the subsampled Gaussian mechanism.

The reference's only privacy story is "weak DP" — norm clipping plus an
ad-hoc Gaussian noise stddev with NO accounting of what privacy it buys
(ref fedml_core/robustness/robust_aggregation.py:51-55, `add_noise`).
This module supplies the missing ledger: per-round Rényi-DP of the
Poisson-subsampled Gaussian mechanism, additive composition across
rounds, and conversion to an (epsilon, delta) guarantee.

Math (public, standard): for integer order alpha >= 2, sampling ratio q
and noise multiplier sigma, the subsampled Gaussian mechanism satisfies

    RDP(alpha) <= 1/(alpha-1) * log( sum_{k=0..alpha}
        C(alpha,k) (1-q)^(alpha-k) q^k * exp(k(k-1)/(2 sigma^2)) )

(the integer-order bound of Mironov's "Rényi DP of the Sampled Gaussian
Mechanism"); at q=1 this reduces to the plain Gaussian RDP
alpha/(2 sigma^2) — pinned as an internal consistency test. RDP composes
additively over rounds; conversion uses the classic bound
epsilon = RDP(alpha) + log(1/delta)/(alpha-1), minimized over orders.

The DP training path executes EXACTLY this mechanism: DP-FedAvg samples
Poisson cohorts (privacy/dp_fedavg.poisson_client_sampling, each client
independently with probability q from a run-seeded secret stream) and
aggregates with the fixed-denominator estimator whose sum-sensitivity is
the clip norm — no fixed-size-vs-Poisson approximation is involved.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

_DEFAULT_ORDERS = tuple(range(2, 129)) + (160, 192, 224, 256, 320, 384, 448, 512)


def _log_comb(a: int, k: int) -> float:
    return (
        math.lgamma(a + 1) - math.lgamma(k + 1) - math.lgamma(a - k + 1)
    )


def _logsumexp(xs) -> float:
    m = max(xs)
    if m == -math.inf:
        return -math.inf
    return m + math.log(sum(math.exp(x - m) for x in xs))


def rdp_subsampled_gaussian(q: float, sigma: float, alpha: int) -> float:
    """RDP of order ``alpha`` for one subsampled-Gaussian round."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling ratio q must be in [0, 1], got {q}")
    if sigma <= 0:
        raise ValueError(f"noise multiplier must be > 0, got {sigma}")
    if alpha < 2 or int(alpha) != alpha:
        raise ValueError(f"integer alpha >= 2 required, got {alpha}")
    if q == 0.0:
        return 0.0
    if q == 1.0:
        return alpha / (2.0 * sigma * sigma)
    log_terms = [
        _log_comb(alpha, k)
        + (alpha - k) * math.log1p(-q)
        + (k * math.log(q) if k else 0.0)
        + (k * (k - 1)) / (2.0 * sigma * sigma)
        for k in range(alpha + 1)
    ]
    return _logsumexp(log_terms) / (alpha - 1)


class RdpAccountant:
    """Additive RDP ledger over training rounds.

    >>> acct = RdpAccountant()
    >>> acct.step(q=10/128, noise_multiplier=1.0)   # one round
    >>> eps, order = acct.epsilon(delta=1e-5)
    """

    def __init__(self, orders: Sequence[int] = _DEFAULT_ORDERS):
        self.orders = tuple(int(a) for a in orders)
        self._rdp = [0.0] * len(self.orders)
        self.rounds = 0
        # (q, sigma) -> per-round RDP vector. A training run steps with
        # the same mechanism every round; without this cache each round
        # re-evaluates ~1e4 lgamma/exp terms on the host.
        self._per_round: dict = {}

    def step(self, q: float, noise_multiplier: float, rounds: int = 1) -> None:
        key = (float(q), float(noise_multiplier))
        vec = self._per_round.get(key)
        if vec is None:
            vec = tuple(
                rdp_subsampled_gaussian(q, noise_multiplier, a)
                for a in self.orders
            )
            self._per_round[key] = vec
        self._rdp = [r + rounds * v for r, v in zip(self._rdp, vec)]
        self.rounds += rounds

    def epsilon(self, delta: float) -> Tuple[float, int]:
        """(epsilon, best_order) for the composed mechanism at ``delta``."""
        if not 0.0 < delta < 1.0:
            raise ValueError(f"delta must be in (0, 1), got {delta}")
        best = (math.inf, self.orders[0])
        log_inv_delta = math.log(1.0 / delta)
        for a, r in zip(self.orders, self._rdp):
            eps = r + log_inv_delta / (a - 1)
            if eps < best[0]:
                best = (eps, a)
        return best
