"""DP-FedAvg — client-level differential privacy with a real ledger.

The reference ships "weak DP" (norm clipping + an arbitrary noise stddev,
fedml_core/robustness/robust_aggregation.py:38-55) as a backdoor DEFENSE;
it never says — or knows — what (epsilon, delta) it provides. This module
implements the DP-FedAvg recipe (McMahan et al., "Learning Differentially
Private Recurrent Language Models" — public algorithm, fresh
implementation) on the same round-hook skeleton the robust defenses use:

  1. the round's cohort is POISSON-sampled: every client independently
     with probability q = m_hat/N, from a per-round PRNG seeded by a
     128-bit OS-entropy secret drawn at API construction
     (np.random.SeedSequence), NOT the round index alone and NOT
     config.seed — a round-seeded or default-seeded draw would be
     publicly predictable, which voids amplification-by-subsampling
     (the adversary must not know who participated). The secret rides
     in checkpoint_state so a resume continues the same stream;
  2. each sampled client's UPDATE delta_i = w_i - w_t is clipped to L2
     norm S over the ENTIRE uploaded tree (params and any stats — the
     guarantee must cover everything transmitted, so unlike the robust
     defense's BN-stat-aware clipping nothing passes through unclipped);
  3. aggregation is w_t + (1/m_hat) * sum_{i in cohort} clip_S(delta_i)
     with the FIXED expected cohort size m_hat = qN as denominator (the
     DP-FedAvg fixed-denominator estimator): the sum's sensitivity to
     adding/removing one client is exactly S regardless of the realized
     cohort, and sample-count weighting is deliberately NOT used —
     weights would make the sensitivity depend on private shard sizes;
  4. Gaussian noise N(0, (z*S/m_hat)^2) is added to every coordinate
     (noise z*S on the sum => noise multiplier z, the accounted value);
  5. an RDP accountant (privacy/accountant.py) composes the rounds. The
     executed sampler and the accounted mechanism are the SAME object:
     Poisson(q) sampling, sum-sensitivity S, noise z*S.

Variable Poisson cohorts meet XLA's static shapes by padding the client
axis to a bucketed size with all-mask-zero dummy clients: their local
step is a gated no-op (delta exactly 0, pinned by tests) AND the
aggregate excludes them explicitly (num_samples == 0), so padding never
changes the mechanism. 2-4 run inside the one jitted round function via
the post_train/aggregate_fn/post_aggregate hooks of make_fedavg_round —
the DP math adds no host round-trips.
"""

from __future__ import annotations

import dataclasses
import secrets
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI, make_fedavg_round
from fedml_tpu.algorithms.fedavg_robust import NOISE_FOLD
from fedml_tpu.data.base import ClientBatch, pad_clients_to, size_class
from fedml_tpu.privacy.accountant import RdpAccountant

# Domain tag folded into the cohort-sampling SeedSequence so the DP
# participation stream can never collide with any other consumer of the
# run seed (data shuffling uses seed*1_000_003+round, model init folds 0).
_DP_SAMPLE_TAG = 0x44505F53  # "DP_S"


def poisson_client_sampling(
    run_seed: int, round_idx: int, client_num_in_total: int, q: float
) -> np.ndarray:
    """One Poisson cohort draw: every client independently with probability
    ``q``, from a fresh per-round stream derived from ``run_seed`` — which
    the API feeds from a 128-bit OS-entropy secret (``fresh_sample_secret``),
    never from ``config.seed``.

    This is the sampler the RDP accountant's subsampled-Gaussian bound is
    FOR — and unlike :func:`fedavg.client_sampling`'s round-seeded draw
    (reference parity, FedAVGAggregator.py:80-88) it is not predictable
    from public information alone: amplification by subsampling requires
    the adversary not to know who participated, so the stream's seed must
    be secret AND high-entropy for the epsilon to hold."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"sampling probability q must be in (0, 1], got {q}")
    rng = np.random.default_rng(
        np.random.SeedSequence((int(run_seed), _DP_SAMPLE_TAG, int(round_idx)))
    )
    return np.flatnonzero(rng.random(client_num_in_total) < q)


def bucket_cohort(m: int) -> int:
    """Static client-axis size for a realized Poisson cohort of ``m`` —
    the shared size-class policy (data/base.size_class), so the set of
    compiled shapes stays small while padding waste is bounded."""
    return size_class(max(int(m), 1))


@dataclasses.dataclass(frozen=True)
class DpConfig:
    """Client-level DP-FedAvg knobs."""

    clip_norm: float = 1.0  # S: per-client update L2 bound
    noise_multiplier: float = 1.0  # z: noise stddev in units of S (on the sum)
    delta: float = 1e-5  # the delta at which epsilon is reported
    # Secret seeding the Poisson participation stream. None (the default)
    # draws 128 bits from OS entropy at API construction — the epsilon
    # claim requires the adversary not to predict who participated, and
    # config.seed is a public, low-entropy, reused value (data shuffling
    # and the broadcast w_0 both derive from it), so it must never seed
    # the cohorts. Pass an explicit value ONLY for tests/repro; anything
    # under 64 bits warns that amplification-by-subsampling is void.
    sample_secret: int | None = None


def fresh_sample_secret() -> int:
    """128 bits of OS entropy for the DP participation stream."""
    return secrets.randbits(128)


def _secret_to_words(secret: int, n_words: int = 8) -> np.ndarray:
    """Secret int -> uint32 word array (little-endian). uint32 because the
    words may ride through jax collectives (multi-host broadcast), where
    64-bit ints are silently truncated to 32 bits with x64 disabled — the
    one encoding shared by checkpointing and broadcast so a truncating
    variant can't creep in."""
    if secret.bit_length() > 32 * n_words:
        raise ValueError(f"secret exceeds {32 * n_words} bits")
    return np.asarray(
        [(secret >> (32 * i)) & 0xFFFFFFFF for i in range(n_words)], np.uint32
    )


def _words_to_secret(words) -> int:
    """Inverse of :func:`_secret_to_words`; decodes by the array's actual
    word width rather than assuming 32 bits (defensive — a checkpoint
    edited or produced by other tooling stays restorable)."""
    words = np.asarray(words)
    bits = words.dtype.itemsize * 8
    return sum(int(w) << (bits * i) for i, w in enumerate(words.tolist()))


def clip_update_tree(local_tree, global_tree, clip_norm: float):
    """w_t + clip_S(w_l - w_t) with the L2 norm taken over EVERY leaf of
    the update (full-tree sensitivity — see module docstring)."""
    sq = sum(
        jnp.sum(jnp.square((l - g).astype(jnp.float32)))
        for l, g in zip(
            jax.tree_util.tree_leaves(local_tree),
            jax.tree_util.tree_leaves(global_tree),
        )
    )
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(
        lambda l, g: (g + (l - g) * scale).astype(l.dtype), local_tree, global_tree
    )


def make_dp_hooks(dp: DpConfig, expected_cohort: int):
    """(post_train, aggregate_fn, post_aggregate) for make_fedavg_round /
    make_sharded_fedavg_round.

    The aggregate is the fixed-denominator estimator
    ``w_t + (1/m_hat) * sum_incl clip_S(delta_i)`` with ``m_hat =
    expected_cohort``: sensitivity of the sum is exactly clip_norm under
    add/remove adjacency whatever the realized Poisson cohort, so the
    noise z*S/m_hat on the result is the accounted subsampled-Gaussian
    mechanism. Padding rows (num_samples == 0) are excluded by the
    inclusion mask — and contribute exact-zero deltas anyway (gated no-op
    local steps). num_samples is used ONLY as the inclusion indicator,
    never as a weight (weights would tie sensitivity to private shard
    sizes)."""

    def post_train(client_vars, global_vars, noise_rng):
        return jax.vmap(
            lambda cv: clip_update_tree(cv, global_vars, dp.clip_norm)
        )(client_vars)

    def aggregate_fn(client_vars, num_samples, g):
        incl = (num_samples > 0).astype(jnp.float32)

        def mean_delta(s, gl):
            base = gl.astype(jnp.float32)
            delta = s.astype(jnp.float32) - base[None]
            return base + jnp.tensordot(incl, delta, axes=1) / float(
                expected_cohort
            )

        return jax.tree_util.tree_map(mean_delta, client_vars, g)

    stddev = dp.noise_multiplier * dp.clip_norm / expected_cohort

    def post_aggregate(new_global, noise_rng):
        flat, treedef = jax.tree_util.tree_flatten(new_global)
        rngs = jax.random.split(noise_rng, len(flat))
        noised = [
            leaf + jax.random.normal(r, leaf.shape, jnp.float32) * stddev
            for r, leaf in zip(rngs, flat)
        ]
        return jax.tree_util.tree_unflatten(treedef, noised)

    return post_train, aggregate_fn, post_aggregate


class DPFedAvgAPI(FedAvgAPI):
    """FedAvg simulator with client-level DP and per-round accounting.

    ``client_num_per_round`` is reinterpreted as the EXPECTED cohort size
    m_hat: cohorts are Poisson(q = m_hat/N) draws (see
    :func:`poisson_client_sampling`), padded to a bucketed static client
    axis so realized sizes don't multiply compiled shapes."""

    _supports_fused = False  # the accountant steps on the host every round
    sampling = "poisson"

    def __init__(self, config, data, model, dp: DpConfig = DpConfig(), **kw):
        self.dp = dp
        super().__init__(config, data, model, **kw)
        # The participation stream's seed is OS entropy, NOT config.seed:
        # config.seed is public/low-entropy (defaults to 0, reused by data
        # shuffling and the broadcast init), so cohorts derived from it are
        # predictable and the accountant's amplification-by-subsampling
        # claim is void (advisor r4, medium). An explicit dp.sample_secret
        # is honored for tests/repro and resume, with a warning when it is
        # too small to be credible entropy.
        if dp.sample_secret is None:
            self._sample_secret = fresh_sample_secret()
            self._secret_provenance = "128-bit OS entropy"
            if jax.process_count() > 1:
                # every process must draw the SAME cohorts (mismatched
                # cohort shapes would wedge the SPMD round's collectives):
                # process 0's draw wins, broadcast as uint32 words (jax
                # would silently truncate 64-bit words with x64 disabled)
                from jax.experimental import multihost_utils

                self._sample_secret = _words_to_secret(
                    np.asarray(
                        multihost_utils.broadcast_one_to_all(
                            _secret_to_words(self._sample_secret)
                        )
                    ).astype(np.uint32)
                )
        else:
            self._sample_secret = int(dp.sample_secret)
            if self._sample_secret < 0:
                raise ValueError(
                    "DpConfig.sample_secret must be a non-negative integer "
                    f"(got {self._sample_secret}); SeedSequence rejects "
                    "negative entropy"
                )
            if self._sample_secret.bit_length() > 256:
                # checkpoint_state serializes the secret into 8 uint32
                # words — reject at construction, not mid-run at the
                # first checkpoint
                raise ValueError(
                    "DpConfig.sample_secret wider than 256 bits cannot be "
                    "checkpointed; 128 bits is already full strength"
                )
            self._secret_provenance = (
                f"explicit DpConfig.sample_secret "
                f"({self._sample_secret.bit_length()} bits — amplification "
                "holds only if this value is secret and high-entropy)"
            )
            if self._sample_secret.bit_length() < 64:
                warnings.warn(
                    "DpConfig.sample_secret has <64 bits of entropy: the "
                    "Poisson cohorts are predictable and the reported "
                    "epsilon's amplification-by-subsampling does not hold. "
                    "Use this only for tests/reproduction.",
                    stacklevel=2,
                )
        self.accountant = RdpAccountant()
        # N from the DATA (the population actually sampled from), not the
        # config echo — the accounted q and the executed q must be the
        # same number
        self._q = config.fed.client_num_per_round / data.num_clients
        if not 0.0 < self._q <= 1.0:
            raise ValueError(
                f"fed.client_num_per_round={config.fed.client_num_per_round} "
                f"with {data.num_clients} clients gives DP sampling "
                f"probability q={self._q:.4g}; need 0 < q <= 1"
            )

    def _sample_clients(self, round_idx: int) -> np.ndarray:
        # the SAME q the accountant steps with — mechanism == ledger
        return poisson_client_sampling(
            self._sample_secret, round_idx, self.data.num_clients, self._q
        )

    def _round_batch(self, sampled, round_idx: int):
        m = len(sampled)
        if m == 0:
            # an empty Poisson cohort is a legal round: the model moves by
            # noise only. Build an all-masked zero batch at the SAME shape
            # class _round_plan advertised (bucket_steps([1]) — one
            # notional sample), so plan and executed shapes agree and the
            # dead compute is one tiny gated no-op step, not a full
            # client's worth.
            _, steps, bs = self._round_plan(round_idx)
            feat = self.data.client_x[0].shape[1:]
            lab = self.data.client_y[0].shape[1:]
            batch = ClientBatch(
                x=np.zeros((1, steps, bs) + feat, self.data.client_x[0].dtype),
                y=np.zeros((1, steps, bs) + lab, self.data.client_y[0].dtype),
                mask=np.zeros((1, steps, bs), np.float32),
                num_samples=np.zeros((1,), np.float32),
            )
        else:
            batch = super()._round_batch(sampled, round_idx)
        return pad_clients_to(batch, bucket_cohort(m))

    def _round_may_pad(self, round_idx: int, force_steps: int = 0) -> bool:
        sampled = self._round_plan(round_idx)[0]
        m = len(sampled)
        if m == 0 or bucket_cohort(m) > m:
            return True  # dummy cohort rows are all-padding steps
        return super()._round_may_pad(round_idx, force_steps)

    def _build_round_fn(self, local_train_fn):
        post_train, aggregate_fn, post_aggregate = make_dp_hooks(
            self.dp, self.config.fed.client_num_per_round
        )
        return make_fedavg_round(
            self.model,
            self.config,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
            post_train=post_train,
            aggregate_fn=aggregate_fn,
            post_aggregate=post_aggregate,
        )

    def _place_batch(self, batch, round_rng):
        base = super()._place_batch(batch, round_rng)
        return base + (jax.random.fold_in(round_rng, NOISE_FOLD),)

    def train_round(self, round_idx: int):
        out = super().train_round(round_idx)
        self.accountant.step(self._q, self.dp.noise_multiplier)
        return out

    def checkpoint_state(self):
        """The RDP ledger is round state: a resume that dropped it would
        report the epsilon of the post-crash rounds only — under-claiming
        the true privacy cost of everything already released."""
        import numpy as np

        # the sampling secret rides along (as uint32 words — it exceeds
        # int64): a resume that re-drew it would fork the participation
        # stream mid-ledger, decoupling the executed mechanism from the
        # accounted one for the remaining rounds. DISCLOSURE: a checkpoint
        # carrying dp_sample_secret reveals the whole participation stream
        # to anyone who reads it — checkpoints of DP runs are secrets
        # themselves and must not be published while the epsilon claim is
        # supposed to hold against recipients of the artifact
        return {
            "dp_rdp": np.asarray(self.accountant._rdp, np.float64),
            "dp_rounds": np.asarray(self.accountant.rounds, np.int64),
            "dp_sample_secret": _secret_to_words(self._sample_secret),
        }

    def restore_state(self, tree):
        import numpy as np

        self.accountant._rdp = [float(v) for v in np.asarray(tree["dp_rdp"])]
        self.accountant.rounds = int(np.asarray(tree["dp_rounds"]))
        if "dp_sample_secret" in tree:
            self._sample_secret = _words_to_secret(tree["dp_sample_secret"])
        else:
            warnings.warn(
                "checkpoint predates dp_sample_secret: it was written by a "
                "build whose cohorts derived from the public config.seed "
                "(amplification-by-subsampling did not hold for those "
                "rounds). The participation stream forks here — continuing "
                "with this API's constructed secret (fresh OS entropy "
                "unless DpConfig.sample_secret was set); the ledger's "
                "epsilon is honest only from this round on.",
                stacklevel=2,
            )
            self._secret_provenance += (
                " (resumed from a pre-secret checkpoint: earlier cohorts "
                "derived from the public config.seed)"
            )

    def privacy_spent(self):
        eps, order = self.accountant.epsilon(self.dp.delta)
        return {
            "DP/epsilon": round(float(eps), 4),
            "DP/delta": self.dp.delta,
            "DP/rdp_order": order,
            "DP/rounds_accounted": self.accountant.rounds,
            "DP/sampling_note": (
                f"Poisson-sampled cohorts executed at q={self._q:.4g} — "
                "the accounted mechanism and the run sampler are the same "
                "object; participation stream seeded from "
                f"{self._secret_provenance} (epsilon assumes the seed "
                "stays secret)"
            ),
        }

    def train(self):
        final = dict(super().train() or {})
        spent = self.privacy_spent()
        final.update(spent)
        self.log_fn(spent)
        return final
