"""DP-FedAvg — client-level differential privacy with a real ledger.

The reference ships "weak DP" (norm clipping + an arbitrary noise stddev,
fedml_core/robustness/robust_aggregation.py:38-55) as a backdoor DEFENSE;
it never says — or knows — what (epsilon, delta) it provides. This module
implements the DP-FedAvg recipe (McMahan et al., "Learning Differentially
Private Recurrent Language Models" — public algorithm, fresh
implementation) on the same round-hook skeleton the robust defenses use:

  1. each sampled client's UPDATE delta_i = w_i - w_t is clipped to L2
     norm S over the ENTIRE uploaded tree (params and any stats — the
     guarantee must cover everything transmitted, so unlike the robust
     defense's BN-stat-aware clipping nothing passes through unclipped);
  2. aggregation is the UNIFORM mean over the fixed-size cohort —
     sample-count weighting would make the sensitivity depend on private
     shard sizes, so it is deliberately NOT used here;
  3. Gaussian noise N(0, (z*S/m)^2) is added to every coordinate of the
     mean (sensitivity of the mean to one client is S/m);
  4. an RDP accountant (privacy/accountant.py) composes the rounds and
     reports (epsilon, delta) for q = m/N per round.

All of 1-3 run inside the one jitted round function via the
post_train/aggregate_fn/post_aggregate hooks of make_fedavg_round — the
DP math adds no host round-trips.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgAPI, make_fedavg_round
from fedml_tpu.algorithms.fedavg_robust import NOISE_FOLD
from fedml_tpu.privacy.accountant import RdpAccountant


@dataclasses.dataclass(frozen=True)
class DpConfig:
    """Client-level DP-FedAvg knobs."""

    clip_norm: float = 1.0  # S: per-client update L2 bound
    noise_multiplier: float = 1.0  # z: noise stddev in units of S (on the sum)
    delta: float = 1e-5  # the delta at which epsilon is reported


def clip_update_tree(local_tree, global_tree, clip_norm: float):
    """w_t + clip_S(w_l - w_t) with the L2 norm taken over EVERY leaf of
    the update (full-tree sensitivity — see module docstring)."""
    sq = sum(
        jnp.sum(jnp.square((l - g).astype(jnp.float32)))
        for l, g in zip(
            jax.tree_util.tree_leaves(local_tree),
            jax.tree_util.tree_leaves(global_tree),
        )
    )
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(
        lambda l, g: (g + (l - g) * scale).astype(l.dtype), local_tree, global_tree
    )


def make_dp_hooks(dp: DpConfig, cohort_size: int):
    """(post_train, aggregate_fn, post_aggregate) for make_fedavg_round."""

    def post_train(client_vars, global_vars, noise_rng):
        return jax.vmap(
            lambda cv: clip_update_tree(cv, global_vars, dp.clip_norm)
        )(client_vars)

    def aggregate_fn(client_vars, num_samples):
        # UNIFORM mean — num_samples is deliberately unused (weights would
        # tie the sensitivity to private shard sizes)
        return jax.tree_util.tree_map(
            lambda s: jnp.mean(s.astype(jnp.float32), axis=0), client_vars
        )

    stddev = dp.noise_multiplier * dp.clip_norm / cohort_size

    def post_aggregate(new_global, noise_rng):
        flat, treedef = jax.tree_util.tree_flatten(new_global)
        rngs = jax.random.split(noise_rng, len(flat))
        noised = [
            leaf + jax.random.normal(r, leaf.shape, jnp.float32) * stddev
            for r, leaf in zip(rngs, flat)
        ]
        return jax.tree_util.tree_unflatten(treedef, noised)

    return post_train, aggregate_fn, post_aggregate


class DPFedAvgAPI(FedAvgAPI):
    """FedAvg simulator with client-level DP and per-round accounting."""

    _supports_fused = False  # the accountant steps on the host every round

    def __init__(self, config, data, model, dp: DpConfig = DpConfig(), **kw):
        self.dp = dp
        super().__init__(config, data, model, **kw)
        self.accountant = RdpAccountant()
        self._q = (
            config.fed.client_num_per_round / config.fed.client_num_in_total
        )

    def _build_round_fn(self, local_train_fn):
        post_train, aggregate_fn, post_aggregate = make_dp_hooks(
            self.dp, self.config.fed.client_num_per_round
        )
        return make_fedavg_round(
            self.model,
            self.config,
            task=self.task,
            local_train_fn=local_train_fn,
            donate=self._donate,
            post_train=post_train,
            aggregate_fn=aggregate_fn,
            post_aggregate=post_aggregate,
        )

    def _place_batch(self, batch, round_rng):
        base = super()._place_batch(batch, round_rng)
        return base + (jax.random.fold_in(round_rng, NOISE_FOLD),)

    def train_round(self, round_idx: int):
        out = super().train_round(round_idx)
        self.accountant.step(self._q, self.dp.noise_multiplier)
        return out

    def checkpoint_state(self):
        """The RDP ledger is round state: a resume that dropped it would
        report the epsilon of the post-crash rounds only — under-claiming
        the true privacy cost of everything already released."""
        import numpy as np

        return {
            "dp_rdp": np.asarray(self.accountant._rdp, np.float64),
            "dp_rounds": np.asarray(self.accountant.rounds, np.int64),
        }

    def restore_state(self, tree):
        import numpy as np

        self.accountant._rdp = [float(v) for v in np.asarray(tree["dp_rdp"])]
        self.accountant.rounds = int(np.asarray(tree["dp_rounds"]))

    def privacy_spent(self):
        eps, order = self.accountant.epsilon(self.dp.delta)
        return {
            "DP/epsilon": round(float(eps), 4),
            "DP/delta": self.dp.delta,
            "DP/rdp_order": order,
            "DP/rounds_accounted": self.accountant.rounds,
            "DP/sampling_note": (
                "fixed-size cohort accounted as Poisson sampling at "
                f"q={self._q:.4g} (standard DP-FL convention)"
            ),
        }

    def train(self):
        final = dict(super().train() or {})
        spent = self.privacy_spent()
        final.update(spent)
        self.log_fn(spent)
        return final
