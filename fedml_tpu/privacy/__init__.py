"""Client-level differential privacy for federated training.

The reference's privacy ceiling is the un-accounted "weak DP" noise of
its robust aggregator (robust_aggregation.py:51-55); this package adds
real DP-FedAvg (clip + calibrated Gaussian noise on the uniform cohort
mean) and a Rényi-DP accountant that reports (epsilon, delta)."""

from fedml_tpu.privacy.accountant import RdpAccountant, rdp_subsampled_gaussian
from fedml_tpu.privacy.dp_fedavg import DpConfig, DPFedAvgAPI, make_dp_hooks

__all__ = [
    "RdpAccountant",
    "rdp_subsampled_gaussian",
    "DpConfig",
    "DPFedAvgAPI",
    "make_dp_hooks",
]
