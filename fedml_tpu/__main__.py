import sys

# `python -m fedml_tpu serve ...` — the multi-tenant service subcommand
# (fedml_tpu/serve/), and `python -m fedml_tpu status ...` — the live
# introspection pretty-printer over a running service's /status endpoint
# (fedml_tpu/serve/introspect.py). Dispatched here by argv inspection so
# the single-run surface stays exactly `python -m fedml_tpu --algorithm
# ...` (turning the CLI into a click group would have broken every
# existing invocation).
if len(sys.argv) > 1 and sys.argv[1] == "serve":
    from fedml_tpu.serve.cli import serve_main

    del sys.argv[1]
    serve_main()
elif len(sys.argv) > 1 and sys.argv[1] == "status":
    from fedml_tpu.serve.introspect import status_main

    del sys.argv[1]
    status_main()
elif len(sys.argv) > 1 and sys.argv[1] == "fleet":
    # `python -m fedml_tpu fleet --spec fleet.json` — the wire-fleet
    # launcher: prefork thousands of OS-process gRPC clients against one
    # server-only tenant (fedml_tpu/fleet/)
    from fedml_tpu.fleet.cli import main as fleet_entry

    del sys.argv[1]
    fleet_entry()
elif len(sys.argv) > 1 and sys.argv[1] == "trace":
    # `python -m fedml_tpu trace merge <dirs>` — cross-process trace
    # merge: align each rank's Chrome trace on send/recv wire timestamp
    # pairs and emit one federation timeline (telemetry/wire.py)
    from fedml_tpu.telemetry.wire import trace_main

    del sys.argv[1]
    trace_main()
else:
    from fedml_tpu.cli import main

    main()
