from fedml_tpu.cli import main

main()
