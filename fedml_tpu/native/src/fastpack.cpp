// fastpack — native runtime kernels for the host-side hot paths.
//
// The reference delegates all native work to torch/MPI libraries (SURVEY §2
// native-code note); this framework's own host hot paths are (a) stacking
// sampled clients' ragged shards into the padded device batch
// (fedml_tpu/data/base.py stack_clients — row gather by permutation into a
// preallocated zero buffer) and (b) assembling the transport wire image
// (core/message.py to_bytes — concatenation of many array buffers). Both are
// pure memory movement: this library does them with std::thread fan-out over
// row/byte ranges. Loaded via ctypes (no pybind11 in the image); the Python
// callers fall back to numpy when the shared object is unavailable.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

int clamp_threads(int64_t work_items, int64_t min_per_thread) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  int64_t by_work = work_items / std::max<int64_t>(min_per_thread, 1);
  return static_cast<int>(std::max<int64_t>(1, std::min<int64_t>(hw, by_work)));
}

}  // namespace

extern "C" {

// Gather rows: dst[i] = src[order[i]] for i in [0, n_rows), each row
// row_bytes wide. dst/src must not overlap.
void fp_gather_rows(const char* src, const int64_t* order, int64_t n_rows,
                    int64_t row_bytes, char* dst) {
  int n_threads = clamp_threads(n_rows * row_bytes, 1 << 20);
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(dst + i * row_bytes, src + order[i] * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  };
  if (n_threads == 1) {
    worker(0, n_rows);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(n_rows, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// Concatenate n buffers into dst at the given offsets (offsets[i] is the
// destination byte offset of buffer i; lens[i] its length).
void fp_concat(const char** bufs, const int64_t* lens, const int64_t* offsets,
               int64_t n, char* dst) {
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) total += lens[i];
  int n_threads = clamp_threads(total, 1 << 22);
  if (n_threads <= 1 || n < 2) {
    for (int64_t i = 0; i < n; ++i) {
      std::memcpy(dst + offsets[i], bufs[i], static_cast<size_t>(lens[i]));
    }
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back([&, lo, hi]() {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(dst + offsets[i], bufs[i],
                    static_cast<size_t>(lens[i]));
      }
    });
  }
  for (auto& t : ts) t.join();
}

int fp_version() { return 1; }

}  // extern "C"
