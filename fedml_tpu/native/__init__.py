"""ctypes loader for the fastpack native library.

Builds src/fastpack.cpp with g++ on first use (cached in build/), exposes
:func:`gather_rows` and :func:`concat_buffers`. Every entry point has a pure
numpy fallback, so the framework runs (slower) where no C++ toolchain
exists. See src/fastpack.cpp for why these paths are native.

Measured vs the numpy fallback (this container, single core — thread
parallelism contributes nothing here, the win is contiguous row memcpy vs
numpy's take machinery): gather_rows on a [400, 28, 28, 1] f32 client
shard 0.34 ms vs 0.62 ms (1.8×); on [5000, 32, 32, 3] 12 ms vs 119 ms
(10×). Multi-core hosts widen this further via the row-range threading."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "src", "fastpack.cpp")
_BUILD_DIR = os.path.join(_DIR, "build")
_SO = os.path.join(_BUILD_DIR, "libfastpack.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                subprocess.run(
                    [
                        "g++", "-O3", "-march=native", "-shared", "-fPIC",
                        "-std=c++17", "-pthread", _SRC, "-o", _SO + ".tmp",
                    ],
                    check=True,
                    capture_output=True,
                )
                os.replace(_SO + ".tmp", _SO)
            lib = ctypes.CDLL(_SO)
            lib.fp_gather_rows.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_char_p,
            ]
            lib.fp_concat.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_char_p,
            ]
            lib.fp_version.restype = ctypes.c_int
            assert lib.fp_version() == 1
            _lib = lib
        except Exception:
            _build_failed = True
            _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def gather_rows(src: np.ndarray, order: np.ndarray, out: np.ndarray) -> None:
    """out[i] = src[order[i]] over the leading axis (rows must be
    contiguous). Falls back to numpy fancy indexing."""
    lib = _load()
    src = np.ascontiguousarray(src)
    if (
        lib is None
        or not out.flags["C_CONTIGUOUS"]
        or src.dtype != out.dtype
        or src.shape[1:] != out.shape[1:]
    ):
        out[...] = src[order]
        return
    order64 = np.ascontiguousarray(order, dtype=np.int64)
    # The native path is a raw memcpy per row: an out-of-range index would be
    # a silent OOB read, unlike numpy's IndexError. Validate first.
    if order64.size and (
        order64.min() < 0
        or order64.max() >= src.shape[0]
        or len(order64) > out.shape[0]
    ):
        out[...] = src[order]  # numpy raises the proper IndexError
        return
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.fp_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        order64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(order64),
        row_bytes,
        out.ctypes.data_as(ctypes.c_char_p),
    )


def concat_buffers(buffers: Sequence[bytes], header: bytes = b"") -> bytes:
    """header + b''.join(buffers), assembled in one pass (threaded when
    large). Falls back to bytes join."""
    lib = _load()
    if lib is None:
        return header + b"".join(buffers)
    lens = np.array([len(header)] + [len(b) for b in buffers], dtype=np.int64)
    offsets = np.zeros_like(lens)
    np.cumsum(lens[:-1], out=offsets[1:])
    total = int(lens.sum())
    out = ctypes.create_string_buffer(total)
    all_bufs = [header] + list(buffers)
    arr = (ctypes.c_char_p * len(all_bufs))(*all_bufs)
    lib.fp_concat(
        arr,
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(all_bufs),
        out,
    )
    return out.raw
