"""Pipeline parallelism (PP): GPipe-style microbatch pipeline over a ``pp``
mesh axis, written as one shard_map program.

Layout: S identical stages; the stacked stage parameters [S, ...] are
sharded P("pp") so each device holds exactly its stage. The schedule is a
``lax.scan`` over T = M + S − 1 ticks: each tick, stage 0 ingests the next
microbatch, every stage applies its layer to the activation it holds, and
activations rotate one step down the ring via ``lax.ppermute``. The last
stage emits a finished microbatch on ticks t ≥ S−1. No data-dependent
control flow — the bubble is masked arithmetic, so the whole pipeline jits
to a single XLA program and differentiates (ppermute's transpose is the
reverse permute; grads of per-stage params stay per-stage, no collective
needed).

The reference's closest concept is split learning (split_nn/client.py:24-34,
server.py:40-60: model cut across processes, activations/grads exchanged
per batch over MPI with turn-taking, no overlap). This module is its
TPU-native superset: the same model-cut idea, but S stages, M in-flight
microbatches, on-device exchange over ICI, and the compiler scheduling the
overlap.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P


def mlp_stage_init(rng, width: int, hidden: int):
    """One residual-MLP stage's params (the default stage used by tests and
    the dryrun; any (params, x)→x callable works)."""
    k1, k2 = jax.random.split(rng)
    s = jax.nn.initializers.lecun_normal()
    return {"w1": s(k1, (width, hidden)), "w2": s(k2, (hidden, width))}


def mlp_stage_apply(params, x):
    return x + jax.nn.gelu(x @ params["w1"]) @ params["w2"]


def stack_stage_params(rng, num_stages: int, width: int, hidden: int):
    """[S, ...]-stacked stage params — shard over P("pp") on the mesh."""
    rngs = jax.random.split(rng, num_stages)
    return jax.vmap(lambda r: mlp_stage_init(r, width, hidden))(rngs)


def sequential_apply(stacked_params, x, stage_apply=mlp_stage_apply):
    """Reference semantics: run the S stages in sequence on one device —
    the oracle the pipeline must match exactly."""

    def body(h, p):
        return stage_apply(p, h), None

    out, _ = jax.lax.scan(body, x, stacked_params)
    return out


def make_pipeline_fn(
    mesh: Mesh,
    pp_axis: str = "pp",
    stage_apply: Callable = mlp_stage_apply,
):
    """Build ``pipeline(stacked_params, microbatches) -> outputs``.

    ``stacked_params``: [S, ...] tree sharded P(pp_axis).
    ``microbatches``: [M, mb, width] (replicated; every device sees the
    stream, only stage 0 consumes it).
    Returns [M, mb, width] outputs (the last stage's results, psum-broadcast
    so every shard returns the full tensor).
    """
    S = mesh.shape[pp_axis]

    def shard_body(stacked_params, microbatches):
        # inside shard_map the local params block is [1, ...] — this device's
        # stage
        params = jax.tree_util.tree_map(lambda v: v[0], stacked_params)
        M = microbatches.shape[0]
        stage = jax.lax.axis_index(pp_axis)
        T = M + S - 1
        mb_shape = microbatches.shape[1:]

        def tick(carry, t):
            act, outs = carry
            # stage 0 ingests microbatch t (zeros once the stream is done)
            feed = microbatches[jnp.minimum(t, M - 1)] * (t < M)
            act = jnp.where(stage == 0, feed, act)
            act = stage_apply(params, act)
            # last stage emits microbatch t-(S-1) at tick t
            emit_idx = t - (S - 1)
            valid = jnp.logical_and(stage == S - 1, emit_idx >= 0)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(valid, act, outs[jnp.maximum(emit_idx, 0)]),
                jnp.maximum(emit_idx, 0),
                axis=0,
            )
            # rotate activations one stage down the ring
            act = jax.lax.ppermute(
                act, pp_axis, [(i, (i + 1) % S) for i in range(S)]
            )
            return (act, outs), None

        # the carry is device-varying (each stage holds a different
        # activation); mark the device-invariant zeros as varying so the
        # scan carry types line up
        act0 = jax.lax.pcast(
            jnp.zeros(mb_shape, microbatches.dtype), (pp_axis,), to="varying"
        )
        outs0 = jax.lax.pcast(
            jnp.zeros((M,) + mb_shape, microbatches.dtype),
            (pp_axis,),
            to="varying",
        )
        (_, outs), _ = jax.lax.scan(
            tick, (act0, outs0), jnp.arange(T)
        )
        # only the last stage holds real outputs; broadcast to all shards
        outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, pp_axis)

    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(pp_axis), P()),
        out_specs=P(),
    )


def make_pp_train_step(
    mesh: Mesh,
    width: int,
    hidden: int,
    lr: float = 1e-3,
    pp_axis: str = "pp",
    stage_apply: Callable = mlp_stage_apply,
    stage_init: Callable = None,
):
    """(init_fn, step_fn) for pipeline-parallel regression training.

    step_fn(params, opt_state, microbatches, targets) — microbatches
    [M, mb, width], targets same; loss = mean squared error over all
    microbatches, differentiated straight through the scanned ppermute
    pipeline.

    A custom ``stage_apply`` must come with the matching
    ``stage_init(rng) -> one stage's params`` (the default pair is the
    residual MLP stage above)."""
    if (stage_apply is not mlp_stage_apply) != (stage_init is not None):
        raise ValueError(
            "stage_apply and stage_init must be overridden together"
        )
    if stage_init is None:
        stage_init = lambda r: mlp_stage_init(r, width, hidden)  # noqa: E731
    pipeline = make_pipeline_fn(mesh, pp_axis, stage_apply)
    opt = optax.adam(lr)

    def step(params, opt_state, microbatches, targets):
        def loss_fn(p):
            preds = pipeline(p, microbatches)
            return jnp.mean(jnp.square(preds - targets))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_fn(rng):
        from jax.sharding import NamedSharding

        rngs = jax.random.split(rng, mesh.shape[pp_axis])
        params = jax.vmap(stage_init)(rngs)
        params = jax.device_put(params, NamedSharding(mesh, P(pp_axis)))
        return params, opt.init(params)

    return init_fn, jax.jit(step)  # fedlint: disable=uncached-jit -- bespoke pipeline-parallel step closed over mesh/stage plan; built once per benchmark run
