"""Mesh construction + client-batch padding.

Replaces the reference's host×GPU→process placement YAML
(fedml_api/distributed/utils/gpu_mapping.py:8-39, gpu_mapping.yaml): a
`jax.sharding.Mesh` over the local (or declared) devices with a named client
axis. Sampled clients per round are padded with zero-weight dummies to a
multiple of the mesh size so the per-shard client count is static."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from fedml_tpu.data.base import ClientBatch


def shardings_from_specs(mesh: Mesh, spec_tree):
    """PartitionSpec tree → NamedSharding tree on ``mesh`` (specs are
    themselves tuples, hence the explicit is_leaf)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def make_mesh(
    client_shards: Optional[int] = None,
    axis_name: str = "clients",
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh along the client axis. ``client_shards=None`` uses every
    visible device (the common case: one shard per chip)."""
    devs = list(devices if devices is not None else jax.devices())
    n = client_shards if client_shards is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"client_shards={n} > available devices {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis_name,))


def pad_client_batch(batch: ClientBatch, multiple: int) -> ClientBatch:
    """Pad the client axis with all-mask-zero dummy clients so C is divisible
    by the mesh size (ref FedAVGAggregator.py:66-71 semantics are preserved
    because dummies have aggregation weight 0 — see data/base.pad_clients_to,
    the one definition of the dummy-client contract)."""
    from fedml_tpu.data.base import _ceil_to, pad_clients_to

    return pad_clients_to(batch, _ceil_to(batch.num_clients, multiple))
